"""Two-tier edge aggregation: shard-local ordered sums, one partial per edge.

FetchSGD's central linearity (Count Sketches of partial sums add to the
sketch of the full sum) makes hierarchical aggregation EXACT: an edge
aggregator can sum its shard's validated r x c tables and forward ONE
partial to the root, cutting root-ingress bytes from W tables to E — and
the sum-only topology is precisely the shape FedSKETCH-style private
aggregation wants (the root only ever sees sums; see the ROADMAP item).

The parity discipline. "Exact" in real arithmetic is not "bitwise" in
float32 — a two-level sum is a different fp association than a flat one.
So the contract is pinned the way every prior subsystem pinned its mode
flags: arming `--serve_edges E` compiles the round's merge as the SAME
two-level fold on BOTH serving paths (engine.make_payload_round_steps
edge variants over `modes.edge_grouped_sum` / `modes.merge_edge_partials`
— explicit lax.scan folds, select-masked so no FMA can round differently),
and each `EdgeAggregator.partial` here executes exactly one lane of that
fold over its shard, in cohort-position order. Edge-tree serving is
therefore BITWISE equal to flat serving of the same edge-armed session
(params + every logged row, pinned in tests/test_scale.py); serve_edges=0
keeps the original program byte-for-byte and differs from any E >= 2 in
last bits (MIGRATION.md).

What crosses the tree per edge: the [r, c] partial, the shard's live
masks, and the per-client metadata the root's screens need — the
WIRE-FORMULA L2 norms (`table_norms_host`, float64 accumulation per
client, the exact formula the ingest gauntlet's screen uses — per-client
independent, so edge-computed and root-computed values are identical) and
the live count/weight sum for accounting. The root merge program consumes
the forwarded norms for the quarantine screen + median ring, so screening
can never diverge between the flat twin and the tree.

Robust merge policies (`--merge_policy trimmed|median`) need PER-CLIENT
tables — a pre-summed partial has destroyed exactly the per-client
structure the order statistics rank. The tree then runs in FORWARD mode:
edges validate and forward their shard's table stacks unsummed (the
bandwidth win is forfeited — that is the robustness-vs-fanin trade-off,
announced loudly at launch and documented in the README), and the root
dispatches the plain robust program. Privacy note: forward mode also
surrenders the sums-only topology; the per-tier compromise (robust merge
at the edge, masked sums at the root) is the ROADMAP's private-aggregation
item.

Edge death: a dead edge contributes a zero partial under zero masks —
bitwise the flat round with its shard's clients dropped — and the cohort
requeue machinery re-serves them (`edge_kill` fault kind, chaos `edge`
mode).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ...obs import registry as obreg
from ...obs import trace as obtrace
from .shard import shard_for


def assign_edges(client_ids, n_edges: int) -> np.ndarray:
    """[W] int32 edge assignment of a cohort — the same client-id hash the
    ingest shards route by (shard_for), so shard k's ingest worker IS edge
    k's aggregator: one ownership function, both tiers."""
    return np.asarray(shard_for(np.asarray(client_ids, np.int64), n_edges),
                      np.int32)


def table_norms_host(tables) -> np.ndarray:
    """[W] float32 sketch-space L2 norms, per client, float64 accumulation
    — the EXACT formula the ingest gauntlet's wire screen uses
    (serve/ingest._screen_table), applied per row. Per-client independent,
    so any partition of the stack computes identical values: this is what
    lets edges compute their shard's norms locally and the root screen
    against them as if it had computed them itself."""
    t = np.asarray(tables, np.float32)
    if t.shape[0] == 0:
        return np.zeros(0, np.float32)  # an edge can own zero invitees
    return np.sqrt(
        np.square(t, dtype=np.float64).reshape(t.shape[0], -1).sum(axis=1)
    ).astype(np.float32)


def screen_mask(norms, clip_multiple: float, median: float) -> np.ndarray:
    """[W] float32 1=kept / 0=quarantined — the HOST mirror of the merge
    program's `_quarantine_mask` over the same f32 norms and the same
    median scalar, with the multiply rounded in f32 exactly as the
    compiled program rounds it, so the edge's pre-fold mask and the root
    program's recomputed mask can never disagree on a boundary value."""
    norms = np.asarray(norms, np.float32)
    bad = ~np.isfinite(norms)
    if clip_multiple > 0 and median > 0:
        thresh = np.float32(clip_multiple) * np.float32(median)
        bad = bad | (norms > thresh)
    return (~bad).astype(np.float32)


@dataclasses.dataclass(frozen=True)
class EdgeReport:
    """What one edge forwards to the root for one round."""

    edge: int
    positions: np.ndarray        # cohort positions this edge owns (asc)
    partial: np.ndarray | None   # [r, c] summed table (None in forward mode)
    tables: np.ndarray | None    # [W_e, r, c] stack (forward mode only)
    norms: np.ndarray            # [W_e] wire-formula L2 norms
    live: np.ndarray             # [W_e] the masks the fold consumed
    live_count: int
    weight_sum: float


def _shard_fold(tables, live):
    """One edge's shard-local ordered sum: a sequential host left fold in
    cohort-position order with select-masking — exactly one lane of
    modes.edge_grouped_sum's in-program scan fold, bitwise: the lane's
    arithmetic is a fixed sequence of float32 elementwise ADDS (the select
    contributes exact zeros or the raw table — no multiply, so no FMA
    contraction anywhere), and IEEE float32 addition of the same values in
    the same order gives the same bits whether numpy or XLA executes it.
    Host numpy deliberately: shard sizes vary round to round with the
    cohort hash, and a jitted fold would recompile per (edge, W_e) shape —
    all compile, no win, for what is a handful of r*c-sized adds."""
    tables = np.asarray(tables, np.float32)
    live = np.asarray(live, np.float32)
    acc = np.zeros(tables.shape[1:], np.float32)
    zero = np.zeros_like(acc)
    for i in range(tables.shape[0]):
        # dead rows ADD an exact zero rather than being skipped: the
        # in-program lane performs that add too, and x + 0.0 flips a
        # -0.0 accumulator entry to +0.0 — skipping would diverge on
        # exactly that bit
        acc = acc + (tables[i] if live[i] > 0 else zero)
    return acc


class EdgeAggregator:
    """One edge: owns the cohort positions whose client ids hash to it,
    validates + ordered-sums their tables (or forwards them unsummed in
    robust/forward mode). The fold is a jitted lax.scan in cohort-position
    order with select-masking — one lane of the root's grouped fold,
    bitwise (see module doc)."""

    def __init__(self, edge: int, table_shape: tuple,
                 forward_tables: bool = False):
        self.edge = edge
        self.table_shape = tuple(table_shape)
        self.forward_tables = forward_tables
        self._fold = _shard_fold

    def aggregate(self, positions, tables, base_live,
                  screen: tuple | None = None) -> EdgeReport:
        """One round's shard-local work: wire-formula norms, the quarantine
        screen applied PRE-FOLD (the edge validates its own shard —
        `screen` is (clip_multiple, median), the round's baseline the root
        advertised; None = quarantine unarmed), then the masked ordered sum
        in cohort-position order — the lane arithmetic of
        modes.edge_grouped_sum — or the unsummed stack in forward mode."""
        positions = np.asarray(positions, np.int64)
        tables = np.asarray(tables, np.float32)
        live = np.asarray(base_live, np.float32)
        norms = table_norms_host(tables)
        if screen is not None:
            live = live * screen_mask(norms, screen[0], screen[1])
        if self.forward_tables:
            partial, stack = None, tables
        else:
            partial = np.asarray(self._fold(tables, live))
            stack = None
        return EdgeReport(
            edge=self.edge, positions=positions, partial=partial,
            tables=stack, norms=norms, live=live,
            live_count=int((live > 0).sum()), weight_sum=float(live.sum()))


class EdgeTree:
    """The round-scoped two-tier topology: partition a cohort over E edge
    aggregators by client-id hash, run each edge's shard-local validate +
    sum, and assemble the root's inputs — the [E, r, c] partial stack in
    FIXED edge order plus the forwarded per-client metadata ([W] norms,
    masks) the root merge program screens with.

    `forward_tables=True` (robust merge policies) forwards per-client
    stacks instead of partials; the root then reassembles the full
    [W, r, c] stack for the plain robust program.

    `kill(edge)` marks an edge dead for the CURRENT round (the edge_kill
    fault kind): its shard forwards nothing — a zero partial under zero
    masks — which is bitwise its clients never arriving; the serving layer
    zeroes their arrival mask so the requeue machinery re-serves them."""

    def __init__(self, n_edges: int, table_shape: tuple,
                 forward_tables: bool = False):
        if n_edges < 2:
            raise ValueError(
                f"n_edges must be >= 2, got {n_edges} (one edge IS the "
                "flat merge)")
        self.n_edges = n_edges
        self.table_shape = tuple(table_shape)
        self.forward_tables = forward_tables
        self.edges = [EdgeAggregator(e, table_shape, forward_tables)
                      for e in range(n_edges)]
        self._dead: set[int] = set()
        self.registry = obreg.default()

    def kill(self, edge: int) -> None:
        if not 0 <= edge < self.n_edges:
            raise ValueError(
                f"edge {edge} out of range [0, {self.n_edges})")
        self._dead.add(edge)
        self.registry.counter("serve_edge_deaths_total").inc()
        obtrace.instant("serve-edge", "edge:killed", edge=edge)

    def revive_all(self) -> None:
        self._dead.clear()

    @property
    def dead_edges(self) -> tuple:
        return tuple(sorted(self._dead))

    def dead_positions(self, ids) -> np.ndarray:
        """Cohort positions owned by currently-dead edges — the serving
        layer zeroes their arrival mask (edge death == shard dropped)."""
        assign = assign_edges(ids, self.n_edges)
        return np.flatnonzero(np.isin(assign, list(self._dead)))

    def aggregate_round(self, rnd: int, ids, tables, base_live,
                        screen: tuple | None = None):
        """Run the tier for one closed round. `tables` is the assembler's
        [W, r, c] validated stack, `base_live` the [W] pre-screen masks
        (part * arrived — already zeroed for dead edges' clients by the
        serving layer); each edge screens its own shard against `screen`
        ((clip_multiple, median) or None) before folding. Returns
        (reports, root_inputs) where root_inputs is the dict the session's
        edge dispatch takes: {"assign", "norms", "partials"} — partials
        None in forward mode (the root then uses the full stack it
        already holds)."""
        ids = np.asarray(ids, np.int64)
        tables = np.asarray(tables, np.float32)
        base_live = np.asarray(base_live, np.float32)
        assign = assign_edges(ids, self.n_edges)
        norms = np.zeros(len(ids), np.float32)
        partials = (None if self.forward_tables else
                    np.zeros((self.n_edges,) + self.table_shape, np.float32))
        reports = []
        for edge in self.edges:
            pos = np.flatnonzero(assign == edge.edge)
            if edge.edge in self._dead:
                # a dead edge forwards NOTHING: zero partial, zero masks —
                # its shard's norms never reach the root either (the
                # serving layer already zeroed these clients' arrival)
                reports.append(EdgeReport(
                    edge=edge.edge, positions=pos, partial=None, tables=None,
                    norms=np.zeros(len(pos), np.float32),
                    live=np.zeros(len(pos), np.float32),
                    live_count=0, weight_sum=0.0))
                continue
            rep = edge.aggregate(pos, tables[pos], base_live[pos], screen)
            reports.append(rep)
            norms[pos] = rep.norms
            if partials is not None and rep.partial is not None:
                partials[edge.edge] = rep.partial
        self.registry.counter("serve_edge_partials_total").inc(
            sum(1 for r in reports if r.partial is not None))
        if obtrace.get().enabled:
            obtrace.instant(
                "serve-edge", "edge:round", round=int(rnd),
                edges=self.n_edges, dead=len(self._dead),
                live=int(sum(r.live_count for r in reports)))
        return reports, {"assign": assign, "norms": norms,
                         "partials": partials}

    def counters(self) -> dict:
        """The /metrics JSON `edge` block."""
        return {
            "n_edges": self.n_edges,
            "mode": "forward" if self.forward_tables else "partial",
            "dead": list(self.dead_edges),
            "deaths": int(self.registry.counter(
                "serve_edge_deaths_total").value),
            "partials": int(self.registry.counter(
                "serve_edge_partials_total").value),
        }
