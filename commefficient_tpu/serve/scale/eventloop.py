"""Event-loop ingest transport: a selectors reactor for the C1M socket path.

The threaded transport (serve/transport.py) spends one OS thread per
connection — fine for the chaos tests it exists for, dead at heavy traffic
(128 threads is already a scheduler problem on a small box; 100k is not a
number threads have). This module is the scale path: ONE reactor thread
multiplexes every connection through `selectors.DefaultSelector` (epoll
where the OS has it), with

- **non-blocking accept**: the listener is registered with the selector;
  an accept burst drains in one wakeup, each accepted socket set
  non-blocking and registered for reads. A `max_conns` cap (fd-bounded,
  default 8192) refuses connections past it — counted, never queued.
- **incremental frame reassembly, zero-copy slicing**: each connection owns
  one append-only `bytearray` consumed by OFFSET — received chunks append,
  complete newline-frames are sliced out with `memoryview` views (no
  per-line buffer recompaction; the buffer compacts once per drain), and
  the payload inside a frame line crosses to the ingest gauntlet exactly
  as the threaded transport hands it: `validate_payload` stays THE G011
  deserialization boundary, reached through the same shared LineProtocol —
  same admission decisions, same chunk-sequence bounds, same MALFORMED
  verdicts, byte for byte.
- **read deadlines**: the selector wait is capped at the nearest
  per-connection deadline; a silent peer (slow-loris, died mid-frame) is
  reaped when its deadline lapses — counted on the same
  `serve_conn_deadline_total` counter the threaded transport uses.
- **max-frame caps + SHEDDING**: the newline-less byte-flood cutoff and the
  overload watermark run IN the shared protocol/queue code — the reactor
  adds no second policy.
- **write backpressure**: replies that would block park on the connection's
  out-buffer and flush when the socket turns writable, so one slow reader
  cannot stall the loop.

Blocking discipline (graftlint G015 blocking-call-in-event-loop): the
reactor's ONLY sanctioned waits are the selector poll and the non-blocking
socket I/O helpers, each declared `# graftlint: drain-point` — a
`time.sleep`, a blocking `recv`, file IO, or a subprocess reachable from
`_loop` anywhere else is a lint failure, because a blocked reactor is every
connection blocked at once.
"""

from __future__ import annotations

import json
import selectors
import socket
import sys
import threading
import time

from ...obs import registry as obreg
from ...obs import trace as obtrace
from ..ingest import IngestQueue
from ..transport import (
    DEFAULT_MAX_FRAME_BYTES,
    LineProtocol,
    submit_over_socket,
)

# fd-bounded concurrent-connection cap of one reactor: each connection is
# one fd + one small buffer, so thousands are cheap — the knob exists so a
# connection flood hits a counted refusal instead of the process fd limit
DEFAULT_MAX_CONNS_EVENTLOOP = 8192
# compact a connection's receive buffer once this many consumed bytes
# accumulate at its head (amortized O(1) per byte either way; this just
# bounds the dead prefix a long-lived chatty connection can pin)
_COMPACT_AT = 1 << 16


class _NoopMetric:
    """Inert counter/gauge stand-in for a standalone reactor's per-shard
    series (see _shard_counter)."""

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass


_NOOP_METRIC = _NoopMetric()


class _Conn:
    """Per-connection reactor state: the socket, the offset-consumed receive
    buffer, the pending out-buffer, the read deadline, and the in-flight
    chunk sequences (same dict shape the threaded handler keeps)."""

    __slots__ = ("sock", "buf", "off", "out", "deadline", "sequences",
                 "closing")

    def __init__(self, sock: socket.socket, deadline: float):
        self.sock = sock
        self.buf = bytearray()
        self.off = 0  # bytes of `buf` already consumed (frame starts here)
        self.out = bytearray()  # pending reply bytes (write backpressure)
        self.deadline = deadline
        self.sequences: dict = {}
        self.closing = False  # flush out-buffer, then close


class EventLoopTransport(LineProtocol):
    """Selectors-based single-threaded ingest reactor (see module doc)."""

    def __init__(self, queue: IngestQueue, host: str = "127.0.0.1",
                 port: int = 0, read_deadline_s: float = 30.0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 max_conns: int = DEFAULT_MAX_CONNS_EVENTLOOP,
                 shard_id: int | None = None, reuse_port: bool = False):
        if read_deadline_s <= 0:
            raise ValueError(
                f"read_deadline_s must be > 0, got {read_deadline_s} — an "
                "unreaped silent peer would hold its fd forever")
        if max_frame_bytes < 1024:
            raise ValueError(
                f"max_frame_bytes must be >= 1024, got {max_frame_bytes}")
        if max_conns < 1:
            raise ValueError(f"max_conns must be >= 1, got {max_conns}")
        self.queue = queue
        self.max_frame_bytes = max_frame_bytes
        self.max_conns = max_conns
        self.read_deadline_s = read_deadline_s
        # None = a standalone reactor; an int = this reactor is shard k of
        # a ShardedIngest — per-shard counters get distinct registry names
        self.shard_id = shard_id
        # SO_REUSEPORT bind: N worker-process reactors listen on the SAME
        # (host, port) and the kernel spreads accepted connections among
        # them by 4-tuple hash (serve/scale/procshard.py). The root
        # reserves the port with a never-listening socket first, so the
        # bind can never race an unrelated process.
        self.reuse_port = reuse_port
        self._host, self._port = host, port
        self._sock: socket.socket | None = None
        self._sel: selectors.BaseSelector | None = None
        self._thread: threading.Thread | None = None
        self._conns: dict[socket.socket, _Conn] = {}
        self._stop = threading.Event()
        # self-pipe: stop() (another thread) writes one byte to wake the
        # selector immediately instead of waiting out the poll timeout
        self._wake_r: socket.socket | None = None
        self._wake_w: socket.socket | None = None
        # batched-gauntlet deferral (--serve_fastpath): verdicts land on
        # gauntlet-worker threads and queue HERE for the reactor to flush
        # on its next self-pipe wake — the reactor itself never blocks on
        # a validation batch (G015)
        self._deferred: list[tuple[_Conn, str]] = []
        self._deferred_lock = threading.Lock()
        # the connection whose frames _consume_frames is dispatching right
        # now (reactor thread only) — what a deferred reply routes back to
        self._cur_conn: _Conn | None = None

    # -- lifecycle ------------------------------------------------------------

    @property
    def address(self) -> tuple[str, int] | None:
        return self._sock.getsockname() if self._sock is not None else None

    def addr_for(self, client_id: int) -> tuple[str, int] | None:
        return self.address

    @property
    def open_conns(self) -> int:
        return len(self._conns)

    def start(self) -> None:
        if self._sock is not None:
            return
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if self.reuse_port:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((self._host, self._port))
        s.listen(1024)
        s.setblocking(False)
        self._sock = s
        self._sel = selectors.DefaultSelector()
        self._sel.register(s, selectors.EVENT_READ, "accept")
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._stop.clear()
        name = ("serve-reactor" if self.shard_id is None
                else f"serve-reactor-{self.shard_id}")
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    def stop(self, join_deadline_s: float = 5.0) -> None:
        self._stop.set()
        if self._wake_w is not None:
            try:
                self._wake_w.send(b"x")
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=join_deadline_s)
            if self._thread.is_alive():
                print("serve: WARNING — reactor thread still alive past "
                      "the stop deadline", file=sys.stderr, flush=True)
            self._thread = None
        # the reactor thread closes everything on its way out; these are
        # the belt-and-braces for a thread that never ran / got wedged
        for sock in (self._wake_w, self._wake_r, self._sock):
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        self._wake_r = self._wake_w = None
        self._sock = None
        self._sel = None
        self._conns.clear()

    # graftlint: drain-point — client-side blocking round-trip (a test /
    # traffic thread's convenience, never the reactor's)
    def submit(self, sub) -> str:
        addr = self.address
        if addr is None:
            raise RuntimeError("EventLoopTransport not started")
        return submit_over_socket(addr, sub)

    # -- the reactor ----------------------------------------------------------

    def _loop(self) -> None:
        """THE event loop: one thread, every connection. Each iteration
        waits on the selector (bounded by the nearest read deadline),
        dispatches readable/writable sockets, then reaps expired
        connections. Nothing in here — or reachable from here — may block
        beyond the selector wait itself (graftlint G015)."""
        assert self._sel is not None
        while not self._stop.is_set():
            timeout = self._next_timeout()
            for key, events in self._select(timeout):
                if key.data == "wake":
                    self._drain_wake()
                elif key.data == "accept":
                    self._accept_burst()
                else:
                    conn: _Conn = key.data
                    if events & selectors.EVENT_WRITE:
                        self._on_writable(conn)
                    if events & selectors.EVENT_READ and not conn.closing:
                        self._on_readable(conn)
            self._reap_deadlines()
        # reactor exit: close every connection (partial chunk sequences
        # count MALFORMED — same contract as a threaded handler's death)
        for conn in list(self._conns.values()):
            self._close_conn(conn, count_sequences=True)
        for sock in (self._wake_r, self._wake_w, self._sock):
            if sock is not None:
                try:
                    self._sel.unregister(sock)
                except (KeyError, ValueError):
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
        self._sel.close()

    # graftlint: drain-point — the selector poll IS the reactor's one
    # sanctioned wait (bounded by the nearest read deadline)
    def _select(self, timeout: float):
        try:
            return self._sel.select(timeout)
        except OSError:
            return []

    def _next_timeout(self) -> float:
        if not self._conns:
            return 0.5
        now = time.monotonic()
        nearest = min(c.deadline for c in self._conns.values())
        return min(max(nearest - now, 0.0), 0.5)

    # graftlint: drain-point — non-blocking drain of the self-pipe
    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass
        self._flush_deferred()

    def _wake(self) -> None:
        """One byte down the self-pipe: wake the selector now (safe from
        any thread — the gauntlet's done-callbacks use it)."""
        w = self._wake_w
        if w is not None:
            try:
                w.send(b"x")
            except OSError:
                pass

    def _deferred_submit(self, sub) -> None:
        """The reactor's non-blocking fast-path defer (overrides the
        threaded transport's parked-Event version): hand the raw
        submission to the gauntlet pool with a callback that queues the
        verdict for the NEXT loop iteration, and return None — no reply
        yet. The reactor keeps serving every other connection while the
        batch validates (G015: a blocked reactor is every connection
        blocked at once)."""
        conn = self._cur_conn

        def deliver(status: str) -> None:
            with self._deferred_lock:
                self._deferred.append((conn, status))
            self._wake()

        self.gauntlet.submit(sub, deliver)
        return None

    def _flush_deferred(self) -> None:
        """Queue deferred verdicts — batched-gauntlet replies, and a
        worker-process reactor's forwarded-misroute replies (serve/scale/
        procshard_worker.py) — onto their connections' out-buffers
        (reactor thread only). A connection that died while its frame sat
        in a batch just drops the reply — the same contract as a threaded
        handler whose peer vanished mid-submit."""
        if not self._deferred:  # racy-but-benign emptiness peek: a miss
            return              # is re-checked on the next wake
        with self._deferred_lock:
            if not self._deferred:
                return
            items, self._deferred = self._deferred, []
        for conn, status in items:
            if self._conns.get(conn.sock) is conn and not conn.closing:
                self._queue_reply(conn, self._reply_for(status))

    # graftlint: drain-point — non-blocking accept burst on the listener
    def _accept_burst(self) -> None:
        while True:
            try:
                sock, _ = self._sock.accept()
            except (BlockingIOError, OSError):
                return
            if len(self._conns) >= self.max_conns:
                obreg.default().counter("serve_conn_refused_total").inc()
                self._shard_counter("conn_refused").inc()
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.setblocking(False)
            conn = _Conn(sock, time.monotonic() + self.read_deadline_s)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            self._shard_gauge("conns").set(len(self._conns))

    # graftlint: drain-point — non-blocking recv; a would-block falls
    # straight back to the selector
    def _on_readable(self, conn: _Conn) -> None:
        try:
            chunk = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn, count_sequences=True)
            return
        if not chunk:
            self._close_conn(conn, count_sequences=True)
            return
        conn.deadline = time.monotonic() + self.read_deadline_s
        conn.buf += chunk
        self._consume_frames(conn)

    def _consume_frames(self, conn: _Conn) -> None:
        """Incremental reassembly over the offset-consumed buffer: complete
        newline-frames are sliced out as memoryview-backed line bytes (one
        copy per line, for the json parse — the buffer itself is never
        recompacted per line) and dispatched through the shared
        LineProtocol; an unterminated tail past the frame cap is the
        byte-flood rejection."""
        buf = conn.buf
        view = memoryview(buf)
        self._cur_conn = conn  # deferred fast-path replies route back here
        while True:
            nl = buf.find(b"\n", conn.off)
            if nl < 0:
                break
            line = bytes(view[conn.off:nl])
            conn.off = nl + 1
            if not line.strip():
                continue
            reply = self._handle_line(line, conn.sequences, len(line))
            if reply is None:
                continue  # mid-sequence chunk: reply comes with the last
            self._queue_reply(conn, reply)
            if reply.get("detail") == "frame too large":
                view.release()
                self._close_conn(conn, count_sequences=True, flush=True)
                return
        pending = len(buf) - conn.off
        if pending > self.max_frame_bytes:
            # newline-less byte flood: cut it off at the cap — the same
            # verdict, counter, and disconnect the threaded transport gives
            obreg.default().counter("serve_rejected_malformed_total").inc()
            self.queue.note_wire_malformed()
            obtrace.instant("serve-ingest", "conn:frame_too_big",
                            bytes=pending)
            self._queue_reply(conn, {"status": "MALFORMED",
                                     "detail": "frame too large"})
            view.release()
            self._close_conn(conn, count_sequences=True, flush=True)
            return
        view.release()
        if conn.off >= _COMPACT_AT:
            del buf[:conn.off]
            conn.off = 0

    def _queue_reply(self, conn: _Conn, reply: dict) -> None:
        if self.shard_id is not None:
            self._shard_counter("submissions").inc()
            if reply.get("status") == "SHEDDING":
                # per-shard overload posture: the shard's own shed counter
                # and the load-scaled hint it handed out, so /metrics.prom
                # can tell an overloaded shard from an overloaded server
                reply = dict(reply)
                reply["retry_after_s"] = self._retry_after_s()
                self._shard_counter("shed").inc()
                self._shard_gauge("retry_after_s").set(
                    float(reply["retry_after_s"]))
        conn.out += json.dumps(reply).encode() + b"\n"
        self._flush_out(conn)

    # graftlint: drain-point — non-blocking send; unsent bytes park on the
    # out-buffer and the socket watches for writability
    def _flush_out(self, conn: _Conn) -> None:
        try:
            while conn.out:
                n = conn.sock.send(conn.out)
                del conn.out[:n]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close_conn(conn, count_sequences=True)
            return
        self._update_events(conn)
        if conn.closing and not conn.out:
            self._close_conn(conn)

    def _on_writable(self, conn: _Conn) -> None:
        self._flush_out(conn)

    def _update_events(self, conn: _Conn) -> None:
        if conn.sock not in self._conns:
            return
        events = selectors.EVENT_READ
        if conn.out:
            events |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, events, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _reap_deadlines(self) -> None:
        now = time.monotonic()
        for conn in [c for c in self._conns.values() if c.deadline <= now]:
            obreg.default().counter("serve_conn_deadline_total").inc()
            obtrace.instant("serve-ingest", "conn:deadline")
            self._close_conn(conn, count_sequences=True)

    def _close_conn(self, conn: _Conn, count_sequences: bool = False,
                    flush: bool = False) -> None:
        """Tear one connection down. `flush=True` keeps it alive just long
        enough to drain the pending reply (MALFORMED verdicts should reach
        the peer when the socket allows), then closes on the next
        writable/deadline tick."""
        if flush and conn.out:
            # the sequences are already abandoned at the DECISION to close:
            # count them now (the later drain-path close passes no flag,
            # and the threaded transport's finally block always counts)
            if count_sequences:
                self._abandoned_sequences(conn.sequences)
                conn.sequences = {}
            conn.closing = True
            self._update_events(conn)
            # the deadline still bounds a peer that never reads the reply
            return
        if count_sequences:
            self._abandoned_sequences(conn.sequences)
            conn.sequences = {}
        self._conns.pop(conn.sock, None)
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._shard_gauge("conns").set(len(self._conns))

    # -- per-shard metric names ----------------------------------------------
    # a STANDALONE reactor (shard_id None) publishes no serve_shard* series
    # at all: a phantom "shard 0" with connections but zero submissions
    # reads as a broken shard in a deployment that isn't sharded

    def _shard_counter(self, what: str):
        if self.shard_id is None:
            return _NOOP_METRIC
        return obreg.default().counter(
            f"serve_shard{self.shard_id}_{what}_total")

    def _shard_gauge(self, what: str):
        if self.shard_id is None:
            return _NOOP_METRIC
        return obreg.default().gauge(f"serve_shard{self.shard_id}_{what}")

    def _retry_after_s(self) -> float:
        """Per-shard load-scaled SHEDDING hint: the base hint stretched by
        how far this reactor's connection count sits above its fair share,
        so clients of a hot shard back off longer than clients of an idle
        one — the per-shard half of the overload contract (the queue-depth
        watermark itself is global)."""
        base = self.queue.shed_retry_after_s
        if self.shard_id is None:
            return base
        share = max(self.max_conns, 1)
        return base * (1.0 + min(len(self._conns) / share, 4.0))
