"""Multi-process closed-loop load harness for the scale-out serving path.

The thread-sharded C1M work (PR 15) measured the ingest with in-process
callers; the process-sharded promotion (PR 18) needs the thing it
actually claims — submissions/s through REAL sockets at six-figure
connection counts — measured from OUTSIDE the server's processes. This
module is that harness: M client PROCESSES (spawn context; this module is
on their import chain and stays numpy/stdlib-only, graftlint G017), each
running one selectors reactor over its share of persistent connections to
the service's shared SO_REUSEPORT port, ramping the fleet from 2048
toward 100k connections in doubling stages.

Each connection is CLOSED-LOOP: submit one announce-style line, wait for
the verdict, think, submit again — offered load tracks service rate
instead of overrunning it, so a stage's submissions/s is a real capacity
number, not a buffer-depth artifact. The think time is modulated by the
diurnal/bursty traffic model the serve tier is benched against
(serve/traffic.py's shapes, re-expressed as a rate multiplier over wall
time): "flat" holds the base think, "diurnal" sweeps a day-curve sinusoid
across each stage, "bursty" alternates quiet baseline with duty-cycle
bursts of near-zero think.

Six-figure fan-out mechanics, all counted and reported per stage:

- every worker binds its OWN loopback source IP (127.0.1.<wid+1>) before
  connecting, so each gets the full ephemeral-port range instead of the
  fleet sharing one (host, port) 4-tuple space (~28k ports);
- every worker caps its connection share at its RLIMIT_NOFILE soft limit
  minus headroom, and REPORTS the cap — when a ramp stage falls short of
  its target, the result names the fd/rlimit ceiling that was actually
  hit instead of silently shrinking (the bench logs it);
- verdict counts (ACCEPTED / DUPLICATE / SHEDDING / rejections) come back
  per worker over the control pipe and aggregate per stage, so an
  admission-refusing server is visible as such, not as throughput.
"""

from __future__ import annotations

import dataclasses
import json
import multiprocessing
import math
import resource
import selectors
import socket
import sys
import time

_FD_HEADROOM = 128  # fds a worker keeps free for pipes/stdio/selector


@dataclasses.dataclass(frozen=True)
class LoadGenConfig:
    """One ramp run against a serving address (see module docstring)."""

    host: str = "127.0.0.1"
    port: int = 0
    connections: int = 2048    # ramp TARGET (stages double toward it)
    processes: int = 4         # client worker processes
    stage_s: float = 5.0       # measured wall time per ramp stage
    model: str = "diurnal"     # flat | diurnal | bursty
    think_s: float = 0.05      # closed-loop base think time per conn
    period_s: float = 4.0      # diurnal period / burst cycle length
    burst_duty: float = 0.25   # bursty: fraction of each cycle in-burst
    round_hint: int = 0        # round number stamped on submissions
    client_base: int = 1 << 20  # id space floor (clear of real cohorts)
    ramp_start: int = 2048     # first stage's connection count
    source_ips: bool = True    # per-worker loopback source IPs
    connect_timeout_s: float = 10.0


def _rate_mult(model: str, t: float, period_s: float,
               burst_duty: float) -> float:
    """Offered-rate multiplier at wall time t (think = think_s / mult)."""
    if model == "diurnal":
        # the day curve swept across the stage: trough 0.1x, peak 1.0x
        return 0.55 + 0.45 * math.sin(2.0 * math.pi * t / period_s)
    if model == "bursty":
        return 4.0 if (t % period_s) < burst_duty * period_s else 0.4
    return 1.0


class _Conn:
    __slots__ = ("sock", "out", "buf", "next_t", "cid", "state")

    def __init__(self, sock, cid: int):
        self.sock = sock
        self.out = b""
        self.buf = b""
        self.next_t = 0.0
        self.cid = cid
        self.state = "connecting"


def _loadgen_worker(cfg: dict, wid: int, share: int, ctl) -> None:
    """One client process: `share` closed-loop connections on a selectors
    reactor for stage_s seconds, results over the control pipe. Spawn
    target — keep the module chain numpy/stdlib-only (G017)."""
    soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    cap = max(int(soft) - _FD_HEADROOM, 16)
    n = min(share, cap)
    src_ip = f"127.0.1.{(wid % 250) + 1}" if cfg["source_ips"] else None
    addr = (cfg["host"], cfg["port"])
    sel = selectors.DefaultSelector()
    conns: list[_Conn] = []
    statuses: dict[str, int] = {}
    errors = 0
    submissions = 0

    def _line(cid: int) -> bytes:
        return (json.dumps({"client_id": cid,
                            "round": int(cfg["round_hint"]),
                            "latency_s": 0.0}) + "\n").encode()

    t0 = time.monotonic()
    deadline = t0 + float(cfg["stage_s"])
    connect_deadline = t0 + float(cfg["connect_timeout_s"])
    opened = 0
    for i in range(n):
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setblocking(False)
            if src_ip is not None:
                try:
                    s.bind((src_ip, 0))
                except OSError:
                    pass  # exotic loopback config: fall back to default
            try:
                s.connect(addr)
            except BlockingIOError:
                pass
            cid = int(cfg["client_base"]) + wid * cap + i
            c = _Conn(s, cid)
            sel.register(s, selectors.EVENT_WRITE, c)
            conns.append(c)
            opened += 1
        except OSError:
            errors += 1
            break  # fd/port exhaustion: report how far we got
    while time.monotonic() < deadline:
        now = time.monotonic()
        events = sel.select(timeout=0.01)
        for key, mask in events:
            c: _Conn = key.data
            try:
                if c.state == "connecting" and (mask
                                                & selectors.EVENT_WRITE):
                    err = c.sock.getsockopt(socket.SOL_SOCKET,
                                            socket.SO_ERROR)
                    if err:
                        raise OSError(err, "connect failed")
                    if now > connect_deadline:
                        raise OSError("connect deadline")
                    c.state = "sending"
                    c.out = _line(c.cid)
                if c.state == "sending" and (mask & selectors.EVENT_WRITE):
                    sent = c.sock.send(c.out)
                    c.out = c.out[sent:]
                    if not c.out:
                        c.state = "reading"
                        sel.modify(c.sock, selectors.EVENT_READ, c)
                elif c.state == "reading" and (mask & selectors.EVENT_READ):
                    data = c.sock.recv(4096)
                    if not data:
                        raise OSError("server closed connection")
                    c.buf += data
                    if b"\n" in c.buf:
                        line, _, c.buf = c.buf.partition(b"\n")
                        st = json.loads(line).get("status", "?")
                        statuses[st] = statuses.get(st, 0) + 1
                        submissions += 1
                        # closed loop: think (model-modulated), resubmit
                        mult = _rate_mult(cfg["model"], now - t0,
                                          cfg["period_s"],
                                          cfg["burst_duty"])
                        c.next_t = now + float(cfg["think_s"]) / max(
                            mult, 1e-3)
                        c.state = "thinking"
                        sel.unregister(c.sock)
            except (OSError, ValueError):
                errors += 1
                try:
                    sel.unregister(c.sock)
                except (KeyError, ValueError):
                    pass
                try:
                    c.sock.close()
                except OSError:
                    pass
                c.state = "dead"
        # wake thinkers whose timers expired (scan is O(conns); at 12.5k
        # conns per worker and 100 wakes/s this is the cheap part next to
        # the syscalls)
        for c in conns:
            if c.state == "thinking" and now >= c.next_t:
                c.state = "sending"
                c.out = _line(c.cid)
                sel.register(c.sock, selectors.EVENT_WRITE, c)
    for c in conns:
        try:
            c.sock.close()
        except OSError:
            pass
    ctl.send({
        "wid": wid, "share": share, "opened": opened,
        "fd_cap": cap, "fd_capped": share > cap,
        "submissions": submissions, "statuses": statuses,
        "errors": errors,
    })
    ctl.close()
    sys.exit(0)


def run_stage(cfg: LoadGenConfig, conns: int) -> dict:
    """One ramp stage: `conns` connections across cfg.processes worker
    processes, measured for cfg.stage_s. Returns the aggregated stage
    record (achieved conns, submissions/s, verdict mix, fd ceiling)."""
    ctx = multiprocessing.get_context("spawn")
    per = max(conns // cfg.processes, 1)
    shares = [per] * cfg.processes
    shares[-1] += conns - per * cfg.processes
    workers = []
    wire = dataclasses.asdict(cfg)
    for wid, share in enumerate(shares):
        parent, child = ctx.Pipe()
        p = ctx.Process(target=_loadgen_worker,
                        args=(wire, wid, share, child),
                        name=f"loadgen-{wid}", daemon=True)
        p.start()
        child.close()
        workers.append((p, parent))
    t0 = time.monotonic()
    results = []
    for p, parent in workers:
        try:
            if parent.poll(cfg.stage_s + cfg.connect_timeout_s + 30.0):
                results.append(parent.recv())
        except (EOFError, OSError):
            pass
        p.join(5.0)
        if p.is_alive():
            p.kill()
            p.join(1.0)
        try:
            parent.close()
        except OSError:
            pass
    wall = time.monotonic() - t0
    total_sub = sum(r["submissions"] for r in results)
    statuses: dict[str, int] = {}
    for r in results:
        for k, v in r["statuses"].items():
            statuses[k] = statuses.get(k, 0) + v
    return {
        "target_conns": conns,
        "opened_conns": sum(r["opened"] for r in results),
        "processes": len(results),
        "submissions": total_sub,
        "submissions_per_s": round(total_sub / max(cfg.stage_s, 1e-9), 1),
        "wall_s": round(wall, 3),
        "statuses": statuses,
        "errors": sum(r["errors"] for r in results),
        "fd_cap_per_proc": min((r["fd_cap"] for r in results), default=0),
        "fd_capped": any(r["fd_capped"] for r in results),
    }


def run_ramp(cfg: LoadGenConfig, log=print) -> dict:
    """The full ramp: doubling stages from cfg.ramp_start toward
    cfg.connections, stopping early (and saying why) when the fd/rlimit
    ceiling or socket errors cap the achievable fleet. Returns
    {"stages": [...], "peak_submissions_per_s": ..., "ceiling": ...}."""
    stages = []
    target = max(int(cfg.connections), 1)
    c = min(max(int(cfg.ramp_start), 1), target)
    plan = []
    while True:
        plan.append(c)
        if c >= target:
            break
        c = min(c * 2, target)
    ceiling = None
    for conns in plan:
        stage = run_stage(cfg, conns)
        stages.append(stage)
        log(f"loadgen: stage {conns} conns -> opened "
            f"{stage['opened_conns']}, {stage['submissions_per_s']}/s, "
            f"errors {stage['errors']}"
            + (f", fd-capped at {stage['fd_cap_per_proc']}/proc"
               if stage["fd_capped"] else ""))
        if stage["fd_capped"] or stage["opened_conns"] < conns * 0.9:
            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            ceiling = {
                "at_target_conns": conns,
                "opened_conns": stage["opened_conns"],
                "rlimit_nofile": [int(soft), int(hard)],
                "why": ("per-process RLIMIT_NOFILE"
                        if stage["fd_capped"] else
                        "connect failures (port/fd exhaustion or "
                        "server accept ceiling)"),
            }
            log(f"loadgen: ramp CEILING at {conns} target conns — "
                f"{ceiling['why']} (rlimit_nofile={soft}/{hard})")
            break
    return {
        "stages": stages,
        "peak_submissions_per_s": max(
            (s["submissions_per_s"] for s in stages), default=0.0),
        "max_opened_conns": max(
            (s["opened_conns"] for s in stages), default=0),
        "ceiling": ceiling,
    }
