"""Sharded ingest: N event-loop reactors over one admission queue.

One reactor thread saturates around one core of frame parsing + payload
gauntlet work (base64 + crc32 + ndarray checks are CPU-bound). The sharded
ingest runs N reactors (`EventLoopTransport`, each its own listener socket
and thread) in front of the SAME thread-safe `IngestQueue`, so admission
state — windows, dedup, capacity, the shed watermark — stays exactly one
source of truth while connection handling and decode CPU spread across
workers.

Routing is by client-id hash: `shard_for(client_id, n)` (splitmix64 — the
same deterministic mixer the client-state streams use, so the assignment
is uniform and stable across runs) names the shard a client connects to,
and `addr_for` hands the serving layer / client helpers the right address.
A submission that lands on the WRONG shard is still decided correctly (the
queue is shared — correctness never depends on routing), but it is counted
per shard as misrouted: in a real deployment that is a load-balancer bug
an operator needs to see.

Per-shard observability (the /metrics + /metrics.prom surfaces):

- `serve_shard<k>_submissions_total` / `serve_shard<k>_shed_total` /
  `serve_shard<k>_conn_refused_total` / `serve_shard<k>_misrouted_total`
  counters,
- `serve_shard<k>_conns` gauge (live connections),
- `serve_shard<k>_retry_after_s` gauge — the load-scaled SHEDDING hint the
  shard last handed out, stretched by its connection count over its fair
  share, so an overloaded SHARD is distinguishable from an overloaded
  SERVER at a glance.
"""

from __future__ import annotations

import numpy as np

from ..clients import fold_in_host
from ..ingest import IngestQueue
from ..transport import DEFAULT_MAX_FRAME_BYTES, submit_over_socket
from .eventloop import DEFAULT_MAX_CONNS_EVENTLOOP, EventLoopTransport


# the routing stream's fixed seed: shard ownership is a property of the
# DEPLOYMENT topology, not of a run's --seed — resuming a run (or changing
# its seed) must not reshuffle which shard owns a client
_ROUTE_SEED = 0x5CA1E


def shard_for(client_id, n_shards: int):
    """The shard (and edge, serve/scale/edge.py) a client id hashes to —
    one splitmix64 fold of the bare id (serve/clients.py `fold_in_host`,
    the same deterministic mixer the client-state streams use): uniform,
    stable across runs, vectorized over an id array. The same function
    routes ingest connections and partitions cohorts over edge
    aggregators, so the two tiers agree about ownership by
    construction."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    out = fold_in_host(_ROUTE_SEED, np.asarray(client_id)) % np.uint64(
        n_shards)
    return out.astype(np.int64) if out.ndim else int(out)


class ShardedIngest:
    """N event-loop reactors fronting one IngestQueue (see module doc).
    Presents the same transport surface the service expects: start/stop,
    submit(sub), address (shard 0 — the "primary" a single-address caller
    sees), addr_for(client_id) for hash routing."""

    def __init__(self, queue: IngestQueue, n_shards: int,
                 host: str = "127.0.0.1", port: int = 0,
                 read_deadline_s: float = 30.0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 max_conns: int = DEFAULT_MAX_CONNS_EVENTLOOP):
        if n_shards < 2:
            raise ValueError(
                f"n_shards must be >= 2, got {n_shards} (one shard IS the "
                "plain event-loop transport — use EventLoopTransport)")
        self.queue = queue
        self.n_shards = n_shards
        # an explicit base port pins shard k to port+k (operators can
        # firewall/monitor per shard); port=0 lets the OS pick each
        self.shards = [
            _ShardReactor(queue, shard_id=k, n_shards=n_shards, host=host,
                          port=(port + k if port else 0),
                          read_deadline_s=read_deadline_s,
                          max_frame_bytes=max_frame_bytes,
                          max_conns=max_conns)
            for k in range(n_shards)
        ]

    def start(self) -> None:
        for s in self.shards:
            s.start()

    def stop(self, join_deadline_s: float = 5.0) -> None:
        for s in self.shards:
            s.stop(join_deadline_s=join_deadline_s)

    @property
    def address(self) -> tuple[str, int] | None:
        return self.shards[0].address

    @property
    def addresses(self) -> list[tuple[str, int] | None]:
        return [s.address for s in self.shards]

    def addr_for(self, client_id: int) -> tuple[str, int] | None:
        return self.shards[shard_for(client_id, self.n_shards)].address

    # graftlint: drain-point — client-side blocking round-trip on the
    # caller's thread (traffic generator / tests), hash-routed
    def submit(self, sub) -> str:
        addr = self.addr_for(sub.client_id)
        if addr is None:
            raise RuntimeError("ShardedIngest not started")
        return submit_over_socket(addr, sub)

    def counters(self) -> dict:
        """Per-shard snapshot for the /metrics JSON `shards` block."""
        from ...obs import registry as obreg

        reg = obreg.default()
        out = {}
        for s in self.shards:
            k = s.shard_id
            out[str(k)] = {
                "addr": (f"{s.address[0]}:{s.address[1]}"
                         if s.address else None),
                "conns": int(reg.gauge(f"serve_shard{k}_conns").value),
                "submissions": int(reg.counter(
                    f"serve_shard{k}_submissions_total").value),
                "shed": int(reg.counter(
                    f"serve_shard{k}_shed_total").value),
                "misrouted": int(reg.counter(
                    f"serve_shard{k}_misrouted_total").value),
                "conn_refused": int(reg.counter(
                    f"serve_shard{k}_conn_refused_total").value),
                "retry_after_s": float(reg.gauge(
                    f"serve_shard{k}_retry_after_s").value),
            }
        return out


class _ShardReactor(EventLoopTransport):
    """One shard's reactor: the event-loop transport plus ownership
    accounting — a submission whose client id hashes elsewhere is decided
    normally (the queue is shared) but counted misrouted."""

    def __init__(self, queue: IngestQueue, shard_id: int, n_shards: int,
                 **kw):
        super().__init__(queue, shard_id=shard_id, **kw)
        self.n_shards = n_shards

    def _submit_reply(self, sub) -> dict:
        if shard_for(sub.client_id, self.n_shards) != self.shard_id:
            self._shard_counter("misrouted").inc()
        return super()._submit_reply(sub)
