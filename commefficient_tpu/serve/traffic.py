"""Trace-driven traffic generator: diurnal load, bursts, device classes.

Two faces over one deterministic core:

- **Round-driven** (`respond_to_invites`): given a round's invite list,
  derive each invitee's submission latency from its device class
  (serve/clients.py — a pure function of (seed, client_id, round), so the
  trace is replayable and O(1) per participant) and push the submissions
  through a transport. This is what drives the serving loop and the chaos
  smoke.
- **Open-world** (`arrival_events`): a Poisson arrival stream over the whole
  population — rate follows a diurnal sinusoid with superimposed bursts —
  used by the ingest bench and as background "unsolicited push" load
  against the admission control (uninvited submissions must bounce, not
  wedge the round). Window-batched: memory is O(arrivals per window), never
  O(population).

Everything is virtual-time: latencies and event times are numbers handed to
the assembler's virtual close, not slept-through wall clock — a 10M-ID
diurnal day replays in milliseconds, and tests stay deterministic.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from . import clients as cl


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Traffic shape. Parsed from a CLI-friendly 'k=v,k=v' spec."""

    population: int = 10_000      # client-ID universe for open-world arrivals
    base_rate: float = 100.0      # mean arrivals/s at the diurnal midline
    diurnal_amplitude: float = 0.6  # 0..1: peak/trough swing around the mean
    diurnal_period_s: float = 86_400.0
    burst_rate: float = 0.0       # expected bursts per second (Poisson)
    burst_size: int = 50          # arrivals per burst (all in one instant)
    seed: int = 0

    @classmethod
    def parse(cls, spec: str) -> "TraceConfig":
        """'population=10000000,base_rate=200,burst_rate=0.1' -> TraceConfig.
        Unknown keys are rejected loudly (a typoed knob must not silently
        run the default trace)."""
        if not spec:
            return cls()
        kw: dict = {}
        fields = {f.name: f.type for f in dataclasses.fields(cls)}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, eq, val = part.partition("=")
            key = key.strip()
            if not eq or key not in fields:
                raise ValueError(
                    f"--serve_trace: unknown key {key!r} "
                    f"(valid: {', '.join(sorted(fields))})")
            caster = int if fields[key] == "int" or fields[key] is int else float
            try:
                kw[key] = caster(val.strip())
            except ValueError as e:
                raise ValueError(
                    f"--serve_trace: bad value for {key}: {val!r}") from e
        return cls(**kw)


class TrafficGenerator:
    """Deterministic traffic over a TraceConfig (see module docstring)."""

    def __init__(self, cfg: TraceConfig, classes=cl.DEFAULT_CLASSES):
        if cfg.population < 1:
            raise ValueError(f"population must be >= 1, got {cfg.population}")
        self.cfg = cfg
        self.classes = classes

    # -- diurnal rate ---------------------------------------------------------

    def rate_at(self, t_s: float) -> float:
        """Instantaneous arrival rate (events/s): diurnal sinusoid with the
        trough at t=0 (midnight) and peak half a period later."""
        c = self.cfg
        phase = 2.0 * math.pi * (t_s / c.diurnal_period_s)
        return max(c.base_rate * (1.0 - c.diurnal_amplitude * math.cos(phase)),
                   0.0)

    # -- open-world arrival stream -------------------------------------------

    def arrival_events(self, t0_s: float, duration_s: float,
                       window_s: float = 1.0):
        """Yield (t_s, client_ids ndarray) per window in [t0, t0+duration):
        Poisson(rate(t) * window) baseline arrivals plus Poisson bursts,
        client ids drawn uniformly from the population. Per-window
        RandomState pinned to (seed, window index): replaying any window is
        independent of how much of the trace was consumed before it."""
        c = self.cfg
        n_windows = max(int(math.ceil(duration_s / window_s)), 0)
        for w in range(n_windows):
            t = t0_s + w * window_s
            rs = np.random.RandomState(
                int(cl.fold_in_host(c.seed, int(t0_s / max(window_s, 1e-9))
                                    + w, 0xA11) % (2**32)))
            n = rs.poisson(self.rate_at(t) * window_s)
            n += rs.poisson(c.burst_rate * window_s) * c.burst_size
            if n <= 0:
                continue
            ids = rs.randint(0, c.population, size=int(n)).astype(np.int64)
            yield t, ids

    # -- round-driven responses ----------------------------------------------

    def invite_latencies(self, rnd: int, invited_ids) -> np.ndarray:
        """[N] submission latencies for the invitees (np.inf = no-show),
        from each client's device class — ONE vectorized derivation, no
        per-client state."""
        return cl.response_latency_s(
            self.cfg.seed, np.asarray(invited_ids, np.int64), rnd,
            self.classes)

    def respond_to_invites(self, rnd: int, invited_ids, submit,
                           deadline_s: float, payloads=None, wire=None,
                           abort=None) -> int:
        """Simulate the invited cohort answering round `rnd`: every invitee
        whose derived latency is finite AND within `deadline_s` submits
        (latency-order, so wall-clock transports see a realistic arrival
        sequence). Returns the number of submissions pushed. `submit` is
        transport.submit — rejections (dup/late/full) are the transport's
        business, counted by the ingest queue.

        Payload rounds (--serve_payload sketch): `payloads` is the
        per-invitee sequence of wire payloads ([r, c] ndarrays — the socket
        helper frames them; inproc ships the array), and `wire` an optional
        FaultPlan.wire_plan dict applying damage AT THIS SEAM — between the
        client's compute and the server's ingest, the hop the validation
        gauntlet exists for:

        - corrupt/truncate damage the FRAME (the array is encoded first so
          the damage hits real wire bytes, whatever the transport);
        - dup re-sends the identical submission (at-least-once double send —
          the server's duplicate detection must keep the merge single-count);
        - delay_s adds to the submission latency (the straggler discipline
          decides whether it still makes the close);
        - drop kills the send: through `abort` (a mid-send connection death,
          socket realism) when given, else the submission just never leaves
          the client — either way the server sees a no-show;
        - withhold suppresses the send entirely (no abort, no wire bytes):
          the client deliberately sits the round out — the first half of
          the client_stale_poison attack, whose second half the serving
          layer submits into the stale band next round."""
        from ..resilience.faults import FaultPlan
        from ..sketch.payload import encode_frame
        from .ingest import Submission

        lat = self.invite_latencies(rnd, invited_ids)
        wire = wire or {}
        if wire:
            lat = np.array(lat, copy=True)
            for p, actions in wire.items():
                if actions.get("delay_s"):
                    lat[p] += actions["delay_s"]
        order = np.argsort(lat, kind="stable")
        sent = 0
        for i in order:
            if not np.isfinite(lat[i]) or lat[i] > deadline_s:
                break  # sorted: everything after is slower
            payload = payloads[i] if payloads is not None else None
            actions = wire.get(int(i), {})
            sub = Submission(client_id=int(invited_ids[i]), round=rnd,
                             latency_s=float(lat[i]), payload=payload)
            if actions.get("withhold"):
                continue  # deliberate silence: not even an aborted send
            if actions.get("drop"):
                if abort is not None:
                    abort(sub)  # the connection dies mid-send
                continue
            if actions.get("corrupt") or actions.get("truncate"):
                frame = (payload if isinstance(payload, dict)
                         else encode_frame(payload))
                if actions.get("corrupt"):
                    frame = FaultPlan.corrupt_frame(frame)
                if actions.get("truncate"):
                    frame = FaultPlan.truncate_frame(frame)
                sub = Submission(client_id=int(invited_ids[i]), round=rnd,
                                 latency_s=float(lat[i]), payload=frame)
            submit(sub)
            if actions.get("dup"):
                submit(sub)  # identical at-least-once re-send
            sent += 1
        return sent
