"""Pinned host table ring: the zero-copy landing zone of the ingest fast path.

The wire-payload round used to pay two host copies per accepted table: the
gauntlet decoded each frame into a fresh per-submission ndarray, and the
assembler's close stacked those ndarrays into the [N, r, c] block the merge
uploads. FetchSGD's whole point is that the sketch IS the unit of work
(arXiv:2007.07682 §1) — the table's bytes are final the moment the gauntlet
validates them, so the fast path (--serve_fastpath) writes them ONCE,
directly into a preallocated host ring block sized by the cohort:

- `TableRing` owns a small pool of reusable blocks (one per concurrently
  open round window, at most `max_open_rounds`); `open_block` zero-fills
  and hands one out at invite time, `release` returns it after the round's
  device stack is built.
- `RingBlock` is one round's landing zone: a [capacity, r, c] float32
  buffer plus per-slot (position, valid, final) state. Decoders `acquire`
  a slot, the gauntlet writes the decoded table into it (`RingSlot.write`
  — THE one sanctioned per-table copy of the fast path, declared
  `# graftlint: ring-write` for G016), and the admission outcome either
  `commit`s the slot (cohort position recorded, valid) or `reject`s it
  (zeroed back — a rejected payload stays bitwise a client that never
  submitted). Slots are never reused within a round, so a finalized slot's
  bytes are immutable: the H2D uploader (serve/service.py) can ship the
  contiguous finalized prefix while the window is still open.
- Overflow is a fallback, never a correctness cliff: when every slot is
  taken (a client retrying after a rejection, a burst past the cohort
  size), `acquire` returns None, the decode falls back to a standalone
  ndarray, and the admission path registers it via `add_extra` — the
  close's scatter picks extras up individually. Counted on
  `serve_ring_overflow_total`.

The ring is a LAYOUT change, not an order change: the device stack built
from ring slots + validity mask is bitwise the host stack the assembler
used to collect (tests pin fastpath == slowpath on every transport).
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs import registry as obreg


class RingSlot:
    """One acquired slot of a RingBlock: where exactly one submission's
    decoded table lands. The gauntlet holds it from decode to verdict;
    `write` is the fast path's single per-table copy."""

    __slots__ = ("block", "index")

    def __init__(self, block: "RingBlock", index: int):
        self.block = block
        self.index = index

    @property
    def view(self) -> np.ndarray:
        return self.block.tables[self.index]

    # graftlint: ring-write — THE sanctioned per-table copy of the fast
    # path (G016): the validated wire table lands in the pinned ring once
    def write(self, arr) -> np.ndarray:
        """Copy a decoded [r, c] table into this slot (the assignment
        casts the wire dtype to float32 bit-exactly) and return the slot
        VIEW — downstream holds the view, never a fresh ndarray."""
        self.block.tables[self.index][...] = arr
        return self.block.tables[self.index]


class RingBlock:
    """One round's pinned landing zone (see module docstring). Thread-safe:
    decoders acquire/commit/reject from transport or gauntlet-worker
    threads; the uploader polls `final_prefix`; the close waits on
    `wait_final`. The block lock is a LEAF lock — ingest's queue lock may
    be held while taking it, never the reverse."""

    def __init__(self, rows: int, cols: int, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.rows, self.cols = int(rows), int(cols)
        self.capacity = int(capacity)
        self.tables = np.zeros((capacity, rows, cols), np.float32)
        # cohort position of each committed slot (-1 = not committed)
        self.positions = np.full(capacity, -1, np.int32)
        # slot holds a validated, ADMITTED table (commit); False = rejected
        # or still in flight
        self.valid = np.zeros(capacity, bool)
        # slot content is immutable from here on (commit OR reject): the
        # uploader only ever ships finalized slots
        self._final = np.zeros(capacity, bool)
        self.rnd = -1
        self.count = 0  # slots acquired (monotone; frozen once the round closes)
        self.extras: list[tuple[int, np.ndarray]] = []
        self._watermark = 0  # cached contiguous finalized prefix
        self._cv = threading.Condition()

    def reset(self, rnd: int) -> None:
        """Re-arm a pooled block for a new round: zero the buffer (the
        exact +0.0 every untouched slot must read as) and clear the state."""
        with self._cv:
            self.tables[...] = 0.0
            self.positions[...] = -1
            self.valid[...] = False
            self._final[...] = False
            self.rnd = int(rnd)
            self.count = 0
            self.extras = []
            self._watermark = 0

    def acquire(self) -> RingSlot | None:
        """Claim the next free slot (None when the block is full — the
        caller falls back to a standalone table + `add_extra`, counted)."""
        with self._cv:
            if self.count >= self.capacity:
                obreg.default().counter("serve_ring_overflow_total").inc()
                return None
            i = self.count
            self.count += 1
            return RingSlot(self, i)

    def commit(self, slot: RingSlot, position: int) -> None:
        """Finalize an ADMITTED slot at its cohort position — from here
        its bytes are immutable and the uploader may ship them."""
        with self._cv:
            self.positions[slot.index] = int(position)
            self.valid[slot.index] = True
            self._final[slot.index] = True
            self._cv.notify_all()

    def reject(self, slot: RingSlot) -> None:
        """Finalize a REJECTED (or stale-detached) slot: zero it back so a
        rejected payload stays bitwise a client that never submitted."""
        with self._cv:
            self.tables[slot.index][...] = 0.0
            self.valid[slot.index] = False
            self._final[slot.index] = True
            self._cv.notify_all()

    def add_extra(self, position: int, table: np.ndarray) -> None:
        """Register an admitted table the ring had no slot for (overflow
        fallback) so the close's scatter still sees it."""
        with self._cv:
            self.extras.append((int(position), table))

    def final_prefix(self) -> int:
        """Length of the contiguous finalized prefix — the slots the
        overlap uploader may ship right now (their bytes can no longer
        change)."""
        with self._cv:
            w = self._watermark
            while w < self.count and self._final[w]:
                w += 1
            self._watermark = w
            return w

    def wait_final(self, timeout_s: float) -> bool:
        """Block until every ACQUIRED slot is finalized (the close barrier:
        acquires stop when the round's window closes, so this is a bounded
        wait on in-flight decodes). False on timeout."""
        with self._cv:
            return self._cv.wait_for(
                lambda: bool(self._final[: self.count].all()),
                timeout=timeout_s)

    def snapshot(self) -> tuple[int, np.ndarray, np.ndarray, list]:
        """(count, positions, valid, extras) copied under the lock — what
        the close's scatter consumes after wait_final."""
        with self._cv:
            return (self.count, self.positions.copy(), self.valid.copy(),
                    list(self.extras))


class TableRing:
    """The pool of reusable RingBlocks (see module docstring). `depth`
    bounds how many released blocks are retained per capacity — the
    pipelined serving mode keeps at most `max_open_rounds` (2) blocks
    live, so the default never allocates past warm-up."""

    def __init__(self, rows: int, cols: int, depth: int = 4):
        self.rows, self.cols = int(rows), int(cols)
        self.depth = int(depth)
        self._lock = threading.Lock()
        self._pool: list[RingBlock] = []

    def open_block(self, rnd: int, capacity: int) -> RingBlock:
        """A zeroed block sized for the round's cohort — pooled when a
        released block of the same capacity is available, freshly
        allocated otherwise (capacity only changes if the cohort size
        does, which a session never does mid-run)."""
        with self._lock:
            for i, blk in enumerate(self._pool):
                if blk.capacity == int(capacity):
                    block = self._pool.pop(i)
                    break
            else:
                block = RingBlock(self.rows, self.cols, int(capacity))
        block.reset(rnd)
        return block

    def release(self, block: RingBlock) -> None:
        """Return a block once its round's device stack is built (nothing
        downstream holds ring views past that point: stale admissions and
        straggler stashes copy out, the device stack owns its own bytes)."""
        with self._lock:
            if len(self._pool) < self.depth:
                self._pool.append(block)
