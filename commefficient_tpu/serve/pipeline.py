"""The always-on serving pipeline: round preparation runs AHEAD of the merge.

The serial served loop leaves the server idle between a round's commit and
the next dispatch: `ServedSource.next()` runs the whole
invite -> collect -> close -> prep cycle inline on the dispatch thread, so
the device waits out every virtual close, every socket deadline, every
batch assembly. At millions of clients that dead time — not compute —
bounds sustained merged-submissions/s (the ROADMAP's always-on item).

`RoundPipeline` closes the gap: ONE worker thread runs the identical
serve-cycle call sequence the serial source runs — `service.serve_round(s)`
for s = start, start+1, ... — and parks the finished
(PreparedRound, ClosedRound) pairs in a bounded hand-off buffer (depth 2 =
double buffering: one round buffered, one in flight on the worker). The
runner's `next()` pops a ready round instead of computing one, so the
commit-to-dispatch gap collapses to a queue pop (`server_idle_ms` ≈ 0 in
the bench `serve` section), and round r+1's ingest/close overlaps round
r's merge on the device — the double-buffered assembler/merge pipeline,
visible as overlapping `serve-pipeline` vs `runner`/`device` spans in a
--trace capture.

Why it stays bit-identical to the serial source (pinned in
tests/test_pipeline_serve.py):

- **Same producer order.** The worker is the ONE thread calling
  sample_cohort / prepare_served_round / finish_served_payload, strictly in
  round order — exactly the single-producer discipline RoundPrefetcher
  established for the host RNG and re-queue streams. Nothing about the
  draws, the requeue, or the fault sites changes; only WHEN they run does.
- **The dispatch gate.** A payload round's client program reads the newest
  DISPATCHED server state (the head-state chain). The worker therefore
  blocks before round s's table compute until the runner reports round
  s-1's merge dispatched (`on_dispatched`, wired through run_loop) — the
  same state the serial source would have read, never an earlier one.
  Announce rounds read no server state at preparation and skip the gate.
- **Committed-snapshot discipline.** The worker records each pending-buffer
  round boundary right after its serve_round (the sequence point the
  serial source recorded it at), and `stop()` JOINS the worker before the
  runner's exit rewind — prepared-but-never-committed rounds unwind
  through the existing RNG/requeue/pending rewinds, so a resumed or reused
  session replays bit-identically.

The worker's blocking points (the hand-off buffer when the runner lags,
the dispatch gate, the close waits inside serve_round) are waits on
bounded conditions, declared drain-points where they live; the dispatch
thread itself only ever blocks popping a READY round.
"""

from __future__ import annotations

import collections
import threading

from ..obs import trace as obtrace


class RoundPipeline:
    """See module docstring. `depth` counts rounds the worker may run ahead
    of the consumer: 1 buffered + 1 in flight = the default double
    buffering (a deeper pipeline buys nothing — the merge consumes rounds
    one at a time — and widens the preemption rewind)."""

    def __init__(self, service, start_round: int, depth: int = 2):
        if depth < 2:
            raise ValueError(f"pipeline depth must be >= 2, got {depth}")
        self.service = service
        self._cv = threading.Condition()
        self._buf: collections.deque = collections.deque()
        self._buffered = max(depth - 1, 1)  # beyond the one in flight
        self._start = start_round
        # newest round the runner has DISPATCHED (the gate's watermark);
        # start-1 = nothing yet, round `start` computes against the
        # committed state like the serial source would
        self._dispatched = start_round - 1
        self._stop = False
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, name="serve-pipeline", daemon=True)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RoundPipeline":
        # the gate hooks the service's payload compute (a no-op attribute
        # on announce paths — prepare reads no server state there)
        self.service._compute_gate = self._gate
        self._thread.start()
        return self

    def stop(self) -> None:
        """Halt the worker and JOIN it — callers rely on the join: the
        runner's exit rewind (host RNG, requeue, pending buffer) must not
        race a worker mid-preparation. The worker's longest legitimate
        park is a wall-clock close (the queue wait's own timeout bounds
        it), so the join budget scales with the service deadline; a worker
        that somehow outlives it is announced loudly — and its residual
        effects are bounded anyway: every hand-off/boundary mutation
        re-checks the stop flag first, and the caller's
        rewind_to_committed prunes anything an orphaned round left."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        deadline = 30.0 + float(getattr(self.service.cfg, "deadline_s", 0.0))
        self._thread.join(timeout=deadline)
        if self._thread.is_alive():
            import sys

            print("serve: WARNING — pipeline worker still alive past the "
                  f"{deadline:.0f}s stop deadline", file=sys.stderr,
                  flush=True)
        self.service._compute_gate = None

    # -- runner side ----------------------------------------------------------

    # graftlint: drain-point — the dispatch thread's sanctioned wait: pops
    # a READY round (the pipeline's whole point is that this never waits
    # out an invite window)
    def next(self):
        """The next (PreparedRound, ClosedRound) in round order; blocks only
        when the worker has genuinely not finished the round yet. Re-raises
        a worker error at the consuming point, like the prefetcher."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._buf or self._err is not None or self._stop)
            if self._buf:
                item = self._buf.popleft()
                self._cv.notify_all()
                return item
            if self._err is not None:
                raise self._err
            raise RuntimeError("RoundPipeline stopped while a consumer "
                               "was waiting for the next round")

    def on_dispatched(self, rnd: int) -> None:
        """run_loop hook: round `rnd`'s merge has been dispatched — the
        worker may now compute round rnd+1's client tables against the
        head state that dispatch chained."""
        with self._cv:
            if rnd > self._dispatched:
                self._dispatched = rnd
                self._cv.notify_all()

    # -- worker side ----------------------------------------------------------

    # graftlint: drain-point — the WORKER thread's gate: payload table
    # compute for round s waits for merge s-1's dispatch by design (the
    # head-state chain is the bit-parity contract); never the dispatch
    # thread
    def _gate(self, rnd: int) -> None:
        with self._cv:
            self._cv.wait_for(
                lambda: self._dispatched >= rnd - 1 or self._stop)

    def _run(self) -> None:
        s = self._start
        try:
            while True:
                with self._cv:
                    self._cv.wait_for(
                        lambda: len(self._buf) < self._buffered
                        or self._stop)
                    if self._stop:
                        return
                with obtrace.span("serve-pipeline", "serve_round", round=s):
                    prep, closed = self.service.serve_round(s)
                with self._cv:
                    if self._stop:
                        # stopped mid-round: deliver NOTHING and touch no
                        # more shared state — the caller's rewind owns the
                        # cleanup from here
                        return
                # the pending-buffer boundary snapshot lands at the same
                # SEQUENCE point the serial source records it (right after
                # round s's open drained the buffer) — wall-clock moved,
                # the committed-snapshot discipline didn't
                self.service._record_boundary(s + 1)
                with self._cv:
                    if self._stop:
                        return
                    self._buf.append((prep, closed))
                    self._cv.notify_all()
                s += 1
        except BaseException as e:  # noqa: BLE001 — parked for the consumer
            with self._cv:
                self._err = e
                self._cv.notify_all()
