"""Cohort assembler: over-provisioned rounds that close at W-of-N.

A round INVITES the full cohort the session sampled (N = num_workers — the
over-provisioning) and CLOSES when W invitees have arrived (the quorum) or
the deadline passes, whichever is first. Everyone in the invite list who
missed the close — stragglers (arrived after the W-th arrival or past the
deadline) and no-shows (never arrived) — is masked out of the round and
re-queued through the session's `_requeue` fairness machinery, so a short
cohort is bit-identical to the batch-simulator round over its survivors
(the PR 4 `_valid` masking parity, now fed by a real arrival stream).

Two close disciplines:

- **virtual** (default, in-process transport): arrivals carry simulated
  latencies; the close is a pure function of the submission set — sort by
  (latency, client_id), the W-th latency is the close time, everything at
  or under min(close, deadline) is in. Deterministic, wall-clock-free.
- **wall** (socket transport): block on the ingest queue's condition for
  quorum-or-timeout; arrival ORDER (recv_order) decides the cut. Realistic,
  used by the socket demo path.

Both close forms take the ROUND they close (the ingest queue holds up to
two concurrently-open windows since the pipelined serving mode landed), so
a close of round r never disturbs round r+1's still-collecting window.

Buffered-async mode (--serve_async) reuses the same machinery with the
quorum reinterpreted as the BUFFER-SIZE trigger (`trigger_label="buffer"`
relabels the close counters) and `collect_stragglers=True`: a payload
round's stragglers — validated tables that arrived but missed the cut —
are carried on the ClosedRound so the serving layer can fold them into a
LATER merge with a staleness weight instead of discarding the work.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..obs import registry as obreg
from ..obs import trace as obtrace
from .ingest import IngestQueue


@dataclasses.dataclass(frozen=True)
class ClosedRound:
    """One closed round: the invite list, who made the cut, and the close
    bookkeeping the metrics endpoint and bench read."""

    rnd: int
    invited: np.ndarray         # [N] int64 cohort (session.sample_cohort)
    arrived: np.ndarray         # [N] float32 0/1 — made the W-of-N close
    latencies: np.ndarray       # [N] float64 submission latency (inf = none)
    closed_by: str              # "quorum" | "deadline"
    close_latency_s: float      # virtual close time (W-th arrival latency)
    stragglers: int             # submitted, but after the close
    no_shows: int               # never submitted
    # [N] float64 host ACCEPT timestamps (perf_counter; inf = never
    # accepted) aligned with `invited` — the obs layer turns these into
    # submission-to-merge spans when the round's merge commits
    wall_ts: np.ndarray | None = None
    # wire-payload rounds only: [N, r, c] float32 validated client tables
    # aligned with `invited` — a zero row everywhere a payload missed the
    # merge (no-show, straggler, rejected frame), so a rejected payload is
    # BITWISE a dropped client before the merge even sees it. None on the
    # announce path.
    tables: np.ndarray | None = None
    # buffered-async mode only (collect_stragglers=True): the validated
    # tables of invitees who ARRIVED but missed the close cut, as
    # (cohort_position, client_id, table) in cohort-position order — the
    # deterministic fold order of the staleness-weighted merge they join
    # one-or-more rounds later. () on sync paths.
    straggler_tables: tuple = ()

    @property
    def survivors(self) -> int:
        return int(self.arrived.sum())


class CohortAssembler:
    def __init__(self, queue: IngestQueue, quorum: int, deadline_s: float,
                 payload_shape: tuple | None = None,
                 trigger_label: str = "quorum",
                 collect_stragglers: bool = False,
                 ring_mode: bool = False):
        if quorum < 1:
            raise ValueError(f"quorum must be >= 1, got {quorum}")
        self.queue = queue
        self.quorum = quorum
        self.deadline_s = deadline_s
        # (r, c) of the wire-payload tables; None = announce path (closed
        # rounds carry no table stack)
        self.payload_shape = payload_shape
        # --serve_fastpath: accepted tables already live in the round's
        # pinned ring block, so the close skips the [N, r, c] stack copy —
        # the serving layer builds the device stack from the ring instead
        # (ClosedRound.tables is None; straggler stashes COPY out of the
        # ring, because a ring view must never outlive its round's block)
        self.ring_mode = ring_mode
        # what a count-triggered close is CALLED: "quorum" (W-of-N sync
        # close) or "buffer" (the async buffer-size trigger) — same cut
        # arithmetic, different operational meaning in the counters
        self.trigger_label = trigger_label
        self.collect_stragglers = collect_stragglers
        # cumulative close counters (metrics endpoint)
        self.rounds_closed = 0
        self.closed_by_quorum = 0
        self.closed_by_deadline = 0
        self.stragglers_total = 0
        self.no_shows_total = 0

    def close_virtual(self, rnd: int, invited) -> ClosedRound:
        """Close on simulated latencies (see module docstring). The queue's
        accepted arrivals are ranked by (latency, client_id); the quorum-th
        latency — capped at the deadline — is the close."""
        arrivals = self.queue.close_round(rnd)
        invited = np.asarray(invited, np.int64)
        pos = {int(c): i for i, c in enumerate(invited)}
        lat = np.full(len(invited), np.inf)
        walls = np.full(len(invited), np.inf)
        for a in arrivals:
            if int(a.client_id) in pos:  # uninvited never got accepted, but
                lat[pos[int(a.client_id)]] = a.latency_s  # stay defensive
                walls[pos[int(a.client_id)]] = a.wall_t
        order = np.lexsort((invited, lat))  # latency, then cid tie-break
        in_time = lat[order] <= self.deadline_s
        n_in_time = int(in_time.sum())
        if n_in_time >= self.quorum:
            close = float(lat[order][self.quorum - 1])
            closed_by = self.trigger_label
        else:
            close = self.deadline_s
            closed_by = "deadline"
        arrived = (lat <= close).astype(np.float32)
        return self._finish(rnd, invited, arrived, lat, closed_by, close,
                            walls, self._collect_tables(pos, arrivals,
                                                        arrived, len(invited)),
                            self._collect_stragglers(pos, arrivals, arrived))

    def close_wall(self, rnd: int, invited) -> ClosedRound:
        """Close on real arrival order: wait for quorum-or-deadline on the
        queue, then cut at the quorum-th ARRIVAL (recv order). Latencies in
        the result are the submitted ones (accounting only).

        The cut is decided on the SNAPSHOT wait_for returned — the admission
        state at the instant the wait was satisfied. Under concurrent socket
        connections more submissions can be ADMITTED between that instant
        and close_round() draining the queue; those are recv-order
        stragglers (they arrived after the wall-clock cut) and must not ride
        in just because they beat the drain — deciding on the drained list
        would also let a deadline-expired wait flip to closed_by="quorum"
        when late arrivals pile in during the gap."""
        cut = self.queue.wait_for(self.quorum, self.deadline_s, rnd=rnd)
        arrivals = self.queue.close_round(rnd)
        invited = np.asarray(invited, np.int64)
        pos = {int(c): i for i, c in enumerate(invited)}
        lat = np.full(len(invited), np.inf)
        walls = np.full(len(invited), np.inf)
        arrived = np.zeros(len(invited), np.float32)
        made_cut = sorted(cut, key=lambda a: a.recv_order)[:self.quorum]
        for a in arrivals:
            if int(a.client_id) in pos:
                lat[pos[int(a.client_id)]] = a.latency_s
                walls[pos[int(a.client_id)]] = a.wall_t
        for a in made_cut:
            if int(a.client_id) in pos:
                arrived[pos[int(a.client_id)]] = 1.0
        closed_by = (self.trigger_label if len(cut) >= self.quorum
                     else "deadline")
        close = (max((a.latency_s for a in made_cut), default=self.deadline_s)
                 if closed_by != "deadline" else self.deadline_s)
        return self._finish(rnd, invited, arrived, lat, closed_by, close,
                            walls, self._collect_tables(pos, arrivals,
                                                        arrived, len(invited)),
                            self._collect_stragglers(pos, arrivals, arrived))

    def _collect_tables(self, pos, arrivals, arrived,
                        n: int) -> np.ndarray | None:
        """[N, r, c] validated-table stack for a payload round: each
        invitee's table where its submission both PASSED the gauntlet and
        made the close, an exact-zero row everywhere else (no-show,
        straggler, rejected frame) — so downstream a rejected payload is
        bitwise a dropped client. None on the announce path."""
        if self.payload_shape is None or self.ring_mode:
            return None
        out = np.zeros((n,) + tuple(self.payload_shape), np.float32)
        copied = 0
        for a in arrivals:
            p = pos.get(int(a.client_id))
            if p is not None and arrived[p] == 1.0 and a.table is not None:
                out[p] = a.table
                copied += 1
        if copied:
            # the slow path's second per-table host copy (the first was the
            # decode) — what bytes_touched_per_table in the bench measures
            obreg.default().counter("serve_table_bytes_copied_total").inc(
                copied * int(np.prod(self.payload_shape)) * 4)
        return out

    def _collect_stragglers(self, pos, arrivals, arrived) -> tuple:
        """Validated tables of invitees who arrived but missed the cut, as
        (position, client_id, table) in cohort-position order — the
        buffered-async mode's stale-fold candidates (their compute is not
        discarded, it folds into a later merge staleness-weighted). ()
        unless collect_stragglers."""
        if not self.collect_stragglers or self.payload_shape is None:
            return ()
        out = []
        for a in arrivals:
            p = pos.get(int(a.client_id))
            if p is not None and arrived[p] == 0.0 and a.table is not None:
                # ring mode: detach from the ring (the block is released
                # when the round's device stack is built, but a straggler
                # stash outlives the round by design)
                table = (np.array(a.table, np.float32) if self.ring_mode
                         else a.table)
                out.append((int(p), int(a.client_id), table))
        return tuple(sorted(out, key=lambda e: e[0]))

    def _finish(self, rnd, invited, arrived, lat, closed_by,
                close, walls=None, tables=None,
                straggler_tables: tuple = ()) -> ClosedRound:
        submitted = np.isfinite(lat)
        stragglers = int((submitted & (arrived == 0.0)).sum())
        no_shows = int((~submitted).sum())
        self.rounds_closed += 1
        if closed_by != "deadline":
            self.closed_by_quorum += 1
        else:
            self.closed_by_deadline += 1
        self.stragglers_total += stragglers
        self.no_shows_total += no_shows
        obtrace.instant(
            "assembler", f"close:{closed_by}", round=int(rnd),
            survivors=int(arrived.sum()), stragglers=stragglers,
            no_shows=no_shows)
        return ClosedRound(
            rnd=rnd, invited=invited, arrived=arrived, latencies=lat,
            closed_by=closed_by, close_latency_s=float(close),
            stragglers=stragglers, no_shows=no_shows, wall_ts=walls,
            tables=tables, straggler_tables=straggler_tables,
        )

    def counters(self) -> dict[str, int]:
        return {
            "rounds_closed": self.rounds_closed,
            "closed_by_quorum": self.closed_by_quorum,
            "closed_by_deadline": self.closed_by_deadline,
            "stragglers": self.stragglers_total,
            "no_shows": self.no_shows_total,
        }
