"""Ingest layer: admission control for client sketch submissions.

The paper's deployment story (FetchSGD §1) is millions of clients *pushing*
updates at an always-on aggregator; the linearity of the Count Sketch makes
the server-side merge of asynchronously-arriving updates cheap. This module
is the front door of that inversion: a bounded, thread-safe arrival queue
with explicit admission decisions — every submission is either ACCEPTED into
an open round or rejected with a reason the transport echoes back to the
client (`QUEUE_FULL` is the backpressure signal a well-behaved client backs
off on).

Since the always-on pipeline landed the queue holds PER-ROUND WINDOWS (up
to `max_open_rounds` concurrently open — the pipelined serving mode keeps
round r+1's invite window open while round r merges), each with its own
invite list, arrival list, dedup set, and — payload rounds — its own
quarantine-median snapshot taken when the window opened, so an early
payload push for round r+1 validates against round r+1's state, never
round r's.

Admission rules, in check order:

- ``CLOSED``       — the service is shutting down (or no round ever opened).
- ``SHEDDING``     — load shedding: the queue is past its pressure watermark
  and the submission is turned away BEFORE any expensive work (with a
  retry-after hint on the socket wire), so overload degrades gracefully
  instead of queuing unboundedly. One O(1) probe runs first: a retry of an
  already-ADMITTED submission still hears DUPLICATE (== success) so an
  at-least-once client never burns its retry budget on a submission the
  merge will count.
- ``QUEUE_FULL``   — the bounded queue is at capacity: backpressure.
- ``OUT_OF_ROUND`` — the submission names a round with no open window.
  Late (already-closed round) is rejected — unless the queue runs in the
  buffered-ASYNC band (`stale_rounds > 0`), where a payload submission for
  a recently-closed round is admitted ``ACCEPTED_STALE`` into the stale
  buffer (validated against ITS round's retained median snapshot) and
  folds into a later merge with a staleness weight. EARLY (the round after
  the newest window ever opened — open or mid-merge) is buffered in the
  bounded pending queue and admitted when that round opens — a pushing
  client does not resubmit just because the server is mid-merge. With a
  payload policy armed, early pushes beyond any OPEN window are rejected
  instead of buffered: a sketch payload is a function of its round's
  params, so a table for a round whose window never opened cannot exist
  yet. (A push for an OPEN round r+1 while r is still merging is not
  "early" at all — it routes to r+1's window and validates against r+1's
  median snapshot. That is the pipelined-invite path.)
- ``NOT_INVITED``  — the client is not in the target round's cohort.
- ``DUPLICATE``    — the client already has an accepted submission for that
  round (an at-least-once transport may retry; the merge must not double
  count a client).

With a payload policy armed (the wire-payload round, ``--serve_payload
sketch``), an otherwise-admissible submission then runs the VALIDATION
GAUNTLET (`validate_payload` — the one sanctioned deserialization boundary,
graftlint G011) before anything can reach compiled scope; its docstring has
the exact first-failure-wins check order (structural MALFORMED, then
STALE_SCHEMA, then layout MALFORMED, then QUARANTINED):

- ``STALE_SCHEMA`` — the frame names a wire schema version this server does
  not speak (refuse rather than guess at layout).
- ``MALFORMED``    — missing payload, undecodable base64, dtype/shape
  mismatch against the server's OWN sketch spec, length-prefix (nbytes)
  mismatch, a checksum failure (one flipped bit anywhere rejects), or a
  broken CHUNK SEQUENCE: a table too big for one frame crosses the wire as
  length-prefixed continuation frames (sketch/payload.py), and the
  reassembly happens HERE, inside the same boundary — a partial, reordered,
  or duplicated sequence is MALFORMED, never a guess.
- ``QUARANTINED``  — the decoded table is non-finite, or its sketch-space
  L2 norm exceeds the quarantine multiple of the running median (the PR 4
  screen, applied at the wire): a poisoned payload is dropped BEFORE the
  merge, bitwise equal to that client never submitting.

The gauntlet screens what a TABLE can reveal — structure, schema,
magnitude. An in-screen Byzantine payload (a sign-flipped table, a
colluding clone at median norm) is norm-invariant and sails through BY
DESIGN; the defense against those is downstream, in the merge itself
(``--merge_policy trimmed|median`` — see the README threat model). The
gauntlet's scalar median snapshot is the same table-space ring the merge
advances, so a payload rejected QUARANTINED here is bitwise the payload
the merge would have quarantined (pinned in tests/test_byzantine.py).

With ``--serve_fastpath`` armed the gauntlet runs BATCHED: the socket
transports hand raw, unparsed frames to a small worker pool
(serve/gauntlet.py) that pushes whole blocks through ``submit_block`` —
decoded tables land directly in the round's pinned ring slots
(serve/ring.py, one write, no per-submission ndarray) and the finite/L2
screen vectorizes over the stacked block (``screen_block``). Decisions
stay per-submission, individually attributed, and bitwise identical to
the inline path; `validate_payload` remains the single G011 boundary.

All counters are cumulative over the service lifetime and feed the metrics
endpoint (serve/metrics.py); the wire-facing rejections additionally bump
process-wide resilience counters in the obs registry.
"""

from __future__ import annotations

import base64
import binascii
import dataclasses
import sys
import threading
import time
import zlib
from typing import Any, Callable

import numpy as np

from ..obs import registry as obreg
from ..obs import trace as obtrace
from ..sketch.payload import MAX_CHUNKS, SCHEMA_VERSION, WIRE_DTYPE

# rejection reasons (wire-visible: the socket transport echoes them)
ACCEPTED = "ACCEPTED"
CLOSED = "CLOSED"
QUEUE_FULL = "QUEUE_FULL"
OUT_OF_ROUND = "OUT_OF_ROUND"
NOT_INVITED = "NOT_INVITED"
DUPLICATE = "DUPLICATE"
BUFFERED = "BUFFERED"  # early submission parked for the next round
# buffered-async mode: a late payload for a recently-closed round, admitted
# into the stale buffer for a staleness-weighted fold (FedBuff-shaped)
ACCEPTED_STALE = "ACCEPTED_STALE"
# wire-payload gauntlet + overload decisions (see module docstring)
MALFORMED = "MALFORMED"
STALE_SCHEMA = "STALE_SCHEMA"
QUARANTINED = "QUARANTINED"
SHEDDING = "SHEDDING"

# obs-registry resilience counters per wire-facing rejection class: the
# chaos acceptance reads these (every rejection = a decision + an obs
# instant + a counter)
_REJECTION_COUNTERS = {
    MALFORMED: "serve_rejected_malformed_total",
    STALE_SCHEMA: "serve_rejected_stale_schema_total",
    QUARANTINED: "serve_rejected_quarantined_total",
    SHEDDING: "serve_shed_total",
    ACCEPTED_STALE: "serve_stale_admitted_total",
}

# EVERY admission decision also mirrors into a serve_admission_* registry
# counter: the round ledger (obs/ledger.py) records per-round deltas of
# these, so a committed round's record carries its admission picture
# without the ledger reaching into queue internals. Precomputed name map —
# the admission path is hot (~1e5 submissions/s in the ingest bench) and
# must not pay an f-string per call.
_ADMISSION_COUNTERS = {s: f"serve_admission_{s.lower()}_total" for s in (
    ACCEPTED, CLOSED, QUEUE_FULL, OUT_OF_ROUND, NOT_INVITED, DUPLICATE,
    BUFFERED, ACCEPTED_STALE, MALFORMED, STALE_SCHEMA, QUARANTINED,
    SHEDDING)}


@dataclasses.dataclass(frozen=True)
class Submission:
    """One client push. `latency_s` is the client's submission delay relative
    to the round's invite (simulated by the traffic generator; a real client
    would stamp send time) — the assembler's VIRTUAL clock orders arrivals
    by it, so a served round is a pure function of the submission set.
    `payload_bytes` sizes the (simulated) sketch blob for wire accounting.
    `payload` is the wire payload of a sketch-carrying submission
    (--serve_payload sketch): a raw [r, c] float32 ndarray on the in-process
    transport, a frame dict (sketch/payload.py encode_frame) — or a LIST of
    continuation frames for a chunked table — off the socket wire; None on
    the announce path."""

    client_id: int
    round: int
    latency_s: float = 0.0
    payload_bytes: int = 0
    payload: Any = None


@dataclasses.dataclass(frozen=True)
class Arrival:
    """An accepted submission, as the assembler sees it."""

    client_id: int
    latency_s: float
    recv_order: int  # wall arrival order (tie-break + socket-mode ordering)
    # host wall timestamp (perf_counter) of the ACCEPT: the start of the
    # submission-to-merge latency the obs layer resolves at commit
    wall_t: float = 0.0
    # the VALIDATED [r, c] table of a payload-carrying submission (already
    # through the gauntlet — the only route wire bytes take to the merge)
    table: Any = None


@dataclasses.dataclass(frozen=True)
class StaleArrival:
    """A late-but-admitted payload submission (buffered-async band): the
    validated table plus the SOURCE round it answered — the staleness
    weight at fold time is a pure function of (merge round - round)."""

    round: int
    client_id: int
    latency_s: float
    recv_order: int
    wall_t: float
    table: Any


@dataclasses.dataclass(frozen=True)
class PayloadPolicy:
    """What the server demands of a wire payload (--serve_payload sketch):
    its OWN sketch spec's shape, and the PR 4 quarantine screen applied at
    the wire. `quarantine_median` is a zero-arg callable returning the live
    threshold baseline (FederatedSession.quarantine_median_host) so the
    screen tracks the running median without re-arming the queue per round;
    `clip_multiple` is --client_update_clip (0 = only the non-finite
    screen)."""

    rows: int
    cols: int
    clip_multiple: float = 0.0
    quarantine_median: Callable[[], float] | None = None

    @property
    def nbytes(self) -> int:
        return self.rows * self.cols * 4  # float32 wire dtype


def _reassemble_chunks(payload):
    """Chunk-sequence reassembly — part of the G011 boundary, first stage of
    validate_payload for a list payload. Returns (frame_dict, None, None) on
    success — a synthetic single frame carrying the header fields of chunk 0
    and the concatenated data — or (None, MALFORMED, detail): a partial,
    reordered, duplicated, oversized, or schema-mixed sequence never
    reaches the layout checks."""
    if len(payload) == 0:
        return None, MALFORMED, "empty chunk sequence"
    if len(payload) > MAX_CHUNKS:
        return None, MALFORMED, (
            f"{len(payload)} chunks > MAX_CHUNKS {MAX_CHUNKS}")
    if not all(isinstance(f, dict) for f in payload):
        return None, MALFORMED, "chunk sequence with a non-frame entry"
    head = payload[0]
    try:
        total = int(head["total"])
        seqs = [int(f["seq"]) for f in payload]
        schemas = {int(f["schema"]) for f in payload}
    except (KeyError, TypeError, ValueError):
        return None, MALFORMED, "chunk missing/bad seq/total/schema field"
    if len(schemas) != 1:
        return None, MALFORMED, "chunk sequence mixes schema versions"
    if total != len(payload):
        return None, MALFORMED, (
            f"partial chunk sequence: {len(payload)} of {total} frames")
    if seqs != list(range(total)):
        return None, MALFORMED, (
            f"chunk sequence out of order or duplicated: {seqs}")
    if any(int(f.get("total", total)) != total for f in payload):
        return None, MALFORMED, "chunk frames disagree about total"
    try:
        data = "".join(str(f["data"]) for f in payload)
    except (KeyError, TypeError):
        return None, MALFORMED, "chunk missing data field"
    merged = dict(head)
    merged["data"] = data
    merged["seq"], merged["total"] = 0, 1
    return merged, None, None


# graftlint: payload-boundary — THE sanctioned decode of untrusted wire
# bytes; every transport payload passes through here before compiled scope
def validate_payload(payload, policy: PayloadPolicy,
                     median: float | None = None,
                     out=None, screen: bool = True):
    """THE deserialization boundary for untrusted wire bytes (graftlint
    G011): every byte a transport hands the server passes through here
    before anything can reach compiled scope. Returns (table, decision,
    detail) — `table` is a validated host float32 [r, c] ndarray only when
    decision == ACCEPTED, else None.

    `out` is the fast path's landing zone (--serve_fastpath): a RingSlot
    (serve/ring.py) the decoded table is written into ONCE, after every
    structural check passed — the returned `table` is then the slot VIEW,
    never a fresh per-submission ndarray. `screen=False` defers the
    finite/L2 screen so the batched gauntlet can run it vectorized over a
    whole block (`screen_block`) — the verdicts are bitwise the same;
    ONLY the batched admission path may pass screen=False.

    Check order (first failure wins — a frame with several defects reports
    the EARLIEST stage, so an unknown-schema frame with a bad checksum is
    STALE_SCHEMA, never MALFORMED):
      MALFORMED     structural: missing payload / not a frame dict, chunk
                    list, or array / missing or unparseable schema field /
                    a broken chunk sequence (partial, reordered,
                    duplicated, schema-mixed — reassembly happens HERE,
                    inside the boundary, never in the transport)
      STALE_SCHEMA  the frame names a wire schema version this server does
                    not speak — refused BEFORE any layout field is trusted
                    (an unknown schema means the layout checks below would
                    be guesses)
      MALFORMED     layout, against the server's OWN spec: dtype / shape /
                    undecodable base64 / length-prefix (nbytes) mismatch /
                    checksum failure (one flipped bit anywhere rejects)
      QUARANTINED   the decoded table is non-finite, or its sketch-space L2
                    exceeds the quarantine multiple of the running median —
                    a poisoned payload drops BEFORE the merge, bitwise equal
                    to that client never submitting

    The in-process transport passes raw ndarrays (no frame to decode — the
    dtype/shape and quarantine screens still apply); the socket transport
    passes the frame dict its wire carried, or the LIST of continuation
    frames of a chunked table (schema >= 2) in receive order."""
    if payload is None:
        return None, MALFORMED, "no payload on a sketch-payload round"
    if isinstance(payload, np.ndarray):
        t = payload
        if t.dtype != np.float32:
            return None, MALFORMED, f"dtype {t.dtype} != float32"
        if t.shape != (policy.rows, policy.cols):
            return None, MALFORMED, (
                f"shape {t.shape} != ({policy.rows}, {policy.cols})")
        if out is not None:
            # inproc fast path: the client program's output table lands
            # straight in its ring slot — no encode/decode round-trip,
            # no standalone copy
            t = out.write(t)
            obreg.default().counter(
                "serve_table_bytes_copied_total").inc(policy.nbytes)
        else:
            t = np.ascontiguousarray(t)
        if not screen:
            return t, ACCEPTED, ""
        return _screen_table(t, policy, median)
    if isinstance(payload, (list, tuple)):
        payload, decision, detail = _reassemble_chunks(list(payload))
        if decision is not None:
            return None, decision, detail
    if not isinstance(payload, dict):
        return None, MALFORMED, f"payload is {type(payload).__name__}"
    try:
        schema = int(payload["schema"])
    except (KeyError, TypeError, ValueError):
        return None, MALFORMED, "missing/bad schema field"
    if schema != SCHEMA_VERSION:
        return None, STALE_SCHEMA, (
            f"schema {schema}, server speaks {SCHEMA_VERSION}")
    try:
        if int(payload.get("total", 1)) != 1 or int(payload.get("seq", 0)):
            # a single-frame submission claiming to be mid-sequence: the
            # transport failed to collect its siblings
            return None, MALFORMED, (
                f"partial chunk sequence: frame {payload.get('seq')} of "
                f"{payload.get('total')}")
    except (TypeError, ValueError):
        return None, MALFORMED, "bad seq/total field"
    if payload.get("dtype") != WIRE_DTYPE:
        return None, MALFORMED, f"dtype {payload.get('dtype')!r} != {WIRE_DTYPE}"
    if list(payload.get("shape", ())) != [policy.rows, policy.cols]:
        return None, MALFORMED, (
            f"shape {payload.get('shape')} != [{policy.rows}, {policy.cols}]")
    try:
        nbytes = int(payload["nbytes"])
        crc = int(payload["crc32"])
        raw = base64.b64decode(payload["data"], validate=True)
    except (KeyError, TypeError, ValueError, binascii.Error) as e:
        return None, MALFORMED, f"undecodable frame ({type(e).__name__})"
    if nbytes != policy.nbytes:
        return None, MALFORMED, (
            f"length prefix {nbytes} != spec {policy.nbytes}")
    if len(raw) != nbytes:
        return None, MALFORMED, (
            f"decoded {len(raw)} bytes, length prefix says {nbytes}")
    if (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
        return None, MALFORMED, "checksum mismatch"
    wire_view = np.frombuffer(raw, dtype=WIRE_DTYPE).reshape(
        policy.rows, policy.cols)
    if out is not None:
        # the fast path's ONE per-table copy: the decoded wire view lands
        # in the pinned ring slot (the write casts <f4 -> float32
        # bit-exactly, same bytes astype would produce)
        t = out.write(wire_view)
    else:
        t = wire_view.astype(np.float32)
    obreg.default().counter(
        "serve_table_bytes_copied_total").inc(policy.nbytes)
    if not screen:
        return t, ACCEPTED, ""
    return _screen_table(t, policy, median)


def _screen_table(t: np.ndarray, policy: PayloadPolicy,
                  median: float | None = None):
    """The PR 4 quarantine screen in sketch space, applied at the wire: a
    payload rejected here is bitwise a dropped client (zero row, zero mask)
    — the merge also re-screens, so the wire screen is a cheap early drop,
    never the only line."""
    if not np.isfinite(t).all():
        return None, QUARANTINED, "non-finite table"
    if policy.clip_multiple > 0 and policy.quarantine_median is not None:
        med = (float(policy.quarantine_median())
               if median is None else float(median))
        if med > 0:
            norm = float(np.sqrt(np.square(t, dtype=np.float64).sum()))
            if norm > policy.clip_multiple * med:
                return None, QUARANTINED, (
                    f"sketch L2 {norm:.3g} > {policy.clip_multiple:g} x "
                    f"median {med:.3g}")
    return t, ACCEPTED, ""


def screen_block(entries, policy: PayloadPolicy):
    """The batched gauntlet's vectorized finite/L2 screen: one numpy pass
    over each contiguous ring range instead of a per-table reduction.
    `entries` is a list of (table, median, block, slot_index) — block/slot
    identify the ring row a slot-backed table occupies; (table, median,
    None, -1) marks a standalone table (ring overflow), screened scalar.
    Returns one (decision, detail) per entry.

    Verdicts are BITWISE the per-table `_screen_table` results: a row of a
    contiguous [m, r, c] block reduces over the same r*c contiguous
    elements in the same order as the 2-D full-sum (numpy's pairwise
    summation is layout-deterministic), the float64 square/sqrt are
    elementwise IEEE-exact, and the detail strings format the identical
    double. Medians arrive RESOLVED (the target round's snapshot) — the
    batched path never reaches for the live quarantine_median callable."""
    verdicts: list = [None] * len(entries)
    want_norms = (policy.clip_multiple > 0
                  and policy.quarantine_median is not None)
    # group slot-backed entries by their owning ring block; each group
    # screens over ONE contiguous view of the block's buffer
    groups: dict[int, tuple[Any, list[int]]] = {}
    for i, (t, _med, blk, slot) in enumerate(entries):
        if blk is not None and slot >= 0:
            groups.setdefault(id(blk), (blk, []))[1].append(i)
        else:
            _t, decision, detail = _screen_table(t, policy, _med)
            verdicts[i] = (decision, detail)
    for blk, idxs in groups.values():
        rows = [entries[i][3] for i in idxs]
        lo, hi = min(rows), max(rows)
        chunk = blk.tables[lo:hi + 1]
        finite = np.isfinite(chunk).all(axis=(1, 2))
        norms = (np.sqrt(np.square(chunk, dtype=np.float64).sum(axis=(1, 2)))
                 if want_norms else None)
        for i, row in zip(idxs, rows):
            if not finite[row - lo]:
                verdicts[i] = (QUARANTINED, "non-finite table")
                continue
            med = float(entries[i][1])
            if want_norms and med > 0:
                norm = float(norms[row - lo])
                if norm > policy.clip_multiple * med:
                    verdicts[i] = (QUARANTINED, (
                        f"sketch L2 {norm:.3g} > {policy.clip_multiple:g} x "
                        f"median {med:.3g}"))
                    continue
            verdicts[i] = (ACCEPTED, "")
    return verdicts


class _Window:
    """One round's open invite window: invite map, arrivals, dedup set, and
    the round's quarantine-median snapshot (payload rounds) — per-ROUND so
    two concurrently-open rounds never screen against each other's
    baseline."""

    __slots__ = ("invited", "arrivals", "seen", "median")

    def __init__(self, invited: dict[int, int], median: float):
        self.invited = invited
        self.arrivals: list[Arrival] = []
        self.seen: set[int] = set()
        self.median = median


class IngestQueue:
    """Bounded arrival queue over up to `max_open_rounds` concurrently-open
    per-round windows, plus a bounded pending buffer of early submissions
    (and, in buffered-async mode, a bounded stale buffer of late payload
    submissions). Thread-safe: transports submit from their own threads;
    the assembler consumes under the same lock.

    `stale_rounds > 0` arms the ASYNC admission band: a payload submission
    for a closed round at most `stale_rounds` behind the newest window is
    ACCEPTED_STALE into the stale buffer — validated against ITS OWN
    round's retained median snapshot and invite list — instead of bouncing
    OUT_OF_ROUND; the serving layer drains the buffer into staleness-
    weighted merge folds. 0 (default) keeps the synchronous behavior
    bit-for-bit."""

    def __init__(self, capacity: int = 1024, pending_capacity: int = 256,
                 payload_policy: PayloadPolicy | None = None,
                 shed_watermark: float = 0.0,
                 shed_retry_after_s: float = 1.0,
                 max_open_rounds: int = 2,
                 stale_rounds: int = 0,
                 stale_capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_open_rounds < 1:
            raise ValueError(
                f"max_open_rounds must be >= 1, got {max_open_rounds}")
        if stale_rounds < 0:
            raise ValueError(
                f"stale_rounds must be >= 0, got {stale_rounds}")
        if not 0.0 <= shed_watermark <= 1.0:
            raise ValueError(
                f"shed_watermark must be in [0, 1] (a fraction of total "
                f"queue capacity; 0 = shedding off), got {shed_watermark}")
        self.capacity = capacity
        self.pending_capacity = max(pending_capacity, 0)
        self.max_open_rounds = max_open_rounds
        # wire-payload gauntlet (None = announce path: payloads ignored)
        self.payload_policy = payload_policy
        # buffered-async admission band (see class docstring); the stale
        # capacity exists only when the band does, so a sync queue's depth
        # arithmetic (and shed watermark) is unchanged by the knob's default
        self.stale_rounds = stale_rounds
        self.stale_capacity = max(stale_capacity, 0) if stale_rounds else 0
        # load shedding: depth at/past this fraction of TOTAL capacity —
        # everything depth() counts: one window's arrivals + pending +
        # (async) the stale band — turns submissions away BEFORE any other
        # work, with a retry-after hint, so overload degrades gracefully
        # instead of queuing unboundedly. 0 = off (QUEUE_FULL only). With
        # two windows open (pipelined invites) the combined arrivals can
        # reach the watermark sooner — the deliberately conservative side
        # for a pressure valve.
        self._shed_depth = (
            max(int(shed_watermark * (capacity + self.pending_capacity
                                      + self.stale_capacity)), 1)
            if shed_watermark > 0 else 0)
        self.shed_retry_after_s = shed_retry_after_s
        self._cv = threading.Condition()
        # open windows, keyed by round (at most max_open_rounds entries)
        self._windows: dict[int, _Window] = {}
        # --serve_fastpath: the open rounds' attached ring blocks
        # (serve/ring.py) — decoded tables land straight in their slots.
        # Popped at close_round; the block lock is a LEAF under this one.
        self._blocks: dict[int, Any] = {}
        # the newest round ever opened; the pending buffer targets
        # _newest + 1 (the round a client may push early for — whether the
        # newest window is still open or the server is mid-merge)
        self._newest: int | None = None
        # recently-CLOSED rounds' (median, invited, seen) retained for the
        # stale band: a late payload validates against the state its round
        # actually had. Pruned to the band on every open.
        self._recent: dict[int, tuple[float, dict[int, int], set[int]]] = {}
        self._stale: list[StaleArrival] = []
        self._closed = False
        # early submissions for round _newest + 1: (client_id, latency_s)
        # in arrival order, deduped; drained into the window at its open
        self._pending: list[tuple[int, float]] = []
        self._recv_counter = 0
        # optional accept hook (the service feeds its arrival-rate window);
        # called with n=1 under the queue lock — must be cheap and must not
        # call back into the queue
        self.on_accept = None
        # cumulative admission counters (metrics endpoint)
        self.accepted = 0
        self.buffered = 0
        self.accepted_stale = 0
        self.rejected_full = 0
        self.rejected_dup = 0
        self.rejected_out_of_round = 0
        self.rejected_uninvited = 0
        self.rejected_closed = 0
        # wire-facing rejections (payload gauntlet + overload)
        self.rejected_malformed = 0
        self.rejected_stale_schema = 0
        self.rejected_quarantined = 0
        self.shed = 0

    def note_wire_malformed(self) -> None:
        """Count a MALFORMED rejection the TRANSPORT decided (oversized
        frame, unparseable line, a chunk sequence cut off by a dead
        connection) — it never reaches submit(), but the /metrics
        submissions block must still see it, or an operator watching
        rejected_malformed concludes a byte-flood isn't happening."""
        with self._cv:
            self.rejected_malformed += 1

    # -- round lifecycle (assembler side) ------------------------------------

    def open_round(self, rnd: int, invited_ids) -> None:
        """Open round `rnd`'s window for the given cohort — alongside any
        window already open, up to `max_open_rounds` (the pipelined serving
        mode opens r+1 while r is still merging; a third concurrent window
        is a caller bug and raises). Pending early submissions from invited
        clients are admitted immediately (recv order preserved); pending
        entries from clients NOT in this cohort stay parked for the round
        after (they pushed for "whatever opens next")."""
        # snapshot the quarantine median BEFORE taking the lock: the read
        # may sync from device (quarantine_median_host), and the baseline
        # is constant for the whole round anyway (server state only
        # advances at the merge) — per-ROUND: each window keeps its own
        median = 0.0
        p = self.payload_policy
        if (p is not None and p.clip_multiple > 0
                and p.quarantine_median is not None):
            median = float(p.quarantine_median())
        with self._cv:
            if self._closed:
                raise RuntimeError("IngestQueue is closed")
            if rnd in self._windows:
                raise RuntimeError(f"round {rnd} is already open")
            if len(self._windows) >= self.max_open_rounds:
                raise RuntimeError(
                    f"open_round({rnd}): {len(self._windows)} window(s) "
                    f"already open ({sorted(self._windows)}), "
                    f"max_open_rounds={self.max_open_rounds} — close one "
                    "first (the pipeline depth is bounded by design)")
            win = _Window({int(c): i for i, c in enumerate(invited_ids)},
                          median)
            self._windows[rnd] = win
            self._newest = rnd if self._newest is None else max(
                self._newest, rnd)
            # the stale band moves with the newest window: prune retained
            # closed-round state (and parked stale entries can no longer
            # grow for pruned rounds; already-parked ones are drained by
            # the service's fold cadence, which enforces the same band)
            if self.stale_rounds:
                low = self._newest - self.stale_rounds
                for r in [r for r in self._recent if r < low]:
                    del self._recent[r]
            else:
                self._recent.clear()
            still_pending: list[tuple[int, float]] = []
            for cid, latency in self._pending:
                if cid in win.invited and cid not in win.seen:
                    self._admit(win, cid, latency)
                else:
                    still_pending.append((cid, latency))
            self._pending = still_pending
            self._cv.notify_all()

    def attach_block(self, rnd: int, block) -> None:
        """Arm the fast path for an OPEN round: decoded payloads for `rnd`
        land in `block`'s ring slots from here until close_round."""
        with self._cv:
            if rnd in self._windows:
                self._blocks[rnd] = block

    def _acquire_slot(self, rnd: int):
        """(block, slot) for a fast-path decode: the round's attached ring
        block and a free slot in it — (block, None) when the block is full
        (the decode falls back to a standalone table, counted as ring
        overflow), (None, None) when no fast path is armed for `rnd`."""
        with self._cv:
            blk = self._blocks.get(int(rnd))
        if blk is None:
            return None, None
        return blk, blk.acquire()

    def close_round(self, rnd: int | None = None) -> list[Arrival]:
        """Close one open window — `rnd` names it; None closes the OLDEST
        open round (the single-window callers' historical behavior) — and
        return its arrivals (submission order). Subsequent submissions
        naming the closed round are OUT_OF_ROUND (or ACCEPTED_STALE inside
        the async band)."""
        with self._cv:
            if rnd is None:
                if not self._windows:
                    return []
                rnd = min(self._windows)
            win = self._windows.pop(rnd, None)
            self._blocks.pop(rnd, None)  # no new ring acquires past close
            if win is None:
                return []
            if self.stale_rounds:
                # retain the round's screen state for the stale band: a
                # late payload validates against ITS round's median, and
                # NOT_INVITED / DUPLICATE still mean what they meant
                self._recent[rnd] = (win.median, win.invited, win.seen)
            return list(win.arrivals)

    def arrivals(self, rnd: int | None = None) -> list[Arrival]:
        """Snapshot of an open round's arrivals so far (None = oldest)."""
        with self._cv:
            win = self._window(rnd)
            return list(win.arrivals) if win is not None else []

    def _window(self, rnd: int | None) -> _Window | None:
        if rnd is not None:
            return self._windows.get(rnd)
        if not self._windows:
            return None
        return self._windows[min(self._windows)]

    # graftlint: drain-point — the serving queue's sanctioned wait: the
    # assembler blocks HERE (wall-clock transports) for quorum or deadline
    def wait_for(self, count: int, timeout_s: float,
                 rnd: int | None = None) -> list[Arrival]:
        """Block until >= `count` arrivals in round `rnd`'s window (None =
        oldest open) or `timeout_s` elapses; return the arrival snapshot.
        Wall-clock close for the socket transport — the in-process path
        closes on virtual latencies instead."""
        with self._cv:
            def ready():
                win = self._window(rnd)
                return (self._closed
                        or (win is not None and len(win.arrivals) >= count))

            self._cv.wait_for(ready, timeout=timeout_s)
            win = self._window(rnd)
            return list(win.arrivals) if win is not None else []

    def shutdown(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- submission (transport side) -----------------------------------------

    def submit(self, sub: Submission) -> str:
        """Admission decision for one submission (see module docstring for
        the rule order). Returns ACCEPTED/BUFFERED/ACCEPTED_STALE or a
        rejection reason. Every decision is a trace instant on the
        serve-ingest track, linked to the later merge span by the
        `submission` id (r<round>/c<cid>)."""
        status = self._decide(sub)
        self._finish_submit(sub, status)
        return status

    def _finish_submit(self, sub: Submission, status: str) -> None:
        """Per-submission attribution tail shared by the inline and the
        BATCHED gauntlet paths: whichever way a submission was decided,
        it gets its own registry counters and its own trace instant."""
        reg = obreg.default()
        counter = _REJECTION_COUNTERS.get(status)
        if counter is not None:
            # wire-facing rejection (or stale admission): a process-wide
            # resilience counter the chaos acceptance reads, alongside the
            # admission counter
            reg.counter(counter).inc()
        reg.counter(_ADMISSION_COUNTERS.get(
            status, "serve_admission_other_total")).inc()
        if obtrace.get().enabled:
            # guard BEFORE building args: this is the admission hot path
            # (the ingest bench pushes ~1e5 submissions/s through it), and
            # an untraced server must pay one attribute check, not two
            # f-strings per message
            obtrace.instant(
                "serve-ingest", f"submit:{status}",
                submission=f"r{int(sub.round)}/c{int(sub.client_id)}",
                round=int(sub.round), client=int(sub.client_id))

    def submit_block(self, subs) -> list[str]:
        """Batched admission (the gauntlet pool's entry point): one
        decision per submission, in order. The batching changes WHEN the
        screen arithmetic runs — one vectorized numpy pass over the
        stacked ring rows instead of per-table reductions — never what it
        computes: every verdict is bitwise the per-submission submit()
        verdict, and every submission keeps its own individually-
        attributed decision (admission counters, stderr rejection line,
        trace instant)."""
        subs = list(subs)
        statuses = self._decide_block(subs)
        for sub, status in zip(subs, statuses):
            self._finish_submit(sub, status)
        return statuses

    def _decide_block(self, subs: list[Submission]) -> list[str]:
        n = len(subs)
        statuses: list[str | None] = [None] * n
        medians = [0.0] * n
        # phase 1 — the O(1) prechecks for the whole block under ONE lock
        # hold (announce-path submissions admit right here, as inline)
        with self._cv:
            announced = False
            for i, sub in enumerate(subs):
                cid = int(sub.client_id)
                status, stale_median = self._precheck(sub, cid)
                if status is not None:
                    statuses[i] = status
                    continue
                win = self._windows.get(sub.round)
                if self.payload_policy is None:
                    self._admit(win, cid, float(sub.latency_s))
                    statuses[i] = ACCEPTED
                    announced = True
                    continue
                medians[i] = win.median if win is not None else stale_median
            if announced:
                self._cv.notify_all()
        if self.payload_policy is None or all(s is not None for s in statuses):
            return statuses
        # phase 2 — structural gauntlet per frame, OUTSIDE the lock (same
        # reasoning as _decide): accepted tables land straight in their
        # round's ring slots, screens deferred to the block pass
        entries = []  # (i, sub, blk, slot, table)
        for i, sub in enumerate(subs):
            if statuses[i] is not None:
                continue
            blk, slot = self._acquire_slot(sub.round)
            table, decision, detail = validate_payload(
                sub.payload, self.payload_policy, median=medians[i],
                out=slot, screen=False)
            if decision != ACCEPTED:
                statuses[i] = self._reject_decoded(
                    sub, decision, detail, blk, slot)
                continue
            entries.append((i, sub, blk, slot, table))
        # phase 3 — ONE vectorized finite/L2 pass over the stacked block
        verdicts = screen_block(
            [(t, medians[i], blk, (slot.index if slot is not None else -1))
             for i, _sub, blk, slot, t in entries], self.payload_policy)
        # phase 4 — per-survivor admission re-check, same as inline
        for (i, sub, blk, slot, table), (decision, detail) in zip(
                entries, verdicts):
            if decision != ACCEPTED:
                statuses[i] = self._reject_decoded(
                    sub, decision, detail, blk, slot)
            else:
                statuses[i] = self._admit_decoded(sub, table, blk, slot)
        return statuses

    def _decide(self, sub: Submission) -> str:
        cid = int(sub.client_id)
        with self._cv:
            status, stale_median = self._precheck(sub, cid)
            if status is not None:
                return status
            win = self._windows.get(sub.round)
            if self.payload_policy is None:
                # announce path: nothing left to validate — admit under the
                # same lock hold (the 1e5/s ingest-bench hot path)
                self._admit(win, cid, float(sub.latency_s))
                self._cv.notify_all()
                return ACCEPTED
            median = win.median if win is not None else stale_median
        # the validation gauntlet runs OUTSIDE the lock: base64 + crc32 +
        # ndarray work over up-to-max-frame bytes is CPU-bound, and the
        # per-connection threads must not serialize behind the one condvar
        # the assembler's wait_for also lives on. The screen threshold is
        # the TARGET ROUND's snapshot median (taken at its open_round):
        # every payload answering a round is judged against that round's
        # baseline no matter how its arrival races the merge — and no
        # device fetch under the lock. With a ring block attached (the
        # inproc fast path validates inline), the decode writes straight
        # into a slot; blk/slot are None otherwise and nothing changes.
        blk, slot = self._acquire_slot(sub.round)
        table, decision, detail = validate_payload(
            sub.payload, self.payload_policy, median=median, out=slot)
        if decision != ACCEPTED:
            return self._reject_decoded(sub, decision, detail, blk, slot)
        return self._admit_decoded(sub, table, blk, slot)

    def _reject_decoded(self, sub: Submission, decision: str, detail: str,
                        blk=None, slot=None) -> str:
        """Post-decode rejection bookkeeping, identical between the inline
        and batched paths: the class counter and the per-client stderr
        line. A ring slot the decode already wrote is zeroed back — a
        rejected payload stays bitwise a client that never submitted."""
        if slot is not None:
            blk.reject(slot)
        with self._cv:
            if decision == MALFORMED:
                self.rejected_malformed += 1
            elif decision == STALE_SCHEMA:
                self.rejected_stale_schema += 1
            else:
                self.rejected_quarantined += 1
        print(f"serve: payload from client {int(sub.client_id)} rejected "
              f"{decision} ({detail})", file=sys.stderr, flush=True)
        return decision

    def _admit_decoded(self, sub: Submission, table, blk=None,
                       slot=None) -> str:
        """Post-gauntlet admission re-check (inline and batched paths):
        the world may have moved while this thread decoded — round closed,
        a duplicate landed, capacity filled. On the fast path the slot is
        committed at the client's cohort position (ACCEPTED) or rejected
        back to zero; a stale admission copies OUT of the ring first (a
        ring view must never outlive its round's block)."""
        cid = int(sub.client_id)
        pos = -1
        with self._cv:
            if self._closed:
                self.rejected_closed += 1
                status = CLOSED
            else:
                win = self._windows.get(sub.round)
                if win is None:
                    # the window closed mid-decode: the stale band may
                    # still take it (the same re-check _precheck ran). A
                    # ring-backed table detaches first — host numpy both
                    # sides, the slot's block dies with its round
                    status = self._admit_stale(
                        sub, cid,
                        np.array(table, np.float32)  # graftlint: disable=G001 — host ring-view detach
                        if slot is not None else table)
                elif cid in win.seen:
                    self.rejected_dup += 1
                    status = DUPLICATE
                elif len(win.arrivals) >= self.capacity:
                    self.rejected_full += 1
                    status = QUEUE_FULL
                else:
                    self._admit(win, cid, float(sub.latency_s), table)
                    pos = win.invited[cid]
                    self._cv.notify_all()
                    status = ACCEPTED
        if slot is not None:
            if status == ACCEPTED:
                blk.commit(slot, pos)
            else:
                blk.reject(slot)
        elif status == ACCEPTED and blk is not None:
            # ring overflow fallback: the block had no free slot, so the
            # admitted table is standalone — register it so the close's
            # scatter still sees it at its cohort position
            blk.add_extra(pos, table)
        return status

    def _precheck(self, sub: Submission,
                  cid: int) -> tuple[str | None, float]:
        """Everything before the payload gauntlet — cheap O(1) set/dict
        probes, lock held. Returns (decision, stale_median): decision None
        when the submission is admissible so far (the caller then runs the
        gauntlet, or admits directly on the announce path); stale_median is
        the target round's retained screen baseline when the submission is
        a stale-band candidate (its window already closed)."""
        if self._closed:
            self.rejected_closed += 1
            return CLOSED, 0.0
        if (self._shed_depth and self.depth_locked() >= self._shed_depth):
            win = self._windows.get(sub.round)
            recent = self._recent.get(sub.round)
            if ((win is not None and cid in win.seen)
                    or (recent is not None and cid in recent[2])):
                # at-least-once under overload: a retry of an ALREADY
                # ADMITTED submission — into the open window OR the stale
                # band — must hear DUPLICATE (== success, the reply was
                # lost), not SHEDDING — otherwise the client burns its
                # whole retry budget on a submission the merge will
                # count. O(1) probes, so the shed path stays flood-cheap.
                self.rejected_dup += 1
                return DUPLICATE, 0.0
            # overload: turn the submission away BEFORE any other work
            # (no invite lookup, no payload decode — the whole point is
            # bounding the per-rejection cost under a flood)
            self.shed += 1
            return SHEDDING, 0.0
        win = self._windows.get(sub.round)
        if win is None:
            if (self._newest is not None and sub.round == self._newest + 1
                    and self.payload_policy is None):
                # early push for the round after the newest window (open
                # or mid-merge): park it, bounded (dup before full: a
                # retry of an already-parked push is a DUPLICATE even
                # when the buffer has no room left)
                if any(c == cid for c, _ in self._pending):
                    self.rejected_dup += 1
                    return DUPLICATE, 0.0
                if len(self._pending) >= self.pending_capacity:
                    self.rejected_full += 1
                    return QUEUE_FULL, 0.0
                self._pending.append((cid, float(sub.latency_s)))
                self.buffered += 1
                return BUFFERED, 0.0
            # LATE: the async band admits a payload for a recently-closed
            # round into the stale buffer (invite/dedup checked against
            # that round's retained state); everything else bounces
            recent = (self._recent.get(sub.round)
                      if self.payload_policy is not None else None)
            if recent is not None:
                _, invited, seen = recent
                if cid not in invited:
                    self.rejected_uninvited += 1
                    return NOT_INVITED, 0.0
                if cid in seen:
                    self.rejected_dup += 1
                    return DUPLICATE, 0.0
                if len(self._stale) >= self.stale_capacity:
                    self.rejected_full += 1
                    return QUEUE_FULL, 0.0
                # admissible into the stale band: gauntlet next, against
                # the round's retained median
                return None, recent[0]
            self.rejected_out_of_round += 1
            return OUT_OF_ROUND, 0.0
        if cid not in win.invited:
            self.rejected_uninvited += 1
            return NOT_INVITED, 0.0
        if cid in win.seen:
            self.rejected_dup += 1
            return DUPLICATE, 0.0
        if len(win.arrivals) >= self.capacity:
            self.rejected_full += 1
            return QUEUE_FULL, 0.0
        # admissible so far: the payload path now runs the gauntlet (lock
        # released) and re-checks; the announce path admits immediately
        return None, 0.0

    def _admit_stale(self, sub: Submission, cid: int, table) -> str:
        """Post-gauntlet admission into the stale buffer (lock held) — the
        same re-checks _precheck ran, because the world may have moved
        while this thread decoded."""
        recent = self._recent.get(sub.round)
        if recent is None:
            self.rejected_out_of_round += 1
            return OUT_OF_ROUND
        _, invited, seen = recent
        if cid not in invited:
            self.rejected_uninvited += 1
            return NOT_INVITED
        if cid in seen:
            self.rejected_dup += 1
            return DUPLICATE
        if len(self._stale) >= self.stale_capacity:
            self.rejected_full += 1
            return QUEUE_FULL
        seen.add(cid)
        self._stale.append(StaleArrival(
            int(sub.round), cid, float(sub.latency_s), self._recv_counter,
            time.perf_counter(), table))
        self._recv_counter += 1
        self.accepted_stale += 1
        self._cv.notify_all()
        return ACCEPTED_STALE

    def _admit(self, win: _Window, cid: int, latency_s: float,
               table=None) -> None:
        """Record an accepted arrival into a window (lock held)."""
        win.arrivals.append(
            Arrival(cid, latency_s, self._recv_counter, time.perf_counter(),
                    table))
        self._recv_counter += 1
        win.seen.add(cid)
        self.accepted += 1
        if self.on_accept is not None:
            self.on_accept(1)

    def drain_stale(self) -> list[StaleArrival]:
        """Hand the parked stale submissions to the serving layer (which
        folds them into the next merge with their staleness weights) and
        clear the buffer."""
        with self._cv:
            out = self._stale
            self._stale = []
            return out

    def prune_stale(self, rnd: int) -> int:
        """Drop parked stale entries AND retained closed-round band state
        for rounds >= `rnd` — the rewind discipline's queue half: a round
        the runner never committed will be RE-served, and its pre-rewind
        stale arrivals (or its stale dedup/median state) must not survive
        into the replay, or the same client's table could merge twice.
        The early-push high-water mark rewinds with it, so the replayed
        timeline's BUFFERED/OUT_OF_ROUND verdicts (and the stale band's
        lower edge) match the original run's round for round. Returns how
        many parked entries were dropped."""
        with self._cv:
            before = len(self._stale)
            self._stale = [s for s in self._stale if s.round < rnd]
            for r in [r for r in self._recent if r >= rnd]:
                del self._recent[r]
            if self._newest is not None and self._newest >= rnd:
                self._newest = rnd - 1 if rnd > 0 else None
            return before - len(self._stale)

    # -- introspection --------------------------------------------------------

    def depth_locked(self) -> int:
        return (sum(len(w.arrivals) for w in self._windows.values())
                + len(self._pending) + len(self._stale))

    def depth(self) -> int:
        """Arrivals across every open window + parked early submissions +
        parked stale submissions (the 'queue depth' the metrics endpoint
        reports)."""
        with self._cv:
            return self.depth_locked()

    def open_rounds(self) -> list[int]:
        """The rounds with an open window, oldest first."""
        with self._cv:
            return sorted(self._windows)

    def pending_snapshot(self) -> list[tuple[int, float]]:
        """Checkpointable view of the early-submission buffer."""
        with self._cv:
            return list(self._pending)

    def restore_pending(self, pending) -> None:
        """Re-seed the early-submission buffer from a checkpoint."""
        with self._cv:
            self._pending = [(int(c), float(s)) for c, s in pending]

    def band_snapshot(self) -> dict:
        """Checkpointable view of the buffered-async band state: the
        parked stale arrivals (validated tables included), the retained
        closed-round screen state (median / invite map / dedup set), the
        high-water mark, and the admission counter — everything a resumed
        or rewound run needs so its stale folds (slot order included, via
        recv_order) replay bit-identically. Tables stay ndarrays here;
        the serving layer owns the JSON encoding (utils/checkpoint.py
        writes the result into meta.json under serve.band)."""
        with self._cv:
            return self._band_snapshot_locked()

    def _band_snapshot_locked(self) -> dict:
        return {
            "stale": [(s.round, s.client_id, s.latency_s,
                       s.recv_order, s.wall_t, s.table)
                      for s in self._stale],
            "recent": [(r, m, dict(inv), set(seen))
                       for r, (m, inv, seen) in self._recent.items()],
            "newest": self._newest,
            "recv_counter": self._recv_counter,
        }

    def boundary_snapshot(self) -> tuple[list, dict]:
        """(pending, band) under ONE lock hold — the round-boundary
        checkpoint pair. Taken separately, a submission landing between
        the two reads would produce a torn boundary (an early arrival
        recorded without its contemporaneous stale admission — a state
        the live queue never held), and a resume from it would diverge
        from the uninterrupted twin."""
        with self._cv:
            return list(self._pending), self._band_snapshot_locked()

    def restore_band(self, band: dict) -> None:
        """Re-seed the buffered-async band state from a snapshot (the
        committed-round-boundary twin of restore_pending) — the rewind
        half of the stale-buffer checkpoint discipline."""
        with self._cv:
            self._stale = [
                StaleArrival(int(r), int(c), float(lat), int(ro),
                             float(w), t)
                for r, c, lat, ro, w, t in band.get("stale", [])]
            self._recent = {
                int(r): (float(m), {int(c): int(p) for c, p in inv.items()},
                         {int(c) for c in seen})
                for r, m, inv, seen in band.get("recent", [])}
            self._newest = (None if band.get("newest") is None
                            else int(band["newest"]))
            self._recv_counter = int(band.get("recv_counter",
                                              self._recv_counter))

    def counters(self) -> dict[str, int]:
        with self._cv:
            return {
                "accepted": self.accepted,
                "buffered": self.buffered,
                "accepted_stale": self.accepted_stale,
                "rejected_full": self.rejected_full,
                "rejected_dup": self.rejected_dup,
                "rejected_out_of_round": self.rejected_out_of_round,
                "rejected_uninvited": self.rejected_uninvited,
                "rejected_closed": self.rejected_closed,
                "rejected_malformed": self.rejected_malformed,
                "rejected_stale_schema": self.rejected_stale_schema,
                "rejected_quarantined": self.rejected_quarantined,
                "shed": self.shed,
            }
