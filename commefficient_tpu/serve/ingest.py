"""Ingest layer: admission control for client sketch submissions.

The paper's deployment story (FetchSGD §1) is millions of clients *pushing*
updates at an always-on aggregator; the linearity of the Count Sketch makes
the server-side merge of asynchronously-arriving updates cheap. This module
is the front door of that inversion: a bounded, thread-safe arrival queue
with explicit admission decisions — every submission is either ACCEPTED into
the open round or rejected with a reason the transport echoes back to the
client (`QUEUE_FULL` is the backpressure signal a well-behaved client backs
off on).

Admission rules, in check order:

- ``CLOSED``       — the service is shutting down (or no round ever opened).
- ``SHEDDING``     — load shedding: the queue is past its pressure watermark
  and the submission is turned away BEFORE any expensive work (with a
  retry-after hint on the socket wire), so overload degrades gracefully
  instead of queuing unboundedly. One O(1) probe runs first: a retry of an
  already-ADMITTED submission still hears DUPLICATE (== success) so an
  at-least-once client never burns its retry budget on a submission the
  merge will count.
- ``QUEUE_FULL``   — the bounded queue is at capacity: backpressure.
- ``OUT_OF_ROUND`` — the submission names a round that is not the open one.
  Late (already-closed round) is always rejected; EARLY (the round after the
  open one — or after the last CLOSED one while the server is mid-merge
  between rounds) is buffered in the bounded pending queue and admitted when
  that round opens — a pushing client does not resubmit just because the
  server is mid-merge. With a payload policy armed, early pushes are
  rejected instead of buffered: a sketch payload is a function of the open
  round's params, so a table "for the next round" cannot exist yet.
- ``NOT_INVITED``  — the client is not in the open round's cohort.
- ``DUPLICATE``    — the client already has an accepted submission this
  round (an at-least-once transport may retry; the merge must not double
  count a client).

With a payload policy armed (the wire-payload round, ``--serve_payload
sketch``), an otherwise-admissible submission then runs the VALIDATION
GAUNTLET (`validate_payload` — the one sanctioned deserialization boundary,
graftlint G011) before anything can reach compiled scope; its docstring has
the exact first-failure-wins check order (structural MALFORMED, then
STALE_SCHEMA, then layout MALFORMED, then QUARANTINED):

- ``STALE_SCHEMA`` — the frame names a wire schema version this server does
  not speak (refuse rather than guess at layout).
- ``MALFORMED``    — missing payload, undecodable base64, dtype/shape
  mismatch against the server's OWN sketch spec, length-prefix (nbytes)
  mismatch, or a checksum failure (one flipped bit anywhere rejects).
- ``QUARANTINED``  — the decoded table is non-finite, or its sketch-space
  L2 norm exceeds the quarantine multiple of the running median (the PR 4
  screen, applied at the wire): a poisoned payload is dropped BEFORE the
  merge, bitwise equal to that client never submitting.

The gauntlet screens what a TABLE can reveal — structure, schema,
magnitude. An in-screen Byzantine payload (a sign-flipped table, a
colluding clone at median norm) is norm-invariant and sails through BY
DESIGN; the defense against those is downstream, in the merge itself
(``--merge_policy trimmed|median`` — see the README threat model). The
gauntlet's scalar median snapshot is the same table-space ring the merge
advances, so a payload rejected QUARANTINED here is bitwise the payload
the merge would have quarantined (pinned in tests/test_byzantine.py).

All counters are cumulative over the service lifetime and feed the metrics
endpoint (serve/metrics.py); the wire-facing rejections additionally bump
process-wide resilience counters in the obs registry.
"""

from __future__ import annotations

import base64
import binascii
import dataclasses
import sys
import threading
import time
import zlib
from typing import Any, Callable

import numpy as np

from ..obs import registry as obreg
from ..obs import trace as obtrace
from ..sketch.payload import SCHEMA_VERSION, WIRE_DTYPE

# rejection reasons (wire-visible: the socket transport echoes them)
ACCEPTED = "ACCEPTED"
CLOSED = "CLOSED"
QUEUE_FULL = "QUEUE_FULL"
OUT_OF_ROUND = "OUT_OF_ROUND"
NOT_INVITED = "NOT_INVITED"
DUPLICATE = "DUPLICATE"
BUFFERED = "BUFFERED"  # early submission parked for the next round
# wire-payload gauntlet + overload decisions (see module docstring)
MALFORMED = "MALFORMED"
STALE_SCHEMA = "STALE_SCHEMA"
QUARANTINED = "QUARANTINED"
SHEDDING = "SHEDDING"

# obs-registry resilience counters per wire-facing rejection class: the
# chaos acceptance reads these (every rejection = a decision + an obs
# instant + a counter)
_REJECTION_COUNTERS = {
    MALFORMED: "serve_rejected_malformed_total",
    STALE_SCHEMA: "serve_rejected_stale_schema_total",
    QUARANTINED: "serve_rejected_quarantined_total",
    SHEDDING: "serve_shed_total",
}


@dataclasses.dataclass(frozen=True)
class Submission:
    """One client push. `latency_s` is the client's submission delay relative
    to the round's invite (simulated by the traffic generator; a real client
    would stamp send time) — the assembler's VIRTUAL clock orders arrivals
    by it, so a served round is a pure function of the submission set.
    `payload_bytes` sizes the (simulated) sketch blob for wire accounting.
    `payload` is the wire payload of a sketch-carrying submission
    (--serve_payload sketch): a raw [r, c] float32 ndarray on the in-process
    transport, a frame dict (sketch/payload.py encode_frame) off the socket
    wire — None on the announce path."""

    client_id: int
    round: int
    latency_s: float = 0.0
    payload_bytes: int = 0
    payload: Any = None


@dataclasses.dataclass(frozen=True)
class Arrival:
    """An accepted submission, as the assembler sees it."""

    client_id: int
    latency_s: float
    recv_order: int  # wall arrival order (tie-break + socket-mode ordering)
    # host wall timestamp (perf_counter) of the ACCEPT: the start of the
    # submission-to-merge latency the obs layer resolves at commit
    wall_t: float = 0.0
    # the VALIDATED [r, c] table of a payload-carrying submission (already
    # through the gauntlet — the only route wire bytes take to the merge)
    table: Any = None


@dataclasses.dataclass(frozen=True)
class PayloadPolicy:
    """What the server demands of a wire payload (--serve_payload sketch):
    its OWN sketch spec's shape, and the PR 4 quarantine screen applied at
    the wire. `quarantine_median` is a zero-arg callable returning the live
    threshold baseline (FederatedSession.quarantine_median_host) so the
    screen tracks the running median without re-arming the queue per round;
    `clip_multiple` is --client_update_clip (0 = only the non-finite
    screen)."""

    rows: int
    cols: int
    clip_multiple: float = 0.0
    quarantine_median: Callable[[], float] | None = None

    @property
    def nbytes(self) -> int:
        return self.rows * self.cols * 4  # float32 wire dtype


# graftlint: payload-boundary — THE sanctioned decode of untrusted wire
# bytes; every transport payload passes through here before compiled scope
def validate_payload(payload, policy: PayloadPolicy,
                     median: float | None = None):
    """THE deserialization boundary for untrusted wire bytes (graftlint
    G011): every byte a transport hands the server passes through here
    before anything can reach compiled scope. Returns (table, decision,
    detail) — `table` is a validated host float32 [r, c] ndarray only when
    decision == ACCEPTED, else None.

    Check order (first failure wins — a frame with several defects reports
    the EARLIEST stage, so an unknown-schema frame with a bad checksum is
    STALE_SCHEMA, never MALFORMED):
      MALFORMED     structural: missing payload / not a frame dict or array
                    / missing or unparseable schema field
      STALE_SCHEMA  the frame names a wire schema version this server does
                    not speak — refused BEFORE any layout field is trusted
                    (an unknown schema means the layout checks below would
                    be guesses)
      MALFORMED     layout, against the server's OWN spec: dtype / shape /
                    undecodable base64 / length-prefix (nbytes) mismatch /
                    checksum failure (one flipped bit anywhere rejects)
      QUARANTINED   the decoded table is non-finite, or its sketch-space L2
                    exceeds the quarantine multiple of the running median —
                    a poisoned payload drops BEFORE the merge, bitwise equal
                    to that client never submitting

    The in-process transport passes raw ndarrays (no frame to decode — the
    dtype/shape and quarantine screens still apply); the socket transport
    passes the frame dict its wire carried."""
    if payload is None:
        return None, MALFORMED, "no payload on a sketch-payload round"
    if isinstance(payload, np.ndarray):
        t = payload
        if t.dtype != np.float32:
            return None, MALFORMED, f"dtype {t.dtype} != float32"
        if t.shape != (policy.rows, policy.cols):
            return None, MALFORMED, (
                f"shape {t.shape} != ({policy.rows}, {policy.cols})")
        return _screen_table(np.ascontiguousarray(t), policy, median)
    if not isinstance(payload, dict):
        return None, MALFORMED, f"payload is {type(payload).__name__}"
    try:
        schema = int(payload["schema"])
    except (KeyError, TypeError, ValueError):
        return None, MALFORMED, "missing/bad schema field"
    if schema != SCHEMA_VERSION:
        return None, STALE_SCHEMA, (
            f"schema {schema}, server speaks {SCHEMA_VERSION}")
    if payload.get("dtype") != WIRE_DTYPE:
        return None, MALFORMED, f"dtype {payload.get('dtype')!r} != {WIRE_DTYPE}"
    if list(payload.get("shape", ())) != [policy.rows, policy.cols]:
        return None, MALFORMED, (
            f"shape {payload.get('shape')} != [{policy.rows}, {policy.cols}]")
    try:
        nbytes = int(payload["nbytes"])
        crc = int(payload["crc32"])
        raw = base64.b64decode(payload["data"], validate=True)
    except (KeyError, TypeError, ValueError, binascii.Error) as e:
        return None, MALFORMED, f"undecodable frame ({type(e).__name__})"
    if nbytes != policy.nbytes:
        return None, MALFORMED, (
            f"length prefix {nbytes} != spec {policy.nbytes}")
    if len(raw) != nbytes:
        return None, MALFORMED, (
            f"decoded {len(raw)} bytes, length prefix says {nbytes}")
    if (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
        return None, MALFORMED, "checksum mismatch"
    t = np.frombuffer(raw, dtype=WIRE_DTYPE).astype(
        np.float32).reshape(policy.rows, policy.cols)
    return _screen_table(t, policy, median)


def _screen_table(t: np.ndarray, policy: PayloadPolicy,
                  median: float | None = None):
    """The PR 4 quarantine screen in sketch space, applied at the wire: a
    payload rejected here is bitwise a dropped client (zero row, zero mask)
    — the merge also re-screens, so the wire screen is a cheap early drop,
    never the only line."""
    if not np.isfinite(t).all():
        return None, QUARANTINED, "non-finite table"
    if policy.clip_multiple > 0 and policy.quarantine_median is not None:
        med = (float(policy.quarantine_median())
               if median is None else float(median))
        if med > 0:
            norm = float(np.sqrt(np.square(t, dtype=np.float64).sum()))
            if norm > policy.clip_multiple * med:
                return None, QUARANTINED, (
                    f"sketch L2 {norm:.3g} > {policy.clip_multiple:g} x "
                    f"median {med:.3g}")
    return t, ACCEPTED, ""


class IngestQueue:
    """Bounded arrival queue for ONE open round plus a bounded pending
    buffer of early submissions. Thread-safe: transports submit from their
    own threads; the assembler consumes under the same lock."""

    def __init__(self, capacity: int = 1024, pending_capacity: int = 256,
                 payload_policy: PayloadPolicy | None = None,
                 shed_watermark: float = 0.0,
                 shed_retry_after_s: float = 1.0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not 0.0 <= shed_watermark <= 1.0:
            raise ValueError(
                f"shed_watermark must be in [0, 1] (a fraction of total "
                f"queue capacity; 0 = shedding off), got {shed_watermark}")
        self.capacity = capacity
        self.pending_capacity = max(pending_capacity, 0)
        # wire-payload gauntlet (None = announce path: payloads ignored)
        self.payload_policy = payload_policy
        # load shedding: depth at/past this fraction of TOTAL capacity
        # (arrivals + pending) turns submissions away BEFORE any other
        # work, with a retry-after hint — overload degrades gracefully
        # instead of queuing unboundedly. 0 = off (QUEUE_FULL only).
        self._shed_depth = (
            max(int(shed_watermark * (capacity + max(pending_capacity, 0))),
                1)
            if shed_watermark > 0 else 0)
        self.shed_retry_after_s = shed_retry_after_s
        # the open round's quarantine-median snapshot (taken at open_round,
        # host float): every payload in a round screens against the same
        # baseline, and no submission pays a device fetch under the lock
        self._round_median = 0.0
        self._cv = threading.Condition()
        self._open_round: int | None = None
        # the round an early push may target while NO round is open (the
        # server is mid-merge between close_round(r) and open_round(r+1)):
        # a client must not have to resubmit just because it raced the merge
        self._next_round: int | None = None
        self._invited: dict[int, int] = {}  # client_id -> cohort position
        self._arrivals: list[Arrival] = []
        self._seen: set[int] = set()
        self._closed = False
        # early submissions for round open+1: (client_id, latency_s) in
        # arrival order, deduped; drained into arrivals at the next open
        self._pending: list[tuple[int, float]] = []
        self._recv_counter = 0
        # optional accept hook (the service feeds its arrival-rate window);
        # called with n=1 under the queue lock — must be cheap and must not
        # call back into the queue
        self.on_accept = None
        # cumulative admission counters (metrics endpoint)
        self.accepted = 0
        self.buffered = 0
        self.rejected_full = 0
        self.rejected_dup = 0
        self.rejected_out_of_round = 0
        self.rejected_uninvited = 0
        self.rejected_closed = 0
        # wire-facing rejections (payload gauntlet + overload)
        self.rejected_malformed = 0
        self.rejected_stale_schema = 0
        self.rejected_quarantined = 0
        self.shed = 0

    def note_wire_malformed(self) -> None:
        """Count a MALFORMED rejection the TRANSPORT decided (oversized
        frame, unparseable line) — it never reaches submit(), but the
        /metrics submissions block must still see it, or an operator
        watching rejected_malformed concludes a byte-flood isn't
        happening."""
        with self._cv:
            self.rejected_malformed += 1

    # -- round lifecycle (assembler side) ------------------------------------

    def open_round(self, rnd: int, invited_ids) -> None:
        """Open round `rnd` for the given cohort. Pending early submissions
        from invited clients are admitted immediately (recv order preserved);
        pending entries from clients NOT in this cohort stay parked for the
        round after (they pushed for "whatever opens next")."""
        # snapshot the quarantine median BEFORE taking the lock: the read
        # may sync from device (quarantine_median_host), and the baseline
        # is constant for the whole round anyway (server state only
        # advances at the merge)
        median = 0.0
        p = self.payload_policy
        if (p is not None and p.clip_multiple > 0
                and p.quarantine_median is not None):
            median = float(p.quarantine_median())
        with self._cv:
            self._round_median = median
            if self._closed:
                raise RuntimeError("IngestQueue is closed")
            self._open_round = rnd
            self._next_round = rnd + 1
            self._invited = {int(c): i for i, c in enumerate(invited_ids)}
            self._arrivals = []
            self._seen = set()
            still_pending: list[tuple[int, float]] = []
            for cid, latency in self._pending:
                if cid in self._invited and cid not in self._seen:
                    self._admit(cid, latency)
                else:
                    still_pending.append((cid, latency))
            self._pending = still_pending
            self._cv.notify_all()

    def close_round(self) -> list[Arrival]:
        """Close the open round and return its arrivals (submission-order).
        Subsequent submissions naming the closed round are OUT_OF_ROUND."""
        with self._cv:
            out = list(self._arrivals)
            self._open_round = None
            self._invited = {}
            self._arrivals = []
            self._seen = set()
            return out

    def arrivals(self) -> list[Arrival]:
        """Snapshot of the open round's arrivals so far."""
        with self._cv:
            return list(self._arrivals)

    # graftlint: drain-point — the serving queue's sanctioned wait: the
    # assembler blocks HERE (wall-clock transports) for quorum or deadline
    def wait_for(self, count: int, timeout_s: float) -> list[Arrival]:
        """Block until >= `count` arrivals or `timeout_s` elapses; return
        the arrival snapshot. Wall-clock close for the socket transport —
        the in-process path closes on virtual latencies instead."""
        with self._cv:
            self._cv.wait_for(
                lambda: len(self._arrivals) >= count or self._closed,
                timeout=timeout_s,
            )
            return list(self._arrivals)

    def shutdown(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- submission (transport side) -----------------------------------------

    def submit(self, sub: Submission) -> str:
        """Admission decision for one submission (see module docstring for
        the rule order). Returns ACCEPTED/BUFFERED or a rejection reason.
        Every decision is a trace instant on the serve-ingest track, linked
        to the later merge span by the `submission` id (r<round>/c<cid>)."""
        status = self._decide(sub)
        counter = _REJECTION_COUNTERS.get(status)
        if counter is not None:
            # wire-facing rejection: a process-wide resilience counter the
            # chaos acceptance reads, alongside the admission counter
            obreg.default().counter(counter).inc()
        if obtrace.get().enabled:
            # guard BEFORE building args: this is the admission hot path
            # (the ingest bench pushes ~1e5 submissions/s through it), and
            # an untraced server must pay one attribute check, not two
            # f-strings per message
            obtrace.instant(
                "serve-ingest", f"submit:{status}",
                submission=f"r{int(sub.round)}/c{int(sub.client_id)}",
                round=int(sub.round), client=int(sub.client_id))
        return status

    def _decide(self, sub: Submission) -> str:
        cid = int(sub.client_id)
        with self._cv:
            status = self._precheck(sub, cid)
            if status is not None:
                return status
            if self.payload_policy is None:
                # announce path: nothing left to validate — admit under the
                # same lock hold (the 1e5/s ingest-bench hot path)
                self._admit(cid, float(sub.latency_s))
                self._cv.notify_all()
                return ACCEPTED
            median = self._round_median
        # the validation gauntlet runs OUTSIDE the lock: base64 + crc32 +
        # ndarray work over up-to-max-frame bytes is CPU-bound, and the
        # per-connection threads must not serialize behind the one condvar
        # the assembler's wait_for also lives on. The screen threshold is
        # the round's SNAPSHOT median (taken at open_round): every payload
        # in a round is judged against the same baseline no matter how its
        # arrival races the merge — and no device fetch under the lock.
        table, decision, detail = validate_payload(
            sub.payload, self.payload_policy, median=median)
        if decision != ACCEPTED:
            with self._cv:
                if decision == MALFORMED:
                    self.rejected_malformed += 1
                elif decision == STALE_SCHEMA:
                    self.rejected_stale_schema += 1
                else:
                    self.rejected_quarantined += 1
            print(f"serve: payload from client {cid} rejected "
                  f"{decision} ({detail})", file=sys.stderr, flush=True)
            return decision
        with self._cv:
            # re-check: the world may have moved while this thread decoded
            # (round closed, a duplicate landed, capacity filled)
            if self._closed:
                self.rejected_closed += 1
                return CLOSED
            if self._open_round is None or sub.round != self._open_round:
                self.rejected_out_of_round += 1
                return OUT_OF_ROUND
            if cid in self._seen:
                self.rejected_dup += 1
                return DUPLICATE
            if len(self._arrivals) >= self.capacity:
                self.rejected_full += 1
                return QUEUE_FULL
            self._admit(cid, float(sub.latency_s), table)
            self._cv.notify_all()
            return ACCEPTED

    def _precheck(self, sub: Submission, cid: int) -> str | None:
        """Everything before the payload gauntlet — cheap O(1) set/dict
        probes, lock held. Returns a decision, or None when the submission
        is admissible so far (the caller then runs the gauntlet, or admits
        directly on the announce path)."""
        if self._closed:
            self.rejected_closed += 1
            return CLOSED
        if (self._shed_depth
                and len(self._arrivals) + len(self._pending)
                >= self._shed_depth):
            if (self._open_round is not None
                    and sub.round == self._open_round
                    and cid in self._seen):
                # at-least-once under overload: a retry of an ALREADY
                # ADMITTED submission must hear DUPLICATE (== success, the
                # reply was lost), not SHEDDING — otherwise the client
                # burns its whole retry budget on a submission the merge
                # will count. An O(1) probe, so the shed path stays
                # flood-cheap.
                self.rejected_dup += 1
                return DUPLICATE
            # overload: turn the submission away BEFORE any other work
            # (no invite lookup, no payload decode — the whole point is
            # bounding the per-rejection cost under a flood)
            self.shed += 1
            return SHEDDING
        if self._open_round is None or sub.round != self._open_round:
            if (self._next_round is not None
                    and sub.round == self._next_round
                    and self.payload_policy is None):
                # early push for the next round: park it, bounded
                # (dup before full: a retry of an already-parked push is
                # a DUPLICATE even when the buffer has no room left)
                if any(c == cid for c, _ in self._pending):
                    self.rejected_dup += 1
                    return DUPLICATE
                if len(self._pending) >= self.pending_capacity:
                    self.rejected_full += 1
                    return QUEUE_FULL
                self._pending.append((cid, float(sub.latency_s)))
                self.buffered += 1
                return BUFFERED
            self.rejected_out_of_round += 1
            return OUT_OF_ROUND
        if cid not in self._invited:
            self.rejected_uninvited += 1
            return NOT_INVITED
        if cid in self._seen:
            self.rejected_dup += 1
            return DUPLICATE
        if len(self._arrivals) >= self.capacity:
            self.rejected_full += 1
            return QUEUE_FULL
        # admissible so far: the payload path now runs the gauntlet (lock
        # released) and re-checks; the announce path admits immediately
        return None

    def _admit(self, cid: int, latency_s: float, table=None) -> None:
        """Record an accepted arrival (lock held)."""
        self._arrivals.append(
            Arrival(cid, latency_s, self._recv_counter, time.perf_counter(),
                    table))
        self._recv_counter += 1
        self._seen.add(cid)
        self.accepted += 1
        if self.on_accept is not None:
            self.on_accept(1)

    # -- introspection --------------------------------------------------------

    def depth(self) -> int:
        """Open-round arrivals + parked early submissions (the 'queue
        depth' the metrics endpoint reports)."""
        with self._cv:
            return len(self._arrivals) + len(self._pending)

    def pending_snapshot(self) -> list[tuple[int, float]]:
        """Checkpointable view of the early-submission buffer."""
        with self._cv:
            return list(self._pending)

    def restore_pending(self, pending) -> None:
        """Re-seed the early-submission buffer from a checkpoint."""
        with self._cv:
            self._pending = [(int(c), float(s)) for c, s in pending]

    def counters(self) -> dict[str, int]:
        with self._cv:
            return {
                "accepted": self.accepted,
                "buffered": self.buffered,
                "rejected_full": self.rejected_full,
                "rejected_dup": self.rejected_dup,
                "rejected_out_of_round": self.rejected_out_of_round,
                "rejected_uninvited": self.rejected_uninvited,
                "rejected_closed": self.rejected_closed,
                "rejected_malformed": self.rejected_malformed,
                "rejected_stale_schema": self.rejected_stale_schema,
                "rejected_quarantined": self.rejected_quarantined,
                "shed": self.shed,
            }
