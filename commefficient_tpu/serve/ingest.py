"""Ingest layer: admission control for client sketch submissions.

The paper's deployment story (FetchSGD §1) is millions of clients *pushing*
updates at an always-on aggregator; the linearity of the Count Sketch makes
the server-side merge of asynchronously-arriving updates cheap. This module
is the front door of that inversion: a bounded, thread-safe arrival queue
with explicit admission decisions — every submission is either ACCEPTED into
the open round or rejected with a reason the transport echoes back to the
client (`QUEUE_FULL` is the backpressure signal a well-behaved client backs
off on).

Admission rules, in check order:

- ``CLOSED``       — the service is shutting down (or no round ever opened).
- ``QUEUE_FULL``   — the bounded queue is at capacity: backpressure.
- ``OUT_OF_ROUND`` — the submission names a round that is not the open one.
  Late (already-closed round) is always rejected; EARLY (the round after the
  open one — or after the last CLOSED one while the server is mid-merge
  between rounds) is buffered in the bounded pending queue and admitted when
  that round opens — a pushing client does not resubmit just because the
  server is mid-merge.
- ``NOT_INVITED``  — the client is not in the open round's cohort.
- ``DUPLICATE``    — the client already has an accepted submission this
  round (an at-least-once transport may retry; the merge must not double
  count a client).

All counters are cumulative over the service lifetime and feed the metrics
endpoint (serve/metrics.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from ..obs import trace as obtrace

# rejection reasons (wire-visible: the socket transport echoes them)
ACCEPTED = "ACCEPTED"
CLOSED = "CLOSED"
QUEUE_FULL = "QUEUE_FULL"
OUT_OF_ROUND = "OUT_OF_ROUND"
NOT_INVITED = "NOT_INVITED"
DUPLICATE = "DUPLICATE"
BUFFERED = "BUFFERED"  # early submission parked for the next round


@dataclasses.dataclass(frozen=True)
class Submission:
    """One client push. `latency_s` is the client's submission delay relative
    to the round's invite (simulated by the traffic generator; a real client
    would stamp send time) — the assembler's VIRTUAL clock orders arrivals
    by it, so a served round is a pure function of the submission set.
    `payload_bytes` sizes the (simulated) sketch blob for wire accounting."""

    client_id: int
    round: int
    latency_s: float = 0.0
    payload_bytes: int = 0


@dataclasses.dataclass(frozen=True)
class Arrival:
    """An accepted submission, as the assembler sees it."""

    client_id: int
    latency_s: float
    recv_order: int  # wall arrival order (tie-break + socket-mode ordering)
    # host wall timestamp (perf_counter) of the ACCEPT: the start of the
    # submission-to-merge latency the obs layer resolves at commit
    wall_t: float = 0.0


class IngestQueue:
    """Bounded arrival queue for ONE open round plus a bounded pending
    buffer of early submissions. Thread-safe: transports submit from their
    own threads; the assembler consumes under the same lock."""

    def __init__(self, capacity: int = 1024, pending_capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.pending_capacity = max(pending_capacity, 0)
        self._cv = threading.Condition()
        self._open_round: int | None = None
        # the round an early push may target while NO round is open (the
        # server is mid-merge between close_round(r) and open_round(r+1)):
        # a client must not have to resubmit just because it raced the merge
        self._next_round: int | None = None
        self._invited: dict[int, int] = {}  # client_id -> cohort position
        self._arrivals: list[Arrival] = []
        self._seen: set[int] = set()
        self._closed = False
        # early submissions for round open+1: (client_id, latency_s) in
        # arrival order, deduped; drained into arrivals at the next open
        self._pending: list[tuple[int, float]] = []
        self._recv_counter = 0
        # optional accept hook (the service feeds its arrival-rate window);
        # called with n=1 under the queue lock — must be cheap and must not
        # call back into the queue
        self.on_accept = None
        # cumulative admission counters (metrics endpoint)
        self.accepted = 0
        self.buffered = 0
        self.rejected_full = 0
        self.rejected_dup = 0
        self.rejected_out_of_round = 0
        self.rejected_uninvited = 0
        self.rejected_closed = 0

    # -- round lifecycle (assembler side) ------------------------------------

    def open_round(self, rnd: int, invited_ids) -> None:
        """Open round `rnd` for the given cohort. Pending early submissions
        from invited clients are admitted immediately (recv order preserved);
        pending entries from clients NOT in this cohort stay parked for the
        round after (they pushed for "whatever opens next")."""
        with self._cv:
            if self._closed:
                raise RuntimeError("IngestQueue is closed")
            self._open_round = rnd
            self._next_round = rnd + 1
            self._invited = {int(c): i for i, c in enumerate(invited_ids)}
            self._arrivals = []
            self._seen = set()
            still_pending: list[tuple[int, float]] = []
            for cid, latency in self._pending:
                if cid in self._invited and cid not in self._seen:
                    self._admit(cid, latency)
                else:
                    still_pending.append((cid, latency))
            self._pending = still_pending
            self._cv.notify_all()

    def close_round(self) -> list[Arrival]:
        """Close the open round and return its arrivals (submission-order).
        Subsequent submissions naming the closed round are OUT_OF_ROUND."""
        with self._cv:
            out = list(self._arrivals)
            self._open_round = None
            self._invited = {}
            self._arrivals = []
            self._seen = set()
            return out

    def arrivals(self) -> list[Arrival]:
        """Snapshot of the open round's arrivals so far."""
        with self._cv:
            return list(self._arrivals)

    # graftlint: drain-point — the serving queue's sanctioned wait: the
    # assembler blocks HERE (wall-clock transports) for quorum or deadline
    def wait_for(self, count: int, timeout_s: float) -> list[Arrival]:
        """Block until >= `count` arrivals or `timeout_s` elapses; return
        the arrival snapshot. Wall-clock close for the socket transport —
        the in-process path closes on virtual latencies instead."""
        with self._cv:
            self._cv.wait_for(
                lambda: len(self._arrivals) >= count or self._closed,
                timeout=timeout_s,
            )
            return list(self._arrivals)

    def shutdown(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # -- submission (transport side) -----------------------------------------

    def submit(self, sub: Submission) -> str:
        """Admission decision for one submission (see module docstring for
        the rule order). Returns ACCEPTED/BUFFERED or a rejection reason.
        Every decision is a trace instant on the serve-ingest track, linked
        to the later merge span by the `submission` id (r<round>/c<cid>)."""
        status = self._decide(sub)
        if obtrace.get().enabled:
            # guard BEFORE building args: this is the admission hot path
            # (the ingest bench pushes ~1e5 submissions/s through it), and
            # an untraced server must pay one attribute check, not two
            # f-strings per message
            obtrace.instant(
                "serve-ingest", f"submit:{status}",
                submission=f"r{int(sub.round)}/c{int(sub.client_id)}",
                round=int(sub.round), client=int(sub.client_id))
        return status

    def _decide(self, sub: Submission) -> str:
        with self._cv:
            if self._closed:
                self.rejected_closed += 1
                return CLOSED
            cid = int(sub.client_id)
            if self._open_round is None or sub.round != self._open_round:
                if (self._next_round is not None
                        and sub.round == self._next_round):
                    # early push for the next round: park it, bounded
                    # (dup before full: a retry of an already-parked push is
                    # a DUPLICATE even when the buffer has no room left)
                    if any(c == cid for c, _ in self._pending):
                        self.rejected_dup += 1
                        return DUPLICATE
                    if len(self._pending) >= self.pending_capacity:
                        self.rejected_full += 1
                        return QUEUE_FULL
                    self._pending.append((cid, float(sub.latency_s)))
                    self.buffered += 1
                    return BUFFERED
                self.rejected_out_of_round += 1
                return OUT_OF_ROUND
            if cid not in self._invited:
                self.rejected_uninvited += 1
                return NOT_INVITED
            if cid in self._seen:
                self.rejected_dup += 1
                return DUPLICATE
            if len(self._arrivals) >= self.capacity:
                self.rejected_full += 1
                return QUEUE_FULL
            self._admit(cid, float(sub.latency_s))
            self._cv.notify_all()
            return ACCEPTED

    def _admit(self, cid: int, latency_s: float) -> None:
        """Record an accepted arrival (lock held)."""
        self._arrivals.append(
            Arrival(cid, latency_s, self._recv_counter, time.perf_counter()))
        self._recv_counter += 1
        self._seen.add(cid)
        self.accepted += 1
        if self.on_accept is not None:
            self.on_accept(1)

    # -- introspection --------------------------------------------------------

    def depth(self) -> int:
        """Open-round arrivals + parked early submissions (the 'queue
        depth' the metrics endpoint reports)."""
        with self._cv:
            return len(self._arrivals) + len(self._pending)

    def pending_snapshot(self) -> list[tuple[int, float]]:
        """Checkpointable view of the early-submission buffer."""
        with self._cv:
            return list(self._pending)

    def restore_pending(self, pending) -> None:
        """Re-seed the early-submission buffer from a checkpoint."""
        with self._cv:
            self._pending = [(int(c), float(s)) for c, s in pending]

    def counters(self) -> dict[str, int]:
        with self._cv:
            return {
                "accepted": self.accepted,
                "buffered": self.buffered,
                "rejected_full": self.rejected_full,
                "rejected_dup": self.rejected_dup,
                "rejected_out_of_round": self.rejected_out_of_round,
                "rejected_uninvited": self.rejected_uninvited,
                "rejected_closed": self.rejected_closed,
            }
