"""G010 flat-ravel-in-round-path.

Sketch-as-you-backprop's load-bearing promise (sketch/layerwise.py): on the
layerwise path the dense [d] gradient NEVER materializes — per-layer blocks
fold straight into the r x c table, and peak live memory is O(r*c) plus one
leaf instead of O(d) (+ the raveled copy + the [W, d] client stacks, the HBM
ceiling ravel_pytree used to pin). A casual `ravel_pytree(...)` added to the
round-path compiled scope re-introduces exactly that flat vector — silently,
since the result is numerically identical — so the flat boundary must be
DECLARED, not accidental.

Detection:

- any call resolving through the import table to
  `jax.flatten_util.ravel_pytree` (or anything else under
  `jax.flatten_util`), in the round-path compiled scope (modes/, sketch/,
  federated/engine.py — the same whole-module treatment G001/G009 use);
- unless an enclosing function carries `# graftlint: sketch-boundary`: the
  ravel path's own functions ARE the declared flat boundary
  (sketch_path="ravel" is the seed behavior and stays supported — the rule
  bans *undeclared* flat materialization, not the ravel path itself).

The `import` statement alone is not flagged (it moves no bytes); only the
call that materializes the flat vector is.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# round-path compiled scope: the modules whose functions may be (part of)
# the compiled round program — same scope G009 uses
_COMPILED_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/sketch/",
    f"{PACKAGE}/federated/engine.py",
)

_FLAT_PREFIX = "jax.flatten_util"


class FlatRavelInRoundPath(Rule):
    code = "G010"
    name = "flat-ravel-in-round-path"
    fixit = ("accumulate per-leaf instead (sketch/layerwise.py: "
             "accumulate_leaf/sketch_tree/apply_delta_tree), or — if this "
             "function IS the ravel path's declared flat boundary — mark "
             "its def with `# graftlint: sketch-boundary` and say why")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_COMPILED_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = src.resolve_dotted(node.func)
            if dotted is None or not dotted.startswith(f"{_FLAT_PREFIX}."):
                continue
            if src.in_sketch_boundary(node.lineno):
                continue
            out.append(self.violation(
                src, node,
                f"{dotted}() materializes the flat [d] vector in the "
                "round-path compiled scope outside the declared sketch "
                "boundary — the layerwise path exists so that vector "
                "never has to exist",
            ))
        return out
