"""G012 robust-order-sensitivity + G013 staleness-fold-boundary.

The repo's aggregation contract is LINEAR: client wires merge by the
ordered sum (csvec.merge_tables / modes.merge_partial_wires), and every
bit-parity pin — mesh == single-device, served == batch, split == fused —
rests on that one fp association. The Byzantine-robust merge
(--merge_policy trimmed|median) deliberately breaks linearity with order
statistics over the client-stacked tables, and it does so in exactly ONE
declared place: ``modes._robust_table_merge``, marked ``# graftlint:
robust-merge``. A sort/median/percentile over client data anywhere else in
parity scope is either a second, undeclared aggregation semantics (two
robust merges that disagree about tie-breaks silently un-pin the
mesh-shape invariance) or an accidental reassociation of the parity-pinned
reduce.

Detection, in the parity scope (modes/ + federated/engine.py):

- any call resolving through the import table to an order-statistics
  primitive — ``jnp.sort/argsort/partition/median/percentile/quantile/
  nanmedian``, ``lax.sort``, or their host-numpy twins — outside a
  function declared ``# graftlint: robust-merge``.
- any robust-merge declaration OUTSIDE ``modes/modes.py``: the boundary
  lives in exactly one sanctioned file, so a declaration elsewhere in
  parity scope (and the exemption it would grant) is itself a violation —
  which is also what catches the cross-file second-boundary case a
  per-file rule could not see.
- a SECOND robust-merge declaration in the same file: the boundary is "the
  one declared function"; a second declared sort site is a second
  aggregation semantics hiding under the first's exemption.

The quarantine's norm-median helpers (engine._masked_median) sort [W] norm
VECTORS — screening thresholds, not merged values; the one such site
carries an inline justification. sketch/ is deliberately out of scope: the
Count-Sketch estimator's per-row median (csvec) sorts over the r hash-row
axis, the estimator's own definition, not a client axis.

G013 is the same shape of contract for the buffered-ASYNC merge
(--serve_async): stale wire tables fold into the server table in exactly
ONE declared place — ``engine._stale_fold``, marked ``# graftlint:
staleness-fold`` — whose slot-ordered lax.scan IS the async mode's whole
numerical contract (fold order = slot order = a pure function of the
submission set; weights join the survivor normalization). Arithmetic over
``stale_*``-named values anywhere else in parity scope is a second,
undeclared fold site: two sites that disagree about order or weight
handling silently un-pin the async==sync bit-identity. Bare argument
FORWARDING (``_stale_fold(tbl, live, stale_tables, stale_weights)``, or
the keyword-forward through ``modes.merge_partial_wires(...)``) is
legal — the merge program has to hand the stack to a boundary; touching
the values outside one is not.

The async x robust COMPOSITION (the per-buffer robust merge) ties the two
rules together: stale wires are ALSO sanctioned inside the declared
robust-merge boundary, where they join the weighted order statistics of
the union stack — that is the one other place their semantics are pinned.
The converse does NOT hold: the staleness-fold boundary sanctions the
LINEAR slot-ordered scan only, so an order statistic smuggled into
``_stale_fold`` fires G012 with a message naming the seam (the weighted
forms live in the robust-merge boundary alone).
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# the parity-pinned merge scope: where client wires are reduced
_PARITY_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/federated/engine.py",
)

# the ONE file the robust-merge boundary may be declared in
_BOUNDARY_FILE = f"{PACKAGE}/modes/modes.py"

# order-statistics primitives (import-resolved): the moves only the
# declared boundary may make over client-stacked data. The weighted forms
# (the per-buffer robust merge: weighted trimmed mean / weighted median
# over the union stack) add searchsorted/lexsort — rank machinery a
# weighted median smuggled outside the boundary would reach for.
_ORDER_STATS = frozenset({
    "jax.numpy.sort", "jax.numpy.argsort", "jax.numpy.partition",
    "jax.numpy.argpartition", "jax.numpy.median", "jax.numpy.nanmedian",
    "jax.numpy.percentile", "jax.numpy.nanpercentile",
    "jax.numpy.quantile", "jax.numpy.nanquantile",
    "jax.numpy.searchsorted", "jax.numpy.lexsort",
    "jax.lax.sort", "jax.lax.sort_key_val",
    "numpy.sort", "numpy.argsort", "numpy.partition", "numpy.median",
    "numpy.nanmedian", "numpy.percentile", "numpy.quantile",
    "numpy.searchsorted", "numpy.lexsort",
})


class RobustOrderSensitivity(Rule):
    code = "G012"
    name = "robust-order-sensitivity"
    fixit = ("route order statistics over client wires through the ONE "
             "declared `# graftlint: robust-merge` boundary "
             "(modes._robust_table_merge) — or, for a screening median "
             "over norm vectors, justify the site inline with "
             "`# graftlint: disable=G012 — why`")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_PARITY_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        declared = [f for f in src.functions if f.robust_merge]
        in_boundary_file = src.rel == _BOUNDARY_FILE
        # the exemption is only honored where the boundary is sanctioned to
        # live; any declaration elsewhere is itself a violation (the
        # cross-file second-boundary case a per-file rule can't count)
        illegal = declared if not in_boundary_file else declared[1:]
        for extra in illegal:
            out.append(Violation(
                code=self.code, name=self.name, rel=src.rel,
                lineno=extra.def_lineno, col=0,
                message=(
                    f"robust-merge boundary declared at {extra.qualname} — "
                    f"the robust merge is ONE declared function in "
                    f"{_BOUNDARY_FILE}; another declaration is a second "
                    f"aggregation semantics hiding under the exemption"),
                fixit=("fold the order statistics into the existing "
                       "declared boundary (modes._robust_table_merge)"),
                line_text=src.line(extra.def_lineno),
                symbol=extra.qualname,
            ))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = src.resolve_dotted(node.func)
            if dotted is None or dotted not in _ORDER_STATS:
                continue
            if in_boundary_file and src.in_robust_merge(node.lineno):
                continue
            if src.in_staleness_fold(node.lineno):
                # the stale-fold seam is explicitly IN scope: the declared
                # staleness-fold boundary sanctions the LINEAR slot-ordered
                # scan, never order statistics — a sort smuggled into
                # _stale_fold is a robust merge hiding behind the wrong
                # boundary's exemption (the weighted order statistics of
                # the per-buffer robust merge live in the robust-merge
                # boundary alone)
                out.append(self.violation(
                    src, node,
                    f"{dotted}() inside the declared staleness-fold "
                    "boundary — the stale fold is a LINEAR slot-ordered "
                    "scan; weighted order statistics over stale wires "
                    "belong in the robust-merge boundary "
                    "(modes._robust_table_merge's union-stack form)"))
                continue
            out.append(self.violation(
                src, node,
                f"{dotted}() is an order statistic in parity scope outside "
                "the declared robust-merge boundary — sorting client data "
                "here either adds an undeclared aggregation semantics or "
                "reassociates the parity-pinned ordered sum"))
        return out


# the ONE file the staleness-fold boundary may be declared in
_STALE_BOUNDARY_FILE = f"{PACKAGE}/federated/engine.py"
# the async merge's stale-wire value names (the merge signature's stack
# args) — config scalars (stale_slots) and derived host metrics are not
# wire values and stay legal outside the boundary
_STALE_NAMES = frozenset({"stale_tables", "stale_weights"})
# the boundary ENTRY POINTS an attribute call may forward the stale stack
# into (the engine's `modes.merge_partial_wires(...)` shape); any other
# attribute call is arithmetic in disguise, not forwarding
_STALE_FORWARD_CALLEES = frozenset({
    "merge_partial_wires", "_robust_table_merge", "_stale_fold"})


class StalenessFoldBoundary(Rule):
    code = "G013"
    name = "staleness-fold-boundary"
    fixit = ("route every piece of arithmetic over stale_* wire values "
             "through the ONE declared `# graftlint: staleness-fold` "
             "boundary (engine._stale_fold) — callers may only FORWARD "
             "the stack to it")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_PARITY_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        declared = [f for f in src.functions if f.staleness_fold]
        in_boundary_file = src.rel == _STALE_BOUNDARY_FILE
        illegal = declared if not in_boundary_file else declared[1:]
        for extra in illegal:
            out.append(Violation(
                code=self.code, name=self.name, rel=src.rel,
                lineno=extra.def_lineno, col=0,
                message=(
                    f"staleness-fold boundary declared at {extra.qualname} "
                    f"— the stale fold is ONE declared function in "
                    f"{_STALE_BOUNDARY_FILE}; another declaration is a "
                    f"second fold semantics hiding under the exemption"),
                fixit=("fold the stale arithmetic into the existing "
                       "declared boundary (engine._stale_fold)"),
                line_text=src.line(extra.def_lineno),
                symbol=extra.qualname,
            ))
        # Name uses of stale_* values are legal in exactly three shapes:
        # inside the declared staleness-fold boundary, inside the declared
        # ROBUST-MERGE boundary (the per-buffer robust merge: stale slots
        # join the weighted order statistics there — the G012 boundary is
        # the one other sanctioned fold semantics), or as a bare argument
        # being FORWARDED toward a boundary: a plain Name call (the
        # historical `_stale_fold(...)` hand-off), or an ATTRIBUTE call
        # whose target IS one of the boundary entry points (the engine's
        # `modes.merge_partial_wires(...)` keyword-forward). A generic
        # attribute call is NOT forwarding — `jnp.average(stale_tables,
        # weights=stale_weights)` is a smuggled fold wearing a call's
        # clothes and must fire. Anything else — a BinOp, a compare, a
        # method call on the value, an index — is stale arithmetic
        # outside the boundaries.
        forwarded: set[int] = set()
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                pass  # plain-call forwarding (the historical shape)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _STALE_FORWARD_CALLEES):
                pass  # attribute-forward into a sanctioned boundary entry
            else:
                continue
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    forwarded.add(id(a))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Name):
                continue
            if node.id not in _STALE_NAMES:
                continue
            if isinstance(node.ctx, ast.Store):
                continue  # binding the incoming stack is not arithmetic
            if id(node) in forwarded:
                continue
            if in_boundary_file and src.in_staleness_fold(node.lineno):
                continue
            if (src.rel == _BOUNDARY_FILE
                    and src.in_robust_merge(node.lineno)):
                # the per-buffer robust merge: stale wires are sanctioned
                # inside the ONE declared robust-merge boundary, where
                # they join the weighted order statistics
                continue
            out.append(self.violation(
                src, node,
                f"`{node.id}` used outside the declared staleness-fold "
                "and robust-merge boundaries — stale wire values may only "
                "be FORWARDED to engine._stale_fold or "
                "modes._robust_table_merge; arithmetic on them here is a "
                "second, undeclared fold site (its order and weight "
                "handling are pinned nowhere)"))
        return out
