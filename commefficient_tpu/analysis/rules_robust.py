"""G012 robust-order-sensitivity.

The repo's aggregation contract is LINEAR: client wires merge by the
ordered sum (csvec.merge_tables / modes.merge_partial_wires), and every
bit-parity pin — mesh == single-device, served == batch, split == fused —
rests on that one fp association. The Byzantine-robust merge
(--merge_policy trimmed|median) deliberately breaks linearity with order
statistics over the client-stacked tables, and it does so in exactly ONE
declared place: ``modes._robust_table_merge``, marked ``# graftlint:
robust-merge``. A sort/median/percentile over client data anywhere else in
parity scope is either a second, undeclared aggregation semantics (two
robust merges that disagree about tie-breaks silently un-pin the
mesh-shape invariance) or an accidental reassociation of the parity-pinned
reduce.

Detection, in the parity scope (modes/ + federated/engine.py):

- any call resolving through the import table to an order-statistics
  primitive — ``jnp.sort/argsort/partition/median/percentile/quantile/
  nanmedian``, ``lax.sort``, or their host-numpy twins — outside a
  function declared ``# graftlint: robust-merge``.
- any robust-merge declaration OUTSIDE ``modes/modes.py``: the boundary
  lives in exactly one sanctioned file, so a declaration elsewhere in
  parity scope (and the exemption it would grant) is itself a violation —
  which is also what catches the cross-file second-boundary case a
  per-file rule could not see.
- a SECOND robust-merge declaration in the same file: the boundary is "the
  one declared function"; a second declared sort site is a second
  aggregation semantics hiding under the first's exemption.

The quarantine's norm-median helpers (engine._masked_median) sort [W] norm
VECTORS — screening thresholds, not merged values; the one such site
carries an inline justification. sketch/ is deliberately out of scope: the
Count-Sketch estimator's per-row median (csvec) sorts over the r hash-row
axis, the estimator's own definition, not a client axis.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# the parity-pinned merge scope: where client wires are reduced
_PARITY_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/federated/engine.py",
)

# the ONE file the robust-merge boundary may be declared in
_BOUNDARY_FILE = f"{PACKAGE}/modes/modes.py"

# order-statistics primitives (import-resolved): the moves only the
# declared boundary may make over client-stacked data
_ORDER_STATS = frozenset({
    "jax.numpy.sort", "jax.numpy.argsort", "jax.numpy.partition",
    "jax.numpy.argpartition", "jax.numpy.median", "jax.numpy.nanmedian",
    "jax.numpy.percentile", "jax.numpy.nanpercentile",
    "jax.numpy.quantile", "jax.numpy.nanquantile",
    "jax.lax.sort", "jax.lax.sort_key_val",
    "numpy.sort", "numpy.argsort", "numpy.partition", "numpy.median",
    "numpy.nanmedian", "numpy.percentile", "numpy.quantile",
})


class RobustOrderSensitivity(Rule):
    code = "G012"
    name = "robust-order-sensitivity"
    fixit = ("route order statistics over client wires through the ONE "
             "declared `# graftlint: robust-merge` boundary "
             "(modes._robust_table_merge) — or, for a screening median "
             "over norm vectors, justify the site inline with "
             "`# graftlint: disable=G012 — why`")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_PARITY_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        declared = [f for f in src.functions if f.robust_merge]
        in_boundary_file = src.rel == _BOUNDARY_FILE
        # the exemption is only honored where the boundary is sanctioned to
        # live; any declaration elsewhere is itself a violation (the
        # cross-file second-boundary case a per-file rule can't count)
        illegal = declared if not in_boundary_file else declared[1:]
        for extra in illegal:
            out.append(Violation(
                code=self.code, name=self.name, rel=src.rel,
                lineno=extra.def_lineno, col=0,
                message=(
                    f"robust-merge boundary declared at {extra.qualname} — "
                    f"the robust merge is ONE declared function in "
                    f"{_BOUNDARY_FILE}; another declaration is a second "
                    f"aggregation semantics hiding under the exemption"),
                fixit=("fold the order statistics into the existing "
                       "declared boundary (modes._robust_table_merge)"),
                line_text=src.line(extra.def_lineno),
                symbol=extra.qualname,
            ))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = src.resolve_dotted(node.func)
            if dotted is None or dotted not in _ORDER_STATS:
                continue
            if in_boundary_file and src.in_robust_merge(node.lineno):
                continue
            out.append(self.violation(
                src, node,
                f"{dotted}() is an order statistic in parity scope outside "
                "the declared robust-merge boundary — sorting client data "
                "here either adds an undeclared aggregation semantics or "
                "reassociates the parity-pinned ordered sum"))
        return out
