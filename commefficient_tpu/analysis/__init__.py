"""graftlint — project-aware static analysis for the commefficient-tpu repo.

Four PRs of growth accumulated a set of load-bearing invariants that lived
only as reviewer lore; this package enforces them mechanically, as an AST
pass over the source (no imports, no jax, runs anywhere in < 10 s):

====  =========================================  ================================
code  name                                       contract it enforces
====  =========================================  ================================
G001  host-sync-in-round-path                    no hidden host sync (device_get
                                                 / .item() / np.asarray / float()
                                                 on traced values) on the round
                                                 dispatch path outside declared
                                                 drain points
G002  unordered-reduction-in-parity-scope        the sketch-merge bit-parity rule:
                                                 no psum/psum_scatter/all_reduce
                                                 in parity-pinned modules — the
                                                 cross-device merge is all_gather
                                                 + ORDERED sum (csvec.merge_tables)
G003  reserved-leaf-access                       the `_valid` reserved batch leaf
                                                 is consumed only via
                                                 engine.split_valid (and the
                                                 faults module that injects it)
G004  raw-checkpoint-write                       checkpoint dirs are written only
                                                 through utils/checkpoint.py's
                                                 atomic staging+rename+manifest
                                                 helpers
G005  donation-after-use                         arguments listed in a jit's
                                                 donate_argnums are dead after
                                                 the call — referencing them
                                                 reads deleted buffers on TPU
G006  rng-key-reuse                              a PRNG key feeds ONE consumer;
                                                 derive with split/fold_in before
                                                 the next draw
G007  blocking-call-on-dispatch-thread           no time.sleep / sync file IO /
                                                 subprocess reachable from the
                                                 runner's prefetch/dispatch path
G008  unvalidated-config-read                    engine/runner code reads only
                                                 args.<flag> names registered
                                                 through utils/config.py
G009  obs-call-in-compiled-scope                 tracing/metrics are host-only:
                                                 no obs API call (span/instant,
                                                 counter.inc, registry access)
                                                 inside jit/shard_map bodies in
                                                 the parity modules
G010  flat-ravel-in-round-path                   the dense [d] gradient never
                                                 materializes by accident:
                                                 ravel_pytree/jax.flatten_util
                                                 calls in the round-path
                                                 compiled scope only inside
                                                 functions declared
                                                 `# graftlint: sketch-boundary`
G011  wire-bytes-in-compiled-scope               untrusted wire frame bytes
                                                 (transport payload fields)
                                                 reach compiled scope only
                                                 through the one declared
                                                 deserialization boundary,
                                                 serve.ingest.validate_payload
                                                 (`# graftlint:
                                                 payload-boundary`)
G012  robust-order-sensitivity                   order statistics (sort/
                                                 median/percentile) over
                                                 client wires in parity scope
                                                 only inside the ONE declared
                                                 robust-merge boundary,
                                                 modes._robust_table_merge
                                                 (`# graftlint: robust-merge`)
G013  staleness-fold-boundary                    staleness-weighted arithmetic
                                                 over stale wires only inside
                                                 the declared staleness-fold
                                                 boundary (`# graftlint:
                                                 staleness-fold`)
G014  ledger-write-outside-commit                the durable round ledger is
                                                 appended only at the declared
                                                 commit site (`# graftlint:
                                                 ledger-commit`)
G015  blocking-call-in-event-loop                the socket reactor thread
                                                 never blocks: no sleeps /
                                                 sync IO / lock waits in the
                                                 event-loop dispatch scope
G016  per-submission-copy-in-fastpath            the zero-copy fast path
                                                 touches table bytes ONCE: no
                                                 base64 decode, per-item
                                                 np.stack, or frombuffer().
                                                 copy() in fast-path modules
                                                 outside the ONE declared
                                                 ring-slot write
                                                 (`# graftlint: ring-write`)
G017  fork-unsafe-import-in-shard-worker         the spawned shard-worker /
                                                 loadgen import chain stays
                                                 numpy/stdlib-only: no
                                                 module-level import (direct
                                                 or transitive, package
                                                 __init__s included) of jax
                                                 or other accelerator-
                                                 runtime packages from the
                                                 worker-entry modules
G018  lock-order-inversion                       the lock-acquisition graph
                                                 across serve/runner/obs
                                                 (B taken while A held,
                                                 interprocedurally) has no
                                                 cycles; `# graftlint:
                                                 lock-order <name>` declares
                                                 the sanctioned global order
G019  unlocked-shared-state                      an attribute mutated from
                                                 two thread roots (derived
                                                 from Thread(target=...) +
                                                 public entry points) is
                                                 mutated only under a common
                                                 declared lock, or carries
                                                 `# graftlint: lockfree <why>`
G020  signal-unsafe-handler                      functions reachable from
                                                 signal.signal(...) never
                                                 acquire non-reentrant locks,
                                                 open files, or call the
                                                 buffered JSONL sinks (the
                                                 instant_signal_safe
                                                 discipline, machine-checked)
====  =========================================  ================================

Run it:

    python -m commefficient_tpu.analysis commefficient_tpu/ [--json]
    scripts/lint.sh          # graftlint + ruff + mypy, LINT_SKIP=1 to skip

Suppress a site:

    x = np.asarray(dev)  # graftlint: disable=G001 — host-side by construction

(the justification text after the code is free-form but encouraged; an
unknown rule code in a directive is itself an error, G000). Functions that
ARE the sanctioned host-sync boundary carry `# graftlint: drain-point` on
the line above their `def` — G001/G007 go silent for the whole function.
Grandfathered sites live in `analysis/baseline.json` (`--write-baseline`
regenerates it; stale entries are reported so the baseline only shrinks).

Adding a rule (~50 LoC): subclass `core.Rule` in a `rules_*` module, give it
`code`/`name`/`applies()`/`check()`, append it to `ALL_RULES` below, add a
violating + conforming fixture pair under tests/fixtures/lint/ and a line to
the README table. Fixture snippets impersonate an in-scope module with a
`# graftlint: module=commefficient_tpu/...` directive.
"""

from __future__ import annotations

from .core import Analyzer, Rule, SourceFile, Violation
from .rules_config import UnvalidatedConfigRead
from .rules_dataflow import DonationAfterUse, RngKeyReuse
from .rules_fastpath import PerSubmissionCopyInFastpath
from .rules_io import RawCheckpointWrite
from .rules_ledger import LedgerWriteOutsideCommit
from .rules_obs import ObsCallInCompiledScope
from .rules_parity import ReservedLeafAccess, UnorderedReduction
from .rules_procsafe import ForkUnsafeImportInShardWorker
from .rules_reactor import BlockingCallInEventLoop
from .rules_robust import (RobustOrderSensitivity,
                           StalenessFoldBoundary)
from .rules_signal import SignalUnsafeHandler
from .rules_sketch import FlatRavelInRoundPath
from .rules_sync import BlockingCallOnDispatchThread, HostSyncInRoundPath
from .rules_threads import LockOrderInversion, UnlockedSharedState
from .rules_wire import WireBytesInCompiledScope

ALL_RULES: tuple[type[Rule], ...] = (
    HostSyncInRoundPath,
    UnorderedReduction,
    ReservedLeafAccess,
    RawCheckpointWrite,
    DonationAfterUse,
    RngKeyReuse,
    BlockingCallOnDispatchThread,
    UnvalidatedConfigRead,
    ObsCallInCompiledScope,
    FlatRavelInRoundPath,
    WireBytesInCompiledScope,
    RobustOrderSensitivity,
    StalenessFoldBoundary,
    LedgerWriteOutsideCommit,
    BlockingCallInEventLoop,
    PerSubmissionCopyInFastpath,
    ForkUnsafeImportInShardWorker,
    LockOrderInversion,
    UnlockedSharedState,
    SignalUnsafeHandler,
)

RULE_CODES: tuple[str, ...] = tuple(r.code for r in ALL_RULES)

__all__ = [
    "ALL_RULES",
    "RULE_CODES",
    "Analyzer",
    "Rule",
    "SourceFile",
    "Violation",
]
