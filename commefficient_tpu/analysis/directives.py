"""`# graftlint:` comment directives.

Three forms, all line-anchored comments:

    # graftlint: disable=G001            suppress these codes on this line
    # graftlint: disable=G001,G004 — why (or the line directly below)
    # graftlint: disable-file=G008       suppress for the whole file
    # graftlint: drain-point             on/above a `def`: this function IS a
                                         sanctioned host-sync / blocking-IO
                                         boundary (G001/G007 exempt)
    # graftlint: sketch-boundary         on/above a `def`: this function IS a
                                         declared flat/ravel boundary of the
                                         sketch path (G010 exempt) — the
                                         ravel-path code that concatenates the
                                         gradient ON PURPOSE
    # graftlint: robust-merge            on/above a `def`: this function IS
                                         the declared robust-merge boundary
                                         (G012 exempt) — the ONE place order
                                         statistics may run over
                                         client-stacked wires in parity scope
    # graftlint: ledger-commit           on/above a `def`: this function IS
                                         the declared round-ledger append
                                         site (G014 exempt) — the ONE place
                                         in runner/+federated/ that may
                                         append to the durable ledger (the
                                         commit boundary)
    # graftlint: ring-write              on/above a `def`: this function IS
                                         the declared ring-slot write site
                                         (G016 exempt) — the ONE place in
                                         fast-path scope that may copy a
                                         per-submission table (into its
                                         pinned ring slot)
    # graftlint: lock-order <name>       on/above a lock-binding assignment
                                         (`self._cv = threading.Condition()`):
                                         gives the lock a name in the declared
                                         GLOBAL acquisition order — names sort
                                         lexicographically (the convention is
                                         an `l0-`/`l1-`/... prefix), and G018
                                         sanctions an edge A->B exactly when
                                         both locks are named and
                                         name(A) < name(B)
    # graftlint: lockfree <why>          on/above an assignment to an
                                         attribute: this shared attribute is
                                         DELIBERATELY mutated without a lock
                                         (GIL-atomic flag, monotonic counter)
                                         — G019 exempt; the <why> is required
                                         prose, reviewed like a disable
                                         justification
    # graftlint: module=<relpath>        fixture support: analyze this file as
                                         if it lived at <relpath> (scoped rules
                                         fire on test snippets)

Anything after an `—`/`--`/`#` separator in a disable is a free-form
justification. A directive naming an unknown rule code, or an unknown
directive verb, is itself reported (code G000) — suppressions must name a
valid rule code or they rot silently when rules are renumbered.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize

# the pseudo-code under which malformed directives are reported
DIRECTIVE_ERROR_CODE = "G000"

_DIRECTIVE_RE = re.compile(r"#\s*graftlint:\s*(?P<body>[^#]*)")
_CODE_RE = re.compile(r"^G\d{3}$")
# separators that end the code list and start a free-form justification
_JUSTIFICATION_SPLIT = re.compile(r"\s+(?:—|--)\s+")
# a declared lock-order name: one token, lexicographically comparable
_ORDER_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


@dataclasses.dataclass
class Directives:
    """Parsed per-file directive state (see module docstring)."""

    # lineno -> set of codes disabled on that line
    line_disables: dict[int, set[str]]
    # codes disabled for the entire file
    file_disables: set[str]
    # linenos carrying a drain-point marker
    drain_linenos: set[int]
    # linenos carrying a sketch-boundary marker (G010's sanctioned ravel
    # sites — the declared flat boundary of the sketch path)
    sketch_boundary_linenos: set[int]
    # linenos carrying a payload-boundary marker (G011's sanctioned wire
    # deserialization sites — serve.ingest.validate_payload)
    payload_boundary_linenos: set[int]
    # linenos carrying a robust-merge marker (G012's sanctioned order-
    # statistics site — modes._robust_table_merge)
    robust_merge_linenos: set[int]
    # linenos carrying a staleness-fold marker (G013's sanctioned
    # staleness-weighted fold site — engine._stale_fold)
    staleness_fold_linenos: set[int]
    # linenos carrying a ledger-commit marker (G014's sanctioned round-
    # ledger append site — FederatedSession._publish_round_obs)
    ledger_commit_linenos: set[int]
    # linenos carrying a ring-write marker (G016's sanctioned per-
    # submission copy site — serve.ring.RingSlot.write)
    ring_write_linenos: set[int]
    # lineno -> declared lock-order name (G018's sanctioned global order;
    # names compare lexicographically)
    lock_order_names: dict[int, str]
    # linenos carrying a lockfree marker (G019's declared deliberately-
    # unlocked shared attributes)
    lockfree_linenos: set[int]
    # fixture impersonation path, or None
    module_override: str | None
    # (lineno, message) for malformed directives — surfaced as G000
    errors: list[tuple[int, str]]

    def disabled(self, code: str, lineno: int) -> bool:
        """A violation at `lineno` is suppressed by a disable on the same
        line or on the line directly above it (comment-above style)."""
        if code in self.file_disables:
            return True
        for ln in (lineno, lineno - 1):
            if code in self.line_disables.get(ln, ()):
                return True
        return False


def _parse_codes(arg: str, lineno: int, valid_codes: frozenset[str],
                 errors: list[tuple[int, str]]) -> set[str]:
    codes: set[str] = set()
    # strip a trailing justification ("disable=G001 — host-side stacking")
    arg = _JUSTIFICATION_SPLIT.split(arg, maxsplit=1)[0].strip()
    for raw in arg.split(","):
        code = raw.strip()
        if not code:
            continue
        if not _CODE_RE.match(code) or code not in valid_codes:
            errors.append((
                lineno,
                f"unknown rule code {code!r} in graftlint directive "
                f"(valid: {', '.join(sorted(valid_codes))})",
            ))
            continue
        codes.add(code)
    return codes


def _comments(text: str) -> list[tuple[int, str]]:
    """(lineno, comment_text) for every real COMMENT token — docstrings and
    string literals that merely MENTION `# graftlint:` never parse as
    directives."""
    out: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except tokenize.TokenError:  # pragma: no cover — ast.parse catches first
        pass
    return out


def parse(text: str, valid_codes: frozenset[str]) -> Directives:
    d = Directives(
        line_disables={}, file_disables=set(), drain_linenos=set(),
        sketch_boundary_linenos=set(), payload_boundary_linenos=set(),
        robust_merge_linenos=set(), staleness_fold_linenos=set(),
        ledger_commit_linenos=set(), ring_write_linenos=set(),
        lock_order_names={}, lockfree_linenos=set(),
        module_override=None, errors=[],
    )
    for lineno, line in _comments(text):
        m = _DIRECTIVE_RE.search(line)
        if m is None:
            continue
        body = m.group("body").strip()
        verb, has_eq, arg = body.partition("=")
        raw_verb = verb.strip()
        # a justification may trail the verb itself ("drain-point — why")
        verb = _JUSTIFICATION_SPLIT.split(raw_verb, maxsplit=1)[0].strip()
        if verb == "disable" and has_eq:
            codes = _parse_codes(arg, lineno, valid_codes, d.errors)
            if codes:
                d.line_disables.setdefault(lineno, set()).update(codes)
        elif verb == "disable-file" and has_eq:
            d.file_disables.update(
                _parse_codes(arg, lineno, valid_codes, d.errors))
        elif verb == "drain-point" and not has_eq:
            d.drain_linenos.add(lineno)
        elif verb == "sketch-boundary" and not has_eq:
            d.sketch_boundary_linenos.add(lineno)
        elif verb == "payload-boundary" and not has_eq:
            d.payload_boundary_linenos.add(lineno)
        elif verb == "robust-merge" and not has_eq:
            d.robust_merge_linenos.add(lineno)
        elif verb == "staleness-fold" and not has_eq:
            d.staleness_fold_linenos.add(lineno)
        elif verb == "ledger-commit" and not has_eq:
            d.ledger_commit_linenos.add(lineno)
        elif verb == "ring-write" and not has_eq:
            d.ring_write_linenos.add(lineno)
        elif verb.split(None, 1)[0:1] == ["lock-order"] and not has_eq:
            # "lock-order <name>": the name is one token; what follows is
            # free-form (same convention as a disable justification)
            words = verb.split()
            if len(words) < 2 or not _ORDER_NAME_RE.match(words[1]):
                d.errors.append((
                    lineno,
                    "lock-order directive needs a name token "
                    "([A-Za-z0-9_.-]+): `# graftlint: lock-order l0-queue`",
                ))
            else:
                d.lock_order_names[lineno] = words[1]
        elif verb.split(None, 1)[0:1] == ["lockfree"] and not has_eq:
            # "lockfree <why>": the why is required prose — an undocumented
            # lockfree claim is exactly the rot this directive exists to
            # prevent. Checked against raw_verb: a why introduced with the
            # `—` justification separator still counts.
            if len(raw_verb.split(None, 1)) < 2:
                d.errors.append((
                    lineno,
                    "lockfree directive needs a justification: "
                    "`# graftlint: lockfree monotonic counter, GIL-atomic`",
                ))
            else:
                d.lockfree_linenos.add(lineno)
        elif verb == "module" and has_eq:
            d.module_override = arg.strip()
        elif not verb:
            d.errors.append((lineno, "empty graftlint directive"))
        else:
            d.errors.append((
                lineno,
                f"unknown graftlint directive {verb!r} "
                "(expected disable/disable-file/drain-point/"
                "sketch-boundary/payload-boundary/robust-merge/"
                "staleness-fold/ledger-commit/ring-write/lock-order/"
                "lockfree/module)",
            ))
    return d
