"""Grandfathered-violation baseline (`analysis/baseline.json`).

An entry matches a violation by (rel path, code, stripped line text) — NOT
by line number, so unrelated edits above a grandfathered site don't
invalidate it, while any edit to the offending line itself (or a new copy of
the pattern elsewhere in the file beyond the granted count) resurfaces the
violation. Entries that matched nothing are reported as stale: the baseline
is designed to only ever shrink. The acceptance bar for this repo is that
G002/G003/G004 (parity, reserved-leaf, raw-checkpoint-write) carry ZERO
baseline entries — those contracts admit no grandfathering.
"""

from __future__ import annotations

import collections
import json
import os
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover — type-only import cycle guard
    from .core import Violation

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


class Baseline:
    def __init__(self, entries: list[dict[str, str]], path: str | None = None):
        self.path = path
        self.entries = entries
        # (rel, code, line_text) -> granted count; consumed by matches()
        self._budget: collections.Counter[tuple[str, str, str]] = (
            collections.Counter(self._key(e) for e in entries))
        self._used: collections.Counter[tuple[str, str, str]] = (
            collections.Counter())

    @staticmethod
    def _key(entry: dict[str, str]) -> tuple[str, str, str]:
        return (entry["path"], entry["code"], entry["line"].strip())

    @classmethod
    def empty(cls) -> "Baseline":
        return cls([])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([], path=path)
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("entries", [])
        for e in entries:
            missing = {"path", "code", "line"} - set(e)
            if missing:
                raise ValueError(
                    f"baseline {path}: entry {e!r} missing {sorted(missing)}")
        return cls(entries, path=path)

    def matches(self, v: "Violation") -> bool:
        key = (v.rel, v.code, v.line_text.strip())
        if self._used[key] < self._budget[key]:
            self._used[key] += 1
            return True
        return False

    def stale(self) -> list[dict[str, str]]:
        """Entries whose budget was never (fully) consumed this run."""
        out: list[dict[str, str]] = []
        leftover = {
            k: self._budget[k] - self._used[k]
            for k in self._budget if self._budget[k] > self._used[k]
        }
        for key, n in sorted(leftover.items()):
            out.extend(
                [{"path": key[0], "code": key[1], "line": key[2]}] * n)
        return out

    @staticmethod
    def write(path: str, violations: list["Violation"]) -> None:
        """Regenerate a baseline from the current findings. Every entry
        should carry a `why` a human wrote — the writer seeds it with the
        enclosing symbol so a naked regeneration is at least attributable."""
        entries = [
            {
                "path": v.rel, "code": v.code, "line": v.line_text.strip(),
                "why": f"grandfathered in {v.symbol} — justify or fix",
            }
            for v in sorted(
                violations, key=lambda v: (v.rel, v.lineno, v.code))
        ]
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=2)
            f.write("\n")
