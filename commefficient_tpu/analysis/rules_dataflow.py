"""G005 donation-after-use and G006 rng-key-reuse — the two rules that need
the lightweight intra-module dataflow pass (analysis/dataflow.py).

G005: an argument listed in `jax.jit(..., donate_argnums=...)` hands its
buffer to XLA — on TPU the array is DELETED the moment the call is traced,
and any later host read raises "Array has been deleted" (or worse, on
backends that alias silently, reads the output's bytes). CPU ignores
donation, which is exactly why tests never catch it — the lint has to. The
pass registers jitted callables assigned to module/class names (literal
donate_argnums, plus the project's `_state_donation()` helper, which returns
`(0,)` or `()` — treated as donating 0, its armed case), then walks each
function for loads of a donated argument after the donating call with no
intervening rebind.

G006: a threefry PRNG key feeds ONE consumer. Tracked per function: names
bound from `jax.random.PRNGKey(...)`, `fold_in(...)`, or tuple-unpacked
`split(...)`; consumers are `jax.random.<draw>(key, ...)` and
`jax.random.split(key, ...)` (official guidance: a key is dead after you
split it). `fold_in(key, i)` is derivation, not consumption — folding the
same parent with distinct ints is the sanctioned fan-out pattern
(engine._dp_noise_agg). A draw from a loop-invariant key inside a for/while
also flags: it reuses the key every iteration.
"""

from __future__ import annotations

import ast

from . import dataflow
from .core import Rule, SourceFile, Violation


class DonationAfterUse(Rule):
    code = "G005"
    name = "donation-after-use"
    fixit = ("use the jitted call's RETURN value instead of the donated "
             "input (the buffer is dead), or drop the argument from "
             "donate_argnums if it must stay readable")

    def check(self, src: SourceFile) -> list[Violation]:
        registry = self._donating_callables(src)
        if not registry:
            return []
        out: list[Violation] = []
        for func in self._functions(src):
            out.extend(self._check_function(src, func, registry))
        return out

    def _functions(self, src: SourceFile) -> list[ast.AST]:
        return [node for node in ast.walk(src.tree)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _donating_callables(self, src: SourceFile) -> dict[str, tuple[int, ...]]:
        """key ('step' / 'self._step') -> donated positional indices, from
        `<key> = jax.jit(fn, donate_argnums=...)` assignments anywhere in
        the module."""
        registry: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            key = dataflow.assign_target_key(node.targets[0])
            if key is None or not isinstance(node.value, ast.Call):
                continue
            dotted = src.resolve_dotted(node.value.func)
            if dotted not in ("jax.jit", "jax.pjit", "jax.jit.jit"):
                continue
            donated = self._donated_indices(src, node.value)
            if donated:
                registry[key] = donated
        return registry

    def _donated_indices(self, src: SourceFile,
                         call: ast.Call) -> tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            lit = dataflow.int_or_tuple_literal(kw.value)
            if lit is not None:
                return lit
            # project-aware: FederatedSession._state_donation() returns
            # (0,) when donation is armed and () otherwise — lint for the
            # armed case, the one that deletes buffers on real hardware
            if isinstance(kw.value, ast.Call):
                helper = src.resolve_dotted(kw.value.func)
                if helper and helper.rsplit(".", 1)[-1].endswith(
                        "_state_donation"):
                    return (0,)
        return ()

    def _check_function(self, src: SourceFile, func: ast.AST,
                        registry: dict[str, tuple[int, ...]]) -> list[Violation]:
        events = dataflow.name_events(func)
        # the canonical donation idiom `state, _, _ = step(state, ...)`
        # rebinds the donated name in the SAME statement — map each call to
        # the names its enclosing assignment rebinds, since those Store
        # events textually precede the call's end
        rebinds: dict[ast.Call, set[str]] = {}
        for stmt in dataflow.walk_in_function(func):
            if not isinstance(stmt, ast.Assign):
                continue
            names = set()
            for tgt in stmt.targets:
                for t in ast.walk(tgt):
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            for sub in ast.walk(stmt.value):
                if isinstance(sub, ast.Call):
                    rebinds[sub] = names
        out: list[Violation] = []
        for node in dataflow.walk_in_function(func):
            if not isinstance(node, ast.Call):
                continue
            key = dataflow.call_target_key(node.func)
            if key is None or key not in registry:
                continue
            end = dataflow.node_end(node)
            for idx in registry[key]:
                if idx >= len(node.args):
                    continue
                arg = node.args[idx]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebinds.get(node, ()):
                    continue  # rebound by the call's own assignment
                # first event on this name after the donating call decides:
                # Load -> reads a deleted buffer; Store -> rebound, safe
                for ev in events:
                    if ev.name != arg.id or ev.pos <= end:
                        continue
                    if ev.is_store:
                        break
                    out.append(self.violation(
                        src, ev.node,
                        f"`{arg.id}` was donated to `{key}` (donate_argnums "
                        f"includes {idx}) at line {node.lineno} and is "
                        "referenced afterwards — its buffer is deleted on "
                        "TPU"))
                    break
        return out


# jax.random draws that consume a key (split included: a key is dead after
# splitting; fold_in is derivation and deliberately absent)
_CONSUMERS = frozenset({
    "split", "normal", "uniform", "bernoulli", "randint", "bits",
    "truncated_normal", "categorical", "choice", "permutation", "gumbel",
    "exponential", "laplace", "logistic", "poisson", "gamma", "beta",
    "dirichlet", "rademacher", "cauchy", "multivariate_normal", "t",
    "loggamma", "rayleigh", "maxwell", "ball", "orthogonal", "binomial",
    "geometric", "chisquare", "f", "generalized_normal", "triangular",
    "wald", "weibull_min",
})
_PRODUCERS = frozenset({"PRNGKey", "key", "fold_in", "split", "clone"})


class RngKeyReuse(Rule):
    code = "G006"
    name = "rng-key-reuse"
    fixit = ("derive fresh keys first: `k1, k2 = jax.random.split(key)` (or "
             "fold_in with distinct ints), one consumer per key")

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_function(src, node))
        return out

    def _check_function(self, src: SourceFile,
                        func: ast.AST) -> list[Violation]:
        key_names = self._key_bindings(src, func)
        if not key_names:
            return []
        loops = dataflow.loop_spans(func)
        # per name: position of its last binding, and of its consumption
        consumed_at: dict[str, dataflow.Pos] = {}
        out: list[Violation] = []
        events = self._ordered_events(src, func)
        for pos, kind, name, node in events:
            if kind == "store":
                consumed_at.pop(name, None)
                continue
            if name not in key_names:
                continue
            born = key_names[name]
            if name in consumed_at:
                out.append(self.violation(
                    src, node,
                    f"PRNG key `{name}` already fed a consumer at line "
                    f"{consumed_at[name][0]} — reusing it correlates the "
                    "two streams"))
                continue
            if (dataflow.inside_any(pos, loops)
                    and not dataflow.inside_any(born, loops)):
                out.append(self.violation(
                    src, node,
                    f"PRNG key `{name}` is consumed inside a loop but bound "
                    "outside it — every iteration draws from the same key"))
                continue
            consumed_at[name] = pos
        return out

    def _key_bindings(self, src: SourceFile,
                      func: ast.AST) -> dict[str, dataflow.Pos]:
        """name -> binding position, for names bound from a key-producing
        jax.random call (PRNGKey/fold_in/key, or tuple-unpacked split) —
        plus every function parameter: a parameter consumed twice is reuse
        no matter how the key arrived."""
        keys: dict[str, dataflow.Pos] = {}
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = func.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                keys[arg.arg] = (func.lineno, func.col_offset)
        for node in dataflow.walk_in_function(func):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            value, target = node.value, node.targets[0]
            if not isinstance(value, ast.Call):
                continue
            fn = self._random_fn(src, value.func)
            if fn is None or fn not in _PRODUCERS:
                continue
            if isinstance(target, ast.Name) and fn != "split":
                keys[target.id] = dataflow.node_pos(target)
            elif isinstance(target, ast.Tuple) and fn == "split":
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        keys[elt.id] = dataflow.node_pos(elt)
        return keys

    def _ordered_events(self, src: SourceFile, func: ast.AST) -> list[
            tuple[dataflow.Pos, str, str, ast.AST]]:
        """(pos, 'store'|'consume', name, node) in source order: stores of
        any name, plus key-consuming jax.random calls on Name arguments."""
        events: list[tuple[dataflow.Pos, str, str, ast.AST]] = []
        for node in dataflow.walk_in_function(func):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                events.append(
                    (dataflow.node_pos(node), "store", node.id, node))
            elif isinstance(node, ast.Call):
                fn = self._random_fn(src, node.func)
                if fn in _CONSUMERS and node.args and isinstance(
                        node.args[0], ast.Name):
                    events.append((dataflow.node_pos(node), "consume",
                                   node.args[0].id, node))
        events.sort(key=lambda e: e[0])
        return events

    @staticmethod
    def _random_fn(src: SourceFile, func: ast.expr) -> str | None:
        """'normal' for a call whose dotted target resolves into
        jax.random (jax.random.normal, jrandom.normal, `from jax.random
        import normal`)."""
        dotted = src.resolve_dotted(func)
        if dotted is None:
            return None
        head, _, last = dotted.rpartition(".")
        if head.endswith("random") and ("jax" in head or head == "random"):
            return last
        if head == "" and dotted in ("PRNGKey", "fold_in"):
            return dotted
        return None
