"""G011 wire-bytes-in-compiled-scope.

The wire-payload round (serve/ ``--serve_payload sketch``) makes the merge
path a consumer of UNTRUSTED input: every submission's frame — base64 data,
length prefix, checksum, schema fields — arrives from a peer the server
does not control. The repo's defense is a single choke point:
``serve.ingest.validate_payload`` (declared with ``# graftlint:
payload-boundary``) is the ONE place wire bytes are deserialized, screened
(schema, dtype/shape, length, checksum, non-finite, sketch-space L2), and
turned into a host ndarray the engine may consume. Any other route from
frame bytes to the compiled round program silently reopens the injection
classes the gauntlet exists to close: a crafted length prefix reading past
a buffer, a stale-schema table misinterpreted shapewise, a NaN bomb
reaching the merge.

Detection, in the wire + compiled scope (serve/, sketch/, modes/,
federated/):

- any call resolving through the import table to the frame DECODING
  primitives — ``base64.b64decode`` or ``np.frombuffer`` — outside a
  function declared ``# graftlint: payload-boundary``. These two are how
  frame bytes become arrays; everything downstream of the boundary works
  on validated ndarrays and never needs them.
- any call resolving into ``jax.*`` (the compiled scope's front door) with
  an argument expression that reads a ``.payload`` attribute — the frame
  as the transport carries it, flowing into compiled scope without the
  gauntlet.

The client-side ENCODER (sketch/payload.py encode_frame: b64encode,
tobytes) is not flagged — serialization of bytes the process itself
produced moves no untrusted data. The chaos injector
(resilience/faults.py) decodes frames it is about to damage; it lives
outside this rule's scope and feeds the transport, not the engine.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# the wire + compiled scope: where frame bytes live (serve/) and where they
# must never arrive unvalidated (the round-path compiled modules)
_WIRE_SCOPE = (
    f"{PACKAGE}/serve/",
    f"{PACKAGE}/sketch/",
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/federated/",
)

# frame bytes -> array primitives: the moves only the boundary may make
_DECODERS = ("base64.b64decode", "numpy.frombuffer")


class WireBytesInCompiledScope(Rule):
    code = "G011"
    name = "wire-bytes-in-compiled-scope"
    fixit = ("route the frame through serve.ingest.validate_payload (the "
             "declared `# graftlint: payload-boundary`) and consume the "
             "validated ndarray it returns — never decode or forward raw "
             "wire bytes yourself")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_WIRE_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = src.resolve_dotted(node.func)
            if dotted is None:
                continue
            if dotted in _DECODERS:
                if src.in_payload_boundary(node.lineno):
                    continue
                out.append(self.violation(
                    src, node,
                    f"{dotted}() deserializes wire frame bytes outside the "
                    "declared payload boundary — validate_payload is the "
                    "one sanctioned decode of untrusted transport input"))
            elif dotted == "jax" or dotted.startswith("jax."):
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    if self._reads_payload(arg):
                        out.append(self.violation(
                            src, node,
                            "a `.payload` frame field flows into compiled "
                            "scope without passing the validation gauntlet "
                            f"({ast.unparse(node.func)} call)"))
                        break
        return out

    @staticmethod
    def _reads_payload(expr: ast.expr) -> bool:
        return any(isinstance(n, ast.Attribute) and n.attr == "payload"
                   for n in ast.walk(expr))
