"""G008 unvalidated-config-read: engine/runner code reads only `args.<name>`
flags registered through utils/config.py's parser.

Every flag in this repo flows through `make_parser` (where it gets a type,
a default, choices, and a help string — the coercion surface) and then
`resolve_defaults`. An `args.foo` read in engine or runner code for a name
that was never registered is either a typo (AttributeError at runtime, but
only on the code path that reaches it — often the recovery path that only
fires mid-incident) or a flag smuggled around the validated surface. The
registered-name set is extracted statically from utils/config.py's
`add_argument("--name", ...)` calls (both task variants, union).
"""

from __future__ import annotations

import ast
import os

from .core import PACKAGE, Rule, SourceFile, Violation


def _find_config_source() -> str | None:
    """utils/config.py, located relative to this package (works from any
    CWD; graftlint never imports the analyzed code)."""
    here = os.path.dirname(os.path.abspath(__file__))
    cand = os.path.join(os.path.dirname(here), "utils", "config.py")
    return cand if os.path.exists(cand) else None


def registered_flags(config_path: str | None = None) -> frozenset[str]:
    """Flag names (normalized: no dashes) registered via add_argument."""
    path = config_path or _find_config_source()
    if path is None:
        return frozenset()
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    names: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            flag = node.args[0].value
            if flag.startswith("-"):
                names.add(flag.lstrip("-").replace("-", "_"))
    return frozenset(names)


class UnvalidatedConfigRead(Rule):
    code = "G008"
    name = "unvalidated-config-read"
    fixit = ("register the flag in utils/config.py make_parser (type + "
             "default + help) so it is parsed, coerced, and visible in "
             "--help; engine/runner code must not grow a shadow flag "
             "surface")

    SCOPE = (
        f"{PACKAGE}/federated/",
        f"{PACKAGE}/runner/",
    )

    def __init__(self) -> None:
        self._registered: frozenset[str] | None = None

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE)

    @property
    def registered(self) -> frozenset[str]:
        if self._registered is None:
            self._registered = registered_flags()
        return self._registered

    def check(self, src: SourceFile) -> list[Violation]:
        if not self.registered:
            return []  # config.py not found (isolated fixture run): no-op
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            name = self._args_read(node)
            if name is not None and name not in self.registered:
                out.append(self.violation(
                    src, node,
                    f"`args.{name}` read in engine/runner code but "
                    "--{} is not registered in utils/config.py".format(
                        name)))
        return out

    @staticmethod
    def _args_read(node: ast.AST) -> str | None:
        """The flag name when `node` reads an attribute off an argparse
        namespace: `args.foo` or `getattr(args, "foo"[, default])`."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "args"
                and isinstance(node.ctx, ast.Load)):
            return node.attr
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "getattr" and len(node.args) >= 2
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id == "args"
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)):
            return node.args[1].value
        return None
