"""G009 obs-call-in-compiled-scope.

Tracing is host-only BY CONTRACT (obs/'s load-bearing promise): a span,
instant, counter.inc, or registry access inside compiled scope — the
jit/shard_map bodies that live in the parity modules (modes/, sketch/,
federated/engine.py) — is wrong in every outcome. Under tracing it runs
once at trace time (so per-round "telemetry" silently freezes at the first
round's values), and anything that tries to read a traced value to record
it forces a concretization, i.e. the exact hidden host sync G001 exists to
ban. The obs layer instruments the HOST halves (runner, federated/api,
serve, resilience) instead; this rule keeps it that way mechanically.

Detection (same whole-module compiled-scope treatment G001 uses):

- any call resolving through the import table into the obs package
  (`span(...)` via `from ..obs.trace import span`, `obtrace.instant(...)`,
  `obs.registry.default()`, ...);
- method calls `.inc(...)` / `.observe(...)` — the counter/histogram
  mutation surface (no jax/numpy API shares these names, so the receiver
  does not need resolving);
- any method call on a receiver named `REGISTRY`/`registry`.

ONE declared exception: calls resolving into ``obs.health`` — the sketch-
health estimator module's device half is compiled-scope BY DESIGN (pure
jnp readers the round program evaluates under the `_health_on` cond;
see obs/health.py's module doc). The exemption is module-scoped, not
blanket: anything that MUTATES telemetry from compiled scope still fires
through the `.inc()`/`.observe()`/registry-receiver backstops above, so a
HealthMonitor (the module's host half) smuggled into a step body is
caught the moment it records anything.

`.set(...)` is deliberately NOT matched bare: `arr.at[idx].set(v)` is the
jax scatter idiom all over compiled scope — gauge writes are caught by the
import-resolution path instead.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# whole modules where any function may be (part of) a jit/shard_map body —
# the same compiled scope G001's float()/bool() check uses
_COMPILED_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/sketch/",
    f"{PACKAGE}/federated/engine.py",
)

# counter/histogram mutators: distinctive enough to flag on name alone
_MUTATOR_ATTRS = ("inc", "observe")

_REGISTRY_NAMES = ("REGISTRY", "registry")


class ObsCallInCompiledScope(Rule):
    code = "G009"
    name = "obs-call-in-compiled-scope"
    fixit = ("hoist the obs call to the host-side caller (runner/, "
             "federated/api.py, serve/, resilience/): tracing is host-only "
             "by contract — a compiled body runs once at trace time, so "
             "the telemetry would freeze or force a host sync")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_COMPILED_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._classify(src, node)
            if msg:
                out.append(self.violation(src, node, msg))
        return out

    def _classify(self, src: SourceFile, node: ast.Call) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted is not None:
            parts = dotted.split(".")
            if "obs" in parts or dotted.startswith(f"{PACKAGE}.obs"):
                if "health" in parts:
                    # obs.health's estimator half is the ONE sanctioned
                    # compiled-scope corner of the obs package (pure jnp
                    # readers — see the module docstring); the mutator
                    # backstops below still police it
                    return None
                return (f"{dotted}() is an obs API call inside compiled "
                        "scope — tracing/metrics are host-only")
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATOR_ATTRS:
                return (f".{node.func.attr}() mutates a registry metric "
                        "inside compiled scope — counters/histograms are "
                        "host-only")
            if (isinstance(node.func.value, ast.Name)
                    and node.func.value.id in _REGISTRY_NAMES):
                return (f"{node.func.value.id}.{node.func.attr}() accesses "
                        "the metrics registry inside compiled scope")
        return None
