"""G015 blocking-call-in-event-loop.

The serve/scale event loop's whole promise is that ONE thread multiplexes
every connection — which means one blocking call anywhere on the reactor's
dispatch path blocks EVERY connection at once (the failure mode is worse
than the threaded transport's, where a blocked handler costs one peer).
This rule extends the G007 reachability machinery (rules_sync.py): from the
reactor's loop root (`_loop`) it walks same-module calls and package-level
import bindings, and fires on

- the G007 blocking set (time.sleep / os.system / open / subprocess.* /
  socket.create_connection), AND
- the SOCKET-OP set — `.recv()` / `.recv_into()` / `.accept()` /
  `.sendall()` / `.send()` / `.connect()` / `.makefile()` / `select.select`
  — anywhere OUTSIDE a declared sanctioned seam: the reactor touches
  sockets only through its non-blocking I/O helpers, each carrying
  `# graftlint: drain-point` (the same in-code seam declaration G001/G007
  use). `sendall` on a non-blocking socket can still spin-block on a slow
  reader; the reactor's `_flush_out` seam uses `send` + an out-buffer,
  which is why even `send` must live behind the declared seam.

A sleep (or a blocking recv, or file IO) smuggled into a helper the loop
calls is exactly the regression this guards: the reactor looks idle, every
connection times out, and the admission path stalls wholesale.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, SourceFile
from .rules_sync import BlockingCallOnDispatchThread

# socket-level attribute calls the reactor may only make inside its
# declared seams: on the event loop, even a "non-blocking" socket op is a
# policy decision (send can spin, recv on a blocking-mode socket parks the
# whole loop), so every one of them must be an explicit, reviewed seam
_SOCKET_OPS = ("recv", "recv_into", "accept", "sendall", "send", "connect",
               "makefile")


class BlockingCallInEventLoop(BlockingCallOnDispatchThread):
    code = "G015"
    name = "blocking-call-in-event-loop"
    fixit = ("the reactor's only sanctioned waits are the selector poll "
             "and the non-blocking I/O helpers, each declared `# graftlint: "
             "drain-point`; move blocking work off the reactor thread (the "
             "queue's own locks are the one sanctioned cross-thread seam)")

    SCOPE = (f"{PACKAGE}/serve/scale/",)
    EXEMPT = ()
    # the reactor's dispatch-loop roots: everything reachable from the
    # loop body runs with every connection's latency on the line
    ROOTS = {"_loop"}

    def _blocking(self, src: SourceFile, node: ast.Call) -> str | None:
        # the full G007 blocking set first (sleep/open/subprocess/...)
        msg = super()._blocking(src, node)
        if msg:
            return msg
        dotted = src.resolve_dotted(node.func)
        if dotted == "select.select":
            return ("select.select() outside the reactor's declared "
                    "selector seam — the loop's one wait is the declared "
                    "poll, not ad-hoc selects")
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _SOCKET_OPS):
            return (f".{node.func.attr}() on the event loop outside a "
                    "declared non-blocking I/O seam — one blocking socket "
                    "op parks EVERY connection at once")
        return None
