"""G018 lock-order-inversion and G019 unlocked-shared-state.

The host-side concurrency that carries the serving path — ingest condvars,
the selectors reactor, the RoundPipeline worker, the checkpoint writer
thread, transport handler threads — was enforced only by convention until
PR 20. These two rules machine-check the conventions, on top of the
dataflow.py interprocedural substrate (lock bindings, held-lock flow
events, the shared call resolver).

G018 builds the global lock-acquisition graph across serve/, runner/ and
obs/: an edge A -> B is recorded whenever lock B is acquired while A is
held — lexically (`with a: with b:`) or interprocedurally (`with a:
helper()` where helper acquires B, followed through same-module calls and
import bindings, depth-bounded). Two thread roots taking the same pair in
opposite orders deadlock; statically that is a cycle in this graph, and
every edge of a cycle is reported in the file that contains it. The
`# graftlint: lock-order <name>` directive on a binding assignment places
the lock in the declared global order (names compare lexicographically;
the convention is an `l0-`/`l1-`/... prefix): an edge where both ends are
named and name(A) < name(B) is sanctioned, name(A) > name(B) is a direct
violation even without a completed cycle.

G019 is module-local: an instance attribute mutated from two different
thread roots must be mutated only while a common declared lock is held.
Thread roots are derived, not annotated: every `Thread(target=f)` target
is a root; public entry points run on the caller's thread (the "main"
root). Lock context is the lexical `with` held-set plus the must-hold
facts of the enclosing function (a private helper whose EVERY caller
holds the lock inherits it — the `_locked` suffix idiom, verified instead
of trusted). `__init__` mutations are pre-publication and exempt; an
attribute that is DELIBERATELY lock-free (GIL-atomic flag, monotonic
counter) carries `# graftlint: lockfree <why>` on one of its mutation
sites.
"""

from __future__ import annotations

import ast
import os

from . import dataflow
from .core import PACKAGE, Rule, SourceFile, Violation

_SCOPE = (f"{PACKAGE}/serve/", f"{PACKAGE}/runner/", f"{PACKAGE}/obs/")

# interprocedural hops followed when attributing lock acquisitions to a
# call site (G018) — same spirit as G007's import-depth bound
_MAX_CALL_DEPTH = 3


class _ModuleInfo:
    """Per-module facts the concurrency rules share: lock bindings, flow
    events bucketed by enclosing function, the call-resolution tables."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.bindings = dataflow.lock_bindings(src)
        self.events = dataflow.flow_events(src, self.bindings)
        self.by_last = dataflow.functions_by_last(src)
        self.imports = dataflow.import_bindings(src)
        self.acquires: dict[str, set[str]] = {}
        self.calls: dict[str, list] = {}
        for e in self.events:
            if e.kind == "acquire":
                self.acquires.setdefault(e.symbol, set()).add(e.key)
            elif e.kind == "call":
                self.calls.setdefault(e.symbol, []).append(e)


def _site_node(lineno: int, col: int) -> ast.AST:
    node = ast.Pass()
    node.lineno = lineno  # type: ignore[attr-defined]
    node.col_offset = col  # type: ignore[attr-defined]
    return node


class LockOrderInversion(Rule):
    code = "G018"
    name = "lock-order-inversion"
    fixit = ("acquire the two locks in one global order everywhere (declare "
             "it: `# graftlint: lock-order l0-<name>` on each binding), or "
             "narrow one critical section so the scopes never nest")

    SCOPE = _SCOPE

    def __init__(self) -> None:
        # package-root -> (edges, bindings); the scope sweep parses ~40
        # modules once per analyzer run, every checked file reuses it
        self._graphs: dict[str, tuple[dict, dict]] = {}
        self._infos: dict[str, _ModuleInfo | None] = {}
        self._acq_memo: dict[tuple[str, str], frozenset[str]] = {}

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        root = dataflow.package_root(src.path)
        if root is not None:
            edges, bindings = self._scope_graph(root)
        else:
            edges, bindings = {}, {}
        apath = os.path.abspath(src.path)
        if self._info(apath) is None or root is None or \
                not apath.startswith(os.path.join(root, PACKAGE) + os.sep):
            # a fixture impersonating a scope module: merge its own edges
            info = _ModuleInfo(src)
            self._infos[apath] = info
            edges = dict(edges)
            bindings = dict(bindings)
            self._merge_module(info, edges, bindings)
        return self._report(src, edges, bindings)

    # -- graph construction ----------------------------------------------------

    def _scope_graph(self, root: str) -> tuple[dict, dict]:
        if root in self._graphs:
            return self._graphs[root]
        edges: dict[tuple[str, str], tuple[str, int, int]] = {}
        bindings: dict[str, dataflow.LockBinding] = {}
        for prefix in self.SCOPE:
            top = os.path.join(root, *prefix.rstrip("/").split("/"))
            if not os.path.isdir(top):
                continue
            for dirpath, dirnames, files in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for f in sorted(files):
                    if not f.endswith(".py"):
                        continue
                    info = self._info(os.path.join(dirpath, f))
                    if info is not None:
                        self._merge_module(info, edges, bindings)
        self._graphs[root] = (edges, bindings)
        return edges, bindings

    def _merge_module(self, info: _ModuleInfo, edges: dict,
                      bindings: dict) -> None:
        bindings.update(info.bindings)
        src = info.src
        for e in info.events:
            if not e.held:
                continue
            acquired: set[str] = set()
            if e.kind == "acquire":
                acquired.add(e.key)
            elif e.kind == "call":
                for callee in self._callees(info, e):
                    acquired |= self._acquired_in(callee[0], callee[1], 0)
            for b in acquired:
                for a in e.held:
                    if a == b:
                        continue  # self-nesting: reentrancy, not ordering
                    site = (src.rel, e.node.lineno,
                            getattr(e.node, "col_offset", 0))
                    prev = edges.get((a, b))
                    if prev is None or site < prev:
                        edges[(a, b)] = site

    def _callees(self, info: _ModuleInfo, event) -> list[tuple[str, str]]:
        """(module abspath, qualname) targets of a call event — same-module
        resolution plus import bindings."""
        out = [(os.path.abspath(info.src.path), q)
               for q in dataflow.local_call_targets(
                   info.src, event.node, event.symbol, info.by_last)]
        tgt = dataflow.import_call_target(info.src, event.node, info.imports)
        if tgt is not None:
            out.append((os.path.abspath(tgt[0]), tgt[1]))
        return out

    def _acquired_in(self, path: str, qualname: str,
                     depth: int) -> frozenset[str]:
        """Transitive set of lock keys `qualname` acquires (its own `with`
        blocks plus depth-bounded callees) — what a call under a held lock
        contributes to the acquisition graph."""
        memo_key = (path, qualname)
        if memo_key in self._acq_memo:
            return self._acq_memo[memo_key]
        self._acq_memo[memo_key] = frozenset()  # cycle guard
        info = self._info(path)
        if info is None:
            return frozenset()
        out = set(info.acquires.get(qualname, ()))
        if depth < _MAX_CALL_DEPTH:
            for e in info.calls.get(qualname, ()):
                for callee in self._callees(info, e):
                    out |= self._acquired_in(callee[0], callee[1], depth + 1)
        result = frozenset(out)
        self._acq_memo[memo_key] = result
        return result

    def _info(self, path: str) -> _ModuleInfo | None:
        apath = os.path.abspath(path)
        if apath in self._infos:
            return self._infos[apath]
        src = dataflow.LOADER.load(apath)
        info = _ModuleInfo(src) if src is not None else None
        self._infos[apath] = info
        return info

    # -- reporting -------------------------------------------------------------

    def _report(self, src: SourceFile, edges: dict,
                bindings: dict) -> list[Violation]:
        out: list[Violation] = []
        cyclic: dict[tuple[str, str], tuple[str, int, int]] = {}
        for (a, b), site in sorted(edges.items(), key=lambda kv: kv[1]):
            na = bindings[a].order_name if a in bindings else None
            nb = bindings[b].order_name if b in bindings else None
            if na is not None and nb is not None:
                if na < nb:
                    continue  # the declared order — sanctioned
                if site[0] == src.rel:
                    out.append(self.violation(
                        src, _site_node(site[1], site[2]),
                        f"{_disp(bindings, b)} acquired while "
                        f"{_disp(bindings, a)} is held — against the "
                        f"declared lock order ({nb} sorts before {na})"))
                continue
            cyclic[(a, b)] = site
        # an edge participates in a deadlock cycle iff b reaches a back
        adj: dict[str, set[str]] = {}
        for (a, b) in cyclic:
            adj.setdefault(a, set()).add(b)
        for (a, b), site in sorted(cyclic.items(), key=lambda kv: kv[1]):
            if site[0] != src.rel:
                continue
            path_back = _find_path(adj, b, a)
            if path_back is None:
                continue
            cycle = " -> ".join(_disp(bindings, k)
                                for k in [a] + path_back)
            out.append(self.violation(
                src, _site_node(site[1], site[2]),
                f"{_disp(bindings, b)} acquired while "
                f"{_disp(bindings, a)} is held closes an acquisition "
                f"cycle ({cycle}) — two threads taking these in opposite "
                "order deadlock"))
        return out


def _disp(bindings: dict, key: str) -> str:
    b = bindings.get(key)
    if b is None:
        return key
    return f"{b.attr} ({b.rel}:{b.lineno})"


def _find_path(adj: dict[str, set[str]], start: str,
               goal: str) -> list[str] | None:
    """Shortest node path start..goal (inclusive) over `adj`, or None."""
    if start == goal:
        return [start]
    parent: dict[str, str] = {start: start}
    frontier = [start]
    while frontier:
        nxt: list[str] = []
        for cur in frontier:
            for n in sorted(adj.get(cur, ())):
                if n in parent:
                    continue
                parent[n] = cur
                if n == goal:
                    path = [n]
                    while path[-1] != start:
                        path.append(parent[path[-1]])
                    return list(reversed(path))
                nxt.append(n)
        frontier = nxt
    return None


class UnlockedSharedState(Rule):
    code = "G019"
    name = "unlocked-shared-state"
    fixit = ("mutate the attribute under the lock that every other mutation "
             "site holds, or declare it `# graftlint: lockfree <why>` on a "
             "mutation site if it is deliberately GIL-atomic")

    SCOPE = _SCOPE

    # iteration cap for the must-hold fixed point (monotone intersections
    # over a module-local call graph converge long before this)
    _MAX_PASSES = 12

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        info = _ModuleInfo(src)
        targets = self._thread_targets(info)
        labels = self._thread_labels(info, targets)
        must_hold = self._must_hold(info, set(targets))
        lockfree = self._lockfree_attrs(src, info)

        by_attr: dict[str, list] = {}
        for e in info.events:
            if e.kind != "mutate" or e.symbol == "<module>":
                continue
            if e.symbol.rsplit(".", 1)[-1] == "__init__":
                continue  # pre-publication: no other thread sees self yet
            if e.key in info.bindings:
                continue  # (re)binding the lock itself
            by_attr.setdefault(e.key, []).append(e)

        out: list[Violation] = []
        for key in sorted(by_attr):
            if key in lockfree:
                continue
            muts = by_attr[key]
            roots: set[str] = set()
            common: set[str] | None = None
            for e in muts:
                roots |= labels.get(e.symbol, frozenset({"main"}))
                held = set(e.held) | must_hold.get(e.symbol, set())
                common = held if common is None else (common & held)
            if len(roots) < 2 or common:
                continue
            first = min(muts, key=lambda e: (e.node.lineno,
                                             getattr(e.node, "col_offset",
                                                     0)))
            attr = key.rsplit(".", 1)[-1]
            out.append(self.violation(
                src, first.node,
                f"self.{attr} is mutated from {len(roots)} thread roots "
                f"({', '.join(sorted(roots))}) with no common lock held "
                "across the mutation sites"))
        return out

    # -- thread roots ----------------------------------------------------------

    def _thread_targets(self, info: _ModuleInfo) -> dict[str, str]:
        """`Thread(target=...)` targets: function qualname -> root label.
        `target=self._run` resolves to same-module methods named _run,
        `target=fn` to the module-level fn."""
        src = info.src
        out: dict[str, str] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if src.resolve_dotted(node.func) != "threading.Thread":
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                cands: set[str] = set()
                if isinstance(kw.value, ast.Name):
                    cands = {q for q in info.by_last.get(kw.value.id, ())
                             if "." not in q}
                elif (isinstance(kw.value, ast.Attribute)
                      and isinstance(kw.value.value, ast.Name)
                      and kw.value.value.id in ("self", "cls")):
                    cands = {q for q in info.by_last.get(kw.value.attr, ())
                             if "." in q}
                for q in cands:
                    out[q] = f"thread({q.rsplit('.', 1)[-1]})"
        return out

    def _thread_labels(self, info: _ModuleInfo,
                       targets: dict[str, str]) -> dict[str, frozenset[str]]:
        """function qualname -> thread-root labels. `Thread(target=f)`
        targets seed their own label; public entry points (and module-level
        calls' targets) seed "main" — the caller's thread. Labels propagate
        along module-local call edges and into nested functions; a function
        nothing reaches defaults to "main" at lookup time."""
        src = info.src
        seeds: dict[str, set[str]] = {q: {label}
                                      for q, label in targets.items()}
        thread_targets = set(seeds)
        for f in src.functions:
            last = f.qualname.rsplit(".", 1)[-1]
            if f.qualname in thread_targets:
                continue
            if not last.startswith("_") or (last.startswith("__")
                                            and last.endswith("__")):
                seeds.setdefault(f.qualname, set()).add("main")
        # propagate along call edges to fixed point
        labels = {q: set(s) for q, s in seeds.items()}
        for _ in range(self._MAX_PASSES):
            changed = False
            for caller, events in info.calls.items():
                got = labels.get(caller)
                if not got:
                    continue
                for e in events:
                    for callee in dataflow.local_call_targets(
                            src, e.node, caller, info.by_last):
                        have = labels.setdefault(callee, set())
                        if not got <= have:
                            have |= got
                            changed = True
            if not changed:
                break
        # a nested def runs in its parent's thread context
        for f in src.functions:
            for q, s in list(labels.items()):
                if f.qualname.startswith(f"{q}."):
                    labels.setdefault(f.qualname, set()).update(s)
        return {q: frozenset(s) for q, s in labels.items() if s}

    # -- must-hold facts -------------------------------------------------------

    def _must_hold(self, info: _ModuleInfo,
                   thread_targets: set[str]) -> dict[str, set[str]]:
        """Locks PROVABLY held on entry to each function: the intersection
        over all module-local call sites of (lexically-held at the site ∪
        must-hold of the caller). Public functions, thread targets and
        uncalled functions get the empty set — anyone may call them bare
        (a thread entry point in particular starts with nothing held, even
        if someone also calls it directly under a lock)."""
        src = info.src
        callers: dict[str, list[tuple[str, tuple[str, ...]]]] = {}
        for caller, events in info.calls.items():
            for e in events:
                for callee in dataflow.local_call_targets(
                        src, e.node, caller, info.by_last):
                    callers.setdefault(callee, []).append((caller, e.held))
        hold: dict[str, set[str]] = {}
        private = {f.qualname for f in src.functions
                   if f.qualname.rsplit(".", 1)[-1].startswith("_")
                   and not f.qualname.rsplit(".", 1)[-1].endswith("__")
                   and f.qualname not in thread_targets}
        for _ in range(self._MAX_PASSES):
            changed = False
            for callee, sites in callers.items():
                if callee not in private:
                    continue
                acc: set[str] | None = None
                for caller, held in sites:
                    site_held = set(held) | hold.get(caller, set())
                    acc = site_held if acc is None else (acc & site_held)
                acc = acc or set()
                if hold.get(callee, set()) != acc:
                    hold[callee] = acc
                    changed = True
            if not changed:
                break
        return hold

    # -- lockfree declarations -------------------------------------------------

    def _lockfree_attrs(self, src: SourceFile,
                        info: _ModuleInfo) -> set[str]:
        """Attribute keys with a `# graftlint: lockfree <why>` marker on
        (or in the comment block above) ANY of their mutation sites — the
        declaration covers the attribute, not the one line."""
        out: set[str] = set()
        for e in info.events:
            if e.kind != "mutate":
                continue
            if dataflow._marker_above(src.directives.lockfree_linenos, src,
                                      e.node.lineno):
                out.add(e.key)
        return out
