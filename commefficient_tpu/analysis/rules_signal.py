"""G020 signal-unsafe-handler.

A Python signal handler runs BETWEEN bytecodes of whatever the main thread
was doing. If the interrupted code holds a non-reentrant lock the handler
then tries to take, the process deadlocks against itself; if it was
mid-write to a JSONL sink, the handler's own write interleaves into the
same buffered stream. PR 7 established the discipline by convention —
SIGTERM handlers set an Event / write to stderr and use the tracer's
`instant_signal_safe` (best-effort, lock-skipping) emit, never `instant` —
and this rule machine-enforces it.

Detection: every handler expression registered via `signal.signal(...)`
(a module function, a bound `self._method`, an imported helper, or an
inline lambda) is resolved through the shared dataflow call machinery and
its reachable body (same-module calls + import bindings, depth-bounded)
may not:

- acquire a NON-REENTRANT lock binding (`with self._lock:` /
  `lock.acquire()` on a Lock/Condition/Semaphore — RLock is exempt: the
  tracer serializes its signal-safe path on one reentrantly);
- perform file IO (`open()`);
- call the buffered JSONL sinks (`.instant(...)`, `.append_round(...)` —
  exact attribute match, so `instant_signal_safe` stays sanctioned).

Violations are reported at the registration site: that is the line that
turned an ordinary function into signal-context code. Handler expressions
beyond static reach (restoring a saved previous handler, `signal.SIG_DFL`)
are skipped silently.
"""

from __future__ import annotations

import ast
import os

from . import dataflow
from .core import Rule, SourceFile, Violation

# reachability bound from the registered handler, in call hops
_MAX_DEPTH = 4

# buffered JSONL sink methods (exact attribute names): TableLogger.append
# is host-side but takes the table lock; Tracer.instant and
# RoundLedger.append_round write line-buffered JSONL under a lock
_SINK_ATTRS = {
    "instant": "use instant_signal_safe — the lock-skipping tracer emit",
    "append_round": "the round ledger is a buffered, locked JSONL sink",
    "append_jsonl": "buffered JSONL writes interleave under a signal",
}


class SignalUnsafeHandler(Rule):
    code = "G020"
    name = "signal-unsafe-handler"
    fixit = ("a handler may set an Event/flag, write to stderr, or call "
             "instant_signal_safe; move lock-taking and IO to the code "
             "that OBSERVES the flag")

    def __init__(self) -> None:
        self._infos: dict[str, tuple | None] = {}

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        regs = [n for n in ast.walk(src.tree)
                if isinstance(n, ast.Call)
                and src.resolve_dotted(n.func) == "signal.signal"
                and len(n.args) >= 2]
        if not regs:
            return out
        info = self._module_info(src)
        for reg in regs:
            handler = reg.args[1]
            if isinstance(handler, ast.Lambda):
                hit = self._scan_body(src, info, handler,
                                      symbol=src.enclosing_symbol(
                                          handler.lineno),
                                      depth=0, seen=set())
                if hit:
                    out.append(self.violation(
                        src, reg, f"signal handler (lambda) {hit}"))
                continue
            for path, qual in self._handler_targets(src, info, handler):
                hit = self._unsafe_in(path, qual, 0, set())
                if hit:
                    out.append(self.violation(
                        src, reg, f"signal handler {qual}() {hit}"))
                    break
        return out

    # -- handler resolution ----------------------------------------------------

    def _handler_targets(self, src: SourceFile, info,
                         handler: ast.expr) -> list[tuple[str, str]]:
        by_last, imports = info[1], info[2]
        apath = os.path.abspath(src.path)
        if isinstance(handler, ast.Name):
            local = {q for q in by_last.get(handler.id, ()) if "." not in q}
            if local:
                return [(apath, q) for q in sorted(local)]
            tgt = imports.get(handler.id)
            if tgt is not None and tgt[1] != "*module*":
                return [tgt]
            return []
        if (isinstance(handler, ast.Attribute)
                and isinstance(handler.value, ast.Name)):
            if handler.value.id in ("self", "cls"):
                cands = {q for q in by_last.get(handler.attr, ())
                         if "." in q}
                qual = src.enclosing_symbol(handler.lineno)
                if "." in qual:
                    own = qual.rsplit(".", 1)[0]
                    same = {q for q in cands if q.rsplit(".", 1)[0] == own}
                    cands = same or cands
                return [(apath, q) for q in sorted(cands)]
            mod = imports.get(handler.value.id)
            if mod is not None and mod[1] == "*module*":
                return [(mod[0], handler.attr)]
        return []  # SIG_DFL, a saved previous handler: out of static reach

    # -- unsafe scan -----------------------------------------------------------

    def _module_info(self, src: SourceFile):
        """(bindings, by_last, imports, events_by_symbol) for a module."""
        apath = os.path.abspath(src.path)
        cached = self._infos.get(apath)
        if cached is not None:
            return cached
        bindings = dataflow.lock_bindings(src)
        by_last = dataflow.functions_by_last(src)
        imports = dataflow.import_bindings(src)
        events: dict[str, list] = {}
        for e in dataflow.flow_events(src, bindings):
            events.setdefault(e.symbol, []).append(e)
        info = (bindings, by_last, imports, events, src)
        self._infos[apath] = info
        return info

    def _unsafe_in(self, path: str, qual: str, depth: int,
                   seen: set) -> str | None:
        if depth > _MAX_DEPTH or (path, qual) in seen:
            return None
        seen.add((path, qual))
        src = dataflow.LOADER.load(path)
        if src is None:
            return None
        info = self._module_info(src)
        bindings, by_last, imports, events, _ = info
        for e in events.get(qual, ()):
            if src.directives.disabled(self.code, e.node.lineno):
                continue
            if e.kind == "acquire":
                b = bindings[e.key]
                if b.kind not in dataflow.REENTRANT_KINDS:
                    return (f"acquires non-reentrant {b.kind} {b.attr} "
                            f"({src.rel}:{e.node.lineno}) — deadlocks if "
                            "the interrupted code holds it")
                continue
            if e.kind != "call":
                continue
            hit = self._unsafe_call(src, bindings, e.node)
            if hit:
                return f"{hit} ({src.rel}:{e.node.lineno})"
            for npath, nqual in self._call_targets(src, info, e):
                hit = self._unsafe_in(npath, nqual, depth + 1, seen)
                if hit:
                    return hit
        return None

    def _unsafe_call(self, src: SourceFile, bindings: dict,
                     node: ast.Call) -> str | None:
        fn = node.func
        dotted = src.resolve_dotted(fn)
        if dotted == "open":
            return "performs file IO via open()"
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SINK_ATTRS:
                return (f"calls the JSONL sink .{fn.attr}() — "
                        f"{_SINK_ATTRS[fn.attr]}")
            if fn.attr == "acquire":
                sym = src.enclosing_symbol(node.lineno)
                cls = sym.rsplit(".", 1)[0] if "." in sym else None
                key = dataflow._lock_expr_key(fn.value, cls, src.rel)
                b = bindings.get(key) if key else None
                if b is not None and b.kind not in dataflow.REENTRANT_KINDS:
                    return (f"acquires non-reentrant {b.kind} {b.attr} — "
                            "deadlocks if the interrupted code holds it")
        return None

    def _call_targets(self, src: SourceFile, info, event):
        _, by_last, imports, _, _ = info
        out = [(os.path.abspath(src.path), q)
               for q in sorted(dataflow.local_call_targets(
                   src, event.node, event.symbol, by_last))]
        tgt = dataflow.import_call_target(src, event.node, imports)
        if tgt is not None:
            out.append((os.path.abspath(tgt[0]), tgt[1]))
        return out

    def _scan_body(self, src: SourceFile, info, lam: ast.Lambda,
                   symbol: str, depth: int, seen: set) -> str | None:
        """Inline-lambda handler: scan its body the same way, charged to
        the registration site's module."""
        bindings = info[0]
        for node in ast.walk(lam.body):
            if not isinstance(node, ast.Call):
                continue
            hit = self._unsafe_call(src, bindings, node)
            if hit:
                return hit
            for npath, nqual in self._call_targets(
                    src, info, _FakeEvent(node, symbol)):
                hit = self._unsafe_in(npath, nqual, depth + 1, seen)
                if hit:
                    return hit
        return None


class _FakeEvent:
    def __init__(self, node: ast.Call, symbol: str):
        self.node = node
        self.symbol = symbol
