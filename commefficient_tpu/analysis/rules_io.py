"""G004 raw-checkpoint-write: checkpoint directories are written ONLY
through utils/checkpoint.py's atomic helpers.

The hardened protocol (stage into `.tmp_round_*`, write the sha256 manifest
last, `os.rename` commit, read-back verify) is what makes a torn write
impossible to mistake for a checkpoint and a corrupt one loud at save time.
A bare `open(ckpt_path, "w")` / `np.save(ckpt_dir/...)` / `pickle.dump`
anywhere else re-opens the failure classes PR 1 closed: partial trees that
restore as garbage, unverifiable files, silent clobbers of the only good
copy. "Targets a checkpoint dir" is a textual heuristic on the file-path
argument (mentions ckpt/checkpoint/staging/round_) — precise enough in this
repo, and a fixture-pinned contract for the next rule author.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

_PATH_MARKERS = ("ckpt", "checkpoint", "staging", "round_")
# write-ish open() modes; bare open(p) defaults to read and stays legal
_WRITE_MODES = frozenset("wax+")


class RawCheckpointWrite(Rule):
    code = "G004"
    name = "raw-checkpoint-write"
    fixit = ("write through utils/checkpoint.py (save/_write_manifest): "
             "atomic .tmp staging + rename commit + sha256 manifest + "
             "read-back verify")

    EXEMPT = (f"{PACKAGE}/utils/checkpoint.py",)

    def applies(self, rel: str) -> bool:
        return rel not in self.EXEMPT

    def check(self, src: SourceFile) -> list[Violation]:
        handles = self._open_handles(src)
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._raw_write_target(src, node)
            if target is None:
                continue
            text = ast.unparse(target).lower()
            # a bare file-handle variable resolves to the path expression of
            # the open() that bound it (`with open(p) as fh: pickle.dump(o, fh)`)
            if isinstance(target, ast.Name) and target.id in handles:
                text = handles[target.id]
            if any(marker in text for marker in _PATH_MARKERS):
                out.append(self.violation(
                    src, node,
                    "raw write targeting a checkpoint directory "
                    f"({ast.unparse(target)}) outside utils/checkpoint.py's "
                    "atomic helpers"))
        return out

    @staticmethod
    def _open_handles(src: SourceFile) -> dict[str, str]:
        """handle-name -> lowercased path-expression text, for names bound
        by `with open(p) as fh:` or `fh = open(p)` anywhere in the file."""
        handles: dict[str, str] = {}

        def record(call: ast.expr, target: ast.expr | None) -> None:
            if (isinstance(call, ast.Call) and isinstance(target, ast.Name)
                    and src.resolve_dotted(call.func) == "open" and call.args):
                handles[target.id] = ast.unparse(call.args[0]).lower()

        for node in ast.walk(src.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    record(item.context_expr, item.optional_vars)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                record(node.value, node.targets[0])
        return handles

    def _raw_write_target(self, src: SourceFile,
                          node: ast.Call) -> ast.expr | None:
        """The file-path argument when `node` is a raw write primitive."""
        dotted = src.resolve_dotted(node.func)
        if dotted == "open" and node.args:
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
                    and set(mode.value) & _WRITE_MODES):
                return node.args[0]
            return None
        if dotted in ("numpy.save", "numpy.savez", "numpy.savez_compressed"):
            return node.args[0] if node.args else None
        if dotted in ("pickle.dump", "cloudpickle.dump", "joblib.dump"):
            # dump(obj, file) — the file argument is positional index 1
            return node.args[1] if len(node.args) >= 2 else None
        return None
