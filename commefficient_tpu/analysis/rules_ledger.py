"""G014 ledger-write-outside-commit.

The round ledger's entire value rests on ONE invariant: a record appears
if and only if its round COMMITTED. That is what lets `replay-check` call
a gap a bug, lets `diff` line two runs up round-by-round, and lets resume
continue one file without duplicates — prepared-but-uncommitted rounds
(prefetched, pipelined, rewound at loop exit) must be invisible to it.
The invariant holds because appends happen at exactly one place: the
commit-boundary publish hook, declared ``# graftlint: ledger-commit``
(FederatedSession._publish_round_obs). An append anywhere else in the
round machinery — a prepare path writing optimistically, a serving layer
logging arrivals as if they were commits, an exit path "flushing" rounds
that never published — silently turns the ledger from a commit log into
a guess.

Detection, in the round-machinery scope (runner/ + federated/):

- any call resolving through the import table into ``obs.ledger``
  (``RoundLedger(...)`` construction is legal — building the writer is
  config wiring; ``append_round``/``write_postmortem_bundle`` reached as
  module functions are not append sites either — the method call is);
- any ``.append_round(...)`` method call — the ledger's one write verb
  (no other API in the repo shares the name);
- outside a function declared ``# graftlint: ledger-commit``. The
  boundary lives in exactly one sanctioned file
  (``federated/api.py``); a declaration elsewhere in scope — or a SECOND
  one there — is itself a violation (the second-boundary discipline G012
  and G013 established).
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# the round machinery: where commits happen, and therefore where a stray
# append could masquerade as one
_LEDGER_SCOPE = (
    f"{PACKAGE}/runner/",
    f"{PACKAGE}/federated/",
)

# the ONE file the ledger-commit boundary may be declared in
_BOUNDARY_FILE = f"{PACKAGE}/federated/api.py"

# the ledger's write verb — distinctive enough to flag on name alone
_APPEND_ATTR = "append_round"


class LedgerWriteOutsideCommit(Rule):
    code = "G014"
    name = "ledger-write-outside-commit"
    fixit = ("route the ledger append through the ONE declared "
             "`# graftlint: ledger-commit` boundary "
             "(FederatedSession._publish_round_obs) — records exist iff "
             "their round committed; an append elsewhere logs rounds the "
             "committed-snapshot rewind may take back")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_LEDGER_SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        declared = [f for f in src.functions if f.ledger_commit]
        in_boundary_file = src.rel == _BOUNDARY_FILE
        illegal = declared if not in_boundary_file else declared[1:]
        for extra in illegal:
            out.append(Violation(
                code=self.code, name=self.name, rel=src.rel,
                lineno=extra.def_lineno, col=0,
                message=(
                    f"ledger-commit boundary declared at {extra.qualname} — "
                    f"the ledger append site is ONE declared function in "
                    f"{_BOUNDARY_FILE}; another declaration is a second "
                    f"write path hiding under the exemption"),
                fixit=("fold the append into the existing declared "
                       "boundary (FederatedSession._publish_round_obs)"),
                line_text=src.line(extra.def_lineno),
                symbol=extra.qualname,
            ))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._classify(src, node)
            if msg is None:
                continue
            if in_boundary_file and src.in_ledger_commit(node.lineno):
                continue
            out.append(self.violation(src, node, msg))
        return out

    def _classify(self, src: SourceFile, node: ast.Call) -> str | None:
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == _APPEND_ATTR):
            return (f".{_APPEND_ATTR}() appends to the round ledger "
                    "outside the declared commit boundary — ledger records "
                    "exist iff their round committed")
        dotted = src.resolve_dotted(node.func)
        if dotted is None:
            return None
        parts = dotted.split(".")
        if "ledger" in parts and (
                "obs" in parts or dotted.startswith(f"{PACKAGE}.obs")):
            tail = parts[-1]
            if tail in ("RoundLedger", "write_postmortem_bundle",
                        "read_records", "round_records", "replay_check",
                        "diff", "main"):
                # constructing the writer / reading / postmortem dumps are
                # wiring and diagnostics, not round appends
                return None
            return (f"{dotted}() reaches into obs.ledger from the round "
                    "machinery outside the declared ledger-commit boundary")
        return None
