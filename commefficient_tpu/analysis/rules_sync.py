"""G001 host-sync-in-round-path and G007 blocking-call-on-dispatch-thread.

Both enforce the async runner's core promise (runner/loop.py): the dispatch
path never hides a host synchronization, and the only sanctioned sync points
are the declared drain points — functions carrying `# graftlint:
drain-point` above their `def` (the batched-metrics drain, commit, eval, the
one-shot RTT probe). Everything else that forces a device round-trip or
blocks the thread must either move behind a drain boundary or carry an
explicit, justified suppression.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# modules where ANY value may be a traced array, so float()/bool() on a
# non-literal is a host sync (compiled-code scope); in the host-side halves
# (api.py, loop.py) those conversions are ordinary host arithmetic and only
# the unambiguous sync primitives are flagged
_COMPILED_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/sketch/",
    f"{PACKAGE}/federated/engine.py",
)

_SYNC_CALLS = ("jax.device_get", "jax.block_until_ready")
_NUMPY_SYNC_ATTRS = ("asarray", "array")


class HostSyncInRoundPath(Rule):
    code = "G001"
    name = "host-sync-in-round-path"
    fixit = ("defer the sync to a drain boundary (runner drain/commit), or "
             "mark the enclosing function `# graftlint: drain-point` if it "
             "IS the sanctioned boundary")

    SCOPE = (
        f"{PACKAGE}/federated/",
        f"{PACKAGE}/modes/",
        f"{PACKAGE}/sketch/",
    )
    EXACT = (f"{PACKAGE}/runner/loop.py",)

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE) or rel in self.EXACT

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        compiled = src.rel.startswith(_COMPILED_SCOPE)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if src.in_drain_point(node.lineno):
                continue
            hit = self._classify(src, node, compiled)
            if hit:
                out.append(self.violation(src, node, hit))
        return out

    def _classify(self, src: SourceFile, node: ast.Call,
                  compiled: bool) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted in _SYNC_CALLS:
            return (f"{dotted}() is a host-device synchronization on the "
                    "round path, outside any declared drain point")
        # <expr>.item() / <expr>.block_until_ready()
        if (isinstance(node.func, ast.Attribute) and not node.args
                and not node.keywords
                and node.func.attr in ("item", "block_until_ready")):
            return (f".{node.func.attr}() forces a device round-trip on the "
                    "round path, outside any declared drain point")
        # numpy conversions materialize traced/device values on host
        if dotted is not None:
            head, _, attr = dotted.rpartition(".")
            if head == "numpy" and attr in _NUMPY_SYNC_ATTRS:
                return (f"np.{attr}() on the round path copies its argument "
                        "to host (a hidden sync when the value is a device "
                        "array)")
        # float()/bool() on a non-literal in compiled-code modules
        if (compiled and isinstance(node.func, ast.Name)
                and node.func.id in ("float", "bool") and len(node.args) == 1
                and isinstance(node.args[0],
                               (ast.Name, ast.Attribute, ast.Subscript))):
            return (f"{node.func.id}() on a value in compiled-scope code "
                    "forces concretization — a host sync under jit tracing")
        return None


_BLOCKING_CALLS = {
    "time.sleep": "time.sleep() stalls the dispatch/prefetch thread",
    "os.system": "os.system() is blocking sync IO on the dispatch path",
    "open": "synchronous file IO on the dispatch path",
}

# entry points of the dispatch/prefetch path; reachability is computed over
# the module's own call graph from these roots
_ROOT_NAMES = {"run_loop", "next", "prepare_round", "dispatch_round",
               "dispatch_block"}


class BlockingCallOnDispatchThread(Rule):
    code = "G007"
    name = "blocking-call-on-dispatch-thread"
    fixit = ("move the blocking work to the writer/watchdog thread or an "
             "exit path; drain points and fault-injection sites carry "
             "`# graftlint: drain-point` / an explicit disable")

    SCOPE = f"{PACKAGE}/runner/"
    # the async writer runs on its own dedicated thread by design
    EXEMPT = (f"{PACKAGE}/runner/writer.py",)

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE) and rel not in self.EXEMPT

    def check(self, src: SourceFile) -> list[Violation]:
        reachable = self._reachable(src)
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            sym = src.enclosing_symbol(node.lineno)
            if sym not in reachable:
                continue
            if src.in_drain_point(node.lineno):
                continue
            msg = self._blocking(src, node)
            if msg:
                out.append(self.violation(
                    src, node,
                    f"{msg} (reachable from the dispatch path via {sym})"))
        return out

    def _blocking(self, src: SourceFile, node: ast.Call) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[dotted]
        if dotted and dotted.startswith("subprocess."):
            return f"{dotted}() launches a blocking subprocess on the " \
                   "dispatch path"
        return None

    def _reachable(self, src: SourceFile) -> set[str]:
        """Qualnames reachable from the dispatch-path roots over same-module
        calls (Name calls resolve innermost-nested-first, then module level;
        self.X calls resolve to any same-module method named X)."""
        by_last: dict[str, set[str]] = {}
        for f in src.functions:
            by_last.setdefault(f.qualname.rsplit(".", 1)[-1], set()).add(
                f.qualname)
        edges: dict[str, set[str]] = {f.qualname: set()
                                      for f in src.functions}
        # one walk: attribute calls and name calls per enclosing function
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = src.enclosing_symbol(node.lineno)
            if caller == "<module>":
                continue
            callee: str | None = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in ("self", "cls")):
                callee = node.func.attr
            if callee and callee in by_last:
                # prefer a nested function of the caller, else any match
                nested = {q for q in by_last[callee]
                          if q.startswith(f"{caller}.")}
                edges.setdefault(caller, set()).update(
                    nested or by_last[callee])
        roots = {f.qualname for f in src.functions
                 if f.qualname.rsplit(".", 1)[-1] in _ROOT_NAMES}
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        # a nested function belongs to its parent's thread context
        for f in src.functions:
            if any(f.qualname.startswith(f"{r}.") for r in list(seen)):
                seen.add(f.qualname)
        return seen
