"""G001 host-sync-in-round-path and G007 blocking-call-on-dispatch-thread.

Both enforce the async runner's core promise (runner/loop.py): the dispatch
path never hides a host synchronization, and the only sanctioned sync points
are the declared drain points — functions carrying `# graftlint:
drain-point` above their `def` (the batched-metrics drain, commit, eval, the
one-shot RTT probe, the serving queue's quorum wait). Everything else that
forces a device round-trip or blocks the thread must either move behind a
drain boundary or carry an explicit, justified suppression.

G007's reachability is PACKAGE-level: from the dispatch-path roots it
follows same-module calls AND import bindings (`from .helper import fn`,
`mod.fn()` through `from . import mod`) into other modules of the package,
depth-bounded — a `time.sleep` smuggled behind a helper import is the same
stall as an inline one. Drain-point declarations and explicit G007 disables
in the HELPER module stop the traversal (that is how serve/transport.py
declares its sanctioned blocking points in code).
"""

from __future__ import annotations

import ast
import os

from .core import PACKAGE, Rule, SourceFile, Violation

# modules where ANY value may be a traced array, so float()/bool() on a
# non-literal is a host sync (compiled-code scope); in the host-side halves
# (api.py, loop.py) those conversions are ordinary host arithmetic and only
# the unambiguous sync primitives are flagged
_COMPILED_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/sketch/",
    f"{PACKAGE}/federated/engine.py",
)

_SYNC_CALLS = ("jax.device_get", "jax.block_until_ready")
_NUMPY_SYNC_ATTRS = ("asarray", "array")


class HostSyncInRoundPath(Rule):
    code = "G001"
    name = "host-sync-in-round-path"
    fixit = ("defer the sync to a drain boundary (runner drain/commit), or "
             "mark the enclosing function `# graftlint: drain-point` if it "
             "IS the sanctioned boundary")

    SCOPE = (
        f"{PACKAGE}/federated/",
        f"{PACKAGE}/modes/",
        f"{PACKAGE}/sketch/",
    )
    # the always-on pipeline seams joined the round path in PR 11: the
    # two-open-rounds ingest buffer sits on the admission hot path, and
    # the pipeline worker runs the serve cycle that feeds every dispatch —
    # a hidden host sync in either stalls the always-on promise exactly
    # like one in the loop would
    EXACT = (
        f"{PACKAGE}/runner/loop.py",
        f"{PACKAGE}/serve/ingest.py",
        f"{PACKAGE}/serve/pipeline.py",
    )

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE) or rel in self.EXACT

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        compiled = src.rel.startswith(_COMPILED_SCOPE)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if src.in_drain_point(node.lineno):
                continue
            hit = self._classify(src, node, compiled)
            if hit:
                out.append(self.violation(src, node, hit))
        return out

    def _classify(self, src: SourceFile, node: ast.Call,
                  compiled: bool) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted in _SYNC_CALLS:
            return (f"{dotted}() is a host-device synchronization on the "
                    "round path, outside any declared drain point")
        # <expr>.item() / <expr>.block_until_ready()
        if (isinstance(node.func, ast.Attribute) and not node.args
                and not node.keywords
                and node.func.attr in ("item", "block_until_ready")):
            return (f".{node.func.attr}() forces a device round-trip on the "
                    "round path, outside any declared drain point")
        # numpy conversions materialize traced/device values on host
        if dotted is not None:
            head, _, attr = dotted.rpartition(".")
            if head == "numpy" and attr in _NUMPY_SYNC_ATTRS:
                return (f"np.{attr}() on the round path copies its argument "
                        "to host (a hidden sync when the value is a device "
                        "array)")
        # float()/bool() on a non-literal in compiled-code modules
        if (compiled and isinstance(node.func, ast.Name)
                and node.func.id in ("float", "bool") and len(node.args) == 1
                and isinstance(node.args[0],
                               (ast.Name, ast.Attribute, ast.Subscript))):
            return (f"{node.func.id}() on a value in compiled-scope code "
                    "forces concretization — a host sync under jit tracing")
        return None


_BLOCKING_CALLS = {
    "time.sleep": "time.sleep() stalls the dispatch/prefetch thread",
    "os.system": "os.system() is blocking sync IO on the dispatch path",
    "open": "synchronous file IO on the dispatch path",
    "socket.create_connection": "socket.create_connection() is a blocking "
                                "network round-trip on the dispatch path",
}

# entry points of the dispatch/prefetch path; reachability is computed over
# the package-level call graph from these roots (serve_round/submit are the
# serving layer's dispatch-path entries)
_ROOT_NAMES = {"run_loop", "next", "prepare_round", "dispatch_round",
               "dispatch_block", "serve_round", "submit"}

# cross-module traversal bound: hops of `from .helper import fn` / `mod.fn()`
# indirection followed before giving up (a sleep buried deeper than this
# behind imports is beyond honest static reach — raise it if one ever is)
_MAX_IMPORT_DEPTH = 4


class BlockingCallOnDispatchThread(Rule):
    code = "G007"
    name = "blocking-call-on-dispatch-thread"
    fixit = ("move the blocking work to the writer/watchdog thread or an "
             "exit path; drain points and fault-injection sites carry "
             "`# graftlint: drain-point` / an explicit disable")

    SCOPE = (f"{PACKAGE}/runner/", f"{PACKAGE}/serve/")
    # the async writer runs on its own dedicated thread by design
    EXEMPT = (f"{PACKAGE}/runner/writer.py",)
    # overridable per subclass: G015 (rules_reactor.py) reuses this whole
    # reachability machine with the event loop's own roots
    ROOTS = _ROOT_NAMES

    def __init__(self) -> None:
        # per-analyzer-run cache of parsed helper modules (abspath ->
        # SourceFile | None); reachability is package-level, so one helper
        # may be consulted from several scoped files
        self._helpers: dict[str, SourceFile | None] = {}

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE) and rel not in self.EXEMPT

    def check(self, src: SourceFile) -> list[Violation]:
        reachable = self._reachable(src)
        imports = _import_bindings(src)
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            sym = src.enclosing_symbol(node.lineno)
            if sym not in reachable:
                continue
            if src.in_drain_point(node.lineno):
                continue
            msg = self._blocking(src, node)
            if msg:
                out.append(self.violation(
                    src, node,
                    f"{msg} (reachable from the dispatch path via {sym})"))
                continue
            # package-level: a call into an IMPORTED helper whose body (or
            # transitive same-package callees) blocks — the "sleep smuggled
            # behind a helper import" a module-local graph cannot see
            imported = self._imported_blocking(src, node, imports)
            if imported:
                out.append(self.violation(
                    src, node,
                    f"{imported} (reachable from the dispatch path via "
                    f"{sym}, through a helper import)"))
        return out

    def _blocking(self, src: SourceFile, node: ast.Call) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[dotted]
        if dotted and dotted.startswith("subprocess."):
            return f"{dotted}() launches a blocking subprocess on the " \
                   "dispatch path"
        return None

    # -- package-level traversal ---------------------------------------------

    def _imported_blocking(self, src: SourceFile, node: ast.Call,
                           imports: dict) -> str | None:
        """Resolve `fn()` / `mod.fn()` through the file's import bindings to
        a function in another module of this package (or the fixture's
        directory) and report the first blocking call reachable from it."""
        target: tuple[str, str] | None = None
        if isinstance(node.func, ast.Name):
            target = imports.get(node.func.id)
        elif (isinstance(node.func, ast.Attribute)
              and isinstance(node.func.value, ast.Name)):
            mod = imports.get(node.func.value.id)
            if mod is not None and mod[1] == "*module*":
                target = (mod[0], node.func.attr)
        if target is None:
            return None
        path, func = target
        return self._func_blocks(path, func, depth=0, seen=set())

    def _func_blocks(self, path: str, func: str, depth: int,
                     seen: set) -> str | None:
        """Does module-level function `func` in the module at `path` reach a
        blocking call (its own body, same-module callees, or further
        imports, depth-bounded)? Declared drain points — the sanctioned
        blocking boundaries — and explicit G007 disables stop the
        traversal."""
        if depth > _MAX_IMPORT_DEPTH or (path, func) in seen:
            return None
        seen.add((path, func))
        helper = self._load_helper(path)
        if helper is None:
            return None
        fns = [f for f in helper.functions if f.qualname == func]
        if not fns or any(f.drain_point for f in fns):
            return None  # undefined here, or a declared sanctioned boundary
        spans = [(f.start, f.end) for f in fns]
        imports = _import_bindings(helper)
        for node in ast.walk(helper.tree):
            if not isinstance(node, ast.Call):
                continue
            if not any(s <= node.lineno <= e for s, e in spans):
                continue
            if helper.enclosing_symbol(node.lineno) != func:
                # a nested def inside the helper is its own (possibly
                # thread-targeted) context — don't charge it to the caller
                continue
            if helper.in_drain_point(node.lineno):
                continue
            if helper.directives.disabled(self.code, node.lineno):
                continue
            msg = self._blocking(helper, node)
            if msg:
                return (f"{msg} — in {helper.rel}:{node.lineno} "
                        f"({func})")
            # same-module callee
            if isinstance(node.func, ast.Name):
                callee = node.func.id
                if any(f.qualname == callee for f in helper.functions):
                    hit = self._func_blocks(path, callee, depth + 1, seen)
                    if hit:
                        return hit
            # further imports
            target = None
            if isinstance(node.func, ast.Name):
                target = imports.get(node.func.id)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)):
                mod = imports.get(node.func.value.id)
                if mod is not None and mod[1] == "*module*":
                    target = (mod[0], node.func.attr)
            if target is not None:
                hit = self._func_blocks(target[0], target[1], depth + 1, seen)
                if hit:
                    return hit
        return None

    def _load_helper(self, path: str) -> SourceFile | None:
        if path in self._helpers:
            return self._helpers[path]
        src: SourceFile | None = None
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            src = SourceFile(path, _helper_rel(path), text,
                             frozenset({self.code}))
        except (OSError, SyntaxError, ValueError):
            src = None  # unreadable helper: out of static reach
        self._helpers[path] = src
        return src

    def _reachable(self, src: SourceFile) -> set[str]:
        """Qualnames reachable from the dispatch-path roots over same-module
        calls (Name calls resolve innermost-nested-first, then module level;
        self.X calls resolve to any same-module method named X)."""
        by_last: dict[str, set[str]] = {}
        for f in src.functions:
            by_last.setdefault(f.qualname.rsplit(".", 1)[-1], set()).add(
                f.qualname)
        edges: dict[str, set[str]] = {f.qualname: set()
                                      for f in src.functions}
        # one walk: attribute calls and name calls per enclosing function
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = src.enclosing_symbol(node.lineno)
            if caller == "<module>":
                continue
            callee: str | None = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in ("self", "cls")):
                callee = node.func.attr
            if callee and callee in by_last:
                # prefer a nested function of the caller, else any match
                nested = {q for q in by_last[callee]
                          if q.startswith(f"{caller}.")}
                edges.setdefault(caller, set()).update(
                    nested or by_last[callee])
        roots = {f.qualname for f in src.functions
                 if f.qualname.rsplit(".", 1)[-1] in self.ROOTS}
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        # a nested function belongs to its parent's thread context
        for f in src.functions:
            if any(f.qualname.startswith(f"{r}.") for r in list(seen)):
                seen.add(f.qualname)
        return seen


# -- import resolution (package-level reachability) ---------------------------


def _helper_rel(path: str) -> str:
    """Project-relative name for a helper module (fixture helpers override
    it with their own `# graftlint: module=`, applied by SourceFile)."""
    from .core import project_rel

    return project_rel(path)


def _package_root(start: str) -> str | None:
    """Nearest ancestor directory CONTAINING the package dir — resolves
    absolute `commefficient_tpu.*` imports from real modules and from
    fixture files living outside the package tree alike."""
    cur = os.path.dirname(os.path.abspath(start))
    for _ in range(12):
        if os.path.isdir(os.path.join(cur, PACKAGE)):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt
    return None


def _import_bindings(src: SourceFile) -> dict[str, tuple[str, str]]:
    """name -> (module file path, target) for every import that resolves to
    a file we can statically follow: target is a function name for
    `from .mod import fn`, or the sentinel "*module*" for module bindings
    (`from . import mod`, `import pkg.mod as m`) whose attributes are
    resolved at the call site. Relative imports resolve against the file's
    REAL directory (which makes fixture-local helper modules work); absolute
    imports resolve only within this package."""
    out: dict[str, tuple[str, str]] = {}
    here = os.path.dirname(os.path.abspath(src.path))

    def module_base(level: int, module: str | None) -> str | None:
        if level > 0:
            base = here
            for _ in range(level - 1):
                base = os.path.dirname(base)
        else:
            if not module or module.split(".")[0] != PACKAGE:
                return None
            root = _package_root(src.path)
            if root is None:
                return None
            base = root
        if module:
            parts = module.split(".")
            if level == 0:
                parts = parts  # starts with PACKAGE, anchored at root
            base = os.path.join(base, *parts)
        return base

    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom):
            base = module_base(node.level, node.module)
            if base is None:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                sub = os.path.join(base, a.name + ".py")
                mod_file = base + ".py"
                pkg_init = os.path.join(base, "__init__.py")
                if os.path.isfile(sub):
                    out[bound] = (sub, "*module*")
                elif os.path.isfile(mod_file):
                    out[bound] = (mod_file, a.name)
                elif os.path.isfile(pkg_init):
                    out[bound] = (pkg_init, a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                parts = a.name.split(".")
                if parts[0] != PACKAGE:
                    continue  # stdlib/third-party: _BLOCKING_CALLS covers it
                root = _package_root(src.path)
                if root is None:
                    continue
                mod_file = os.path.join(root, *parts) + ".py"
                pkg_init = os.path.join(root, *parts, "__init__.py")
                bound = a.asname or parts[0]
                if a.asname is None:
                    continue  # dotted access via the bare package name is
                    # not a call-site shape resolve_dotted feeds us
                if os.path.isfile(mod_file):
                    out[bound] = (mod_file, "*module*")
                elif os.path.isfile(pkg_init):
                    out[bound] = (pkg_init, "*module*")
    return out
