"""G001 host-sync-in-round-path and G007 blocking-call-on-dispatch-thread.

Both enforce the async runner's core promise (runner/loop.py): the dispatch
path never hides a host synchronization, and the only sanctioned sync points
are the declared drain points — functions carrying `# graftlint:
drain-point` above their `def` (the batched-metrics drain, commit, eval, the
one-shot RTT probe, the serving queue's quorum wait). Everything else that
forces a device round-trip or blocks the thread must either move behind a
drain boundary or carry an explicit, justified suppression.

G007's reachability is PACKAGE-level: from the dispatch-path roots it
follows same-module calls AND import bindings (`from .helper import fn`,
`mod.fn()` through `from . import mod`) into other modules of the package,
depth-bounded — a `time.sleep` smuggled behind a helper import is the same
stall as an inline one. Drain-point declarations and explicit G007 disables
in the HELPER module stop the traversal (that is how serve/transport.py
declares its sanctioned blocking points in code).
"""

from __future__ import annotations

import ast

from . import dataflow
from .core import PACKAGE, Rule, SourceFile, Violation

# modules where ANY value may be a traced array, so float()/bool() on a
# non-literal is a host sync (compiled-code scope); in the host-side halves
# (api.py, loop.py) those conversions are ordinary host arithmetic and only
# the unambiguous sync primitives are flagged
_COMPILED_SCOPE = (
    f"{PACKAGE}/modes/",
    f"{PACKAGE}/sketch/",
    f"{PACKAGE}/federated/engine.py",
)

_SYNC_CALLS = ("jax.device_get", "jax.block_until_ready")
_NUMPY_SYNC_ATTRS = ("asarray", "array")


class HostSyncInRoundPath(Rule):
    code = "G001"
    name = "host-sync-in-round-path"
    fixit = ("defer the sync to a drain boundary (runner drain/commit), or "
             "mark the enclosing function `# graftlint: drain-point` if it "
             "IS the sanctioned boundary")

    SCOPE = (
        f"{PACKAGE}/federated/",
        f"{PACKAGE}/modes/",
        f"{PACKAGE}/sketch/",
    )
    # the always-on pipeline seams joined the round path in PR 11: the
    # two-open-rounds ingest buffer sits on the admission hot path, and
    # the pipeline worker runs the serve cycle that feeds every dispatch —
    # a hidden host sync in either stalls the always-on promise exactly
    # like one in the loop would
    EXACT = (
        f"{PACKAGE}/runner/loop.py",
        f"{PACKAGE}/serve/ingest.py",
        f"{PACKAGE}/serve/pipeline.py",
    )

    # the interprocedural taint pass (PR 20): `float(x)` smuggled behind a
    # helper call fires too. Subclassable off so the regression test can
    # demonstrate exactly what the pre-taint syntactic rule missed.
    taint_pass = True

    # hops of helper-call indirection the taint pass follows before giving
    # up (a coercion buried deeper is beyond honest static reach)
    _MAX_TAINT_DEPTH = 3

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE) or rel in self.EXACT

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        compiled = src.rel.startswith(_COMPILED_SCOPE)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if src.in_drain_point(node.lineno):
                continue
            hit = self._classify(src, node, compiled)
            if hit:
                out.append(self.violation(src, node, hit))
        if compiled and self.taint_pass:
            out.extend(self._taint_findings(src))
        return out

    # -- interprocedural taint -------------------------------------------------

    def _taint_findings(self, src: SourceFile) -> list[Violation]:
        """float()/bool()/int() on a traced value HIDDEN BEHIND a helper
        call: every parameter of a compiled-scope function is a potential
        tracer, so an argument derived from one that flows into an
        out-of-scope helper which coerces it is the same hidden sync as an
        inline float() — reported at the call site that smuggles it."""
        imports = dataflow.import_bindings(src)
        if not imports:
            return []
        out: list[Violation] = []
        for fnode in ast.walk(src.tree):
            if not isinstance(fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            seeds = set(dataflow.param_names(fnode))
            if not seeds:
                continue
            tainted = dataflow.tainted_names(fnode, seeds)
            for call in dataflow.walk_in_function(fnode):
                if not isinstance(call, ast.Call):
                    continue
                if src.in_drain_point(call.lineno):
                    continue
                target = dataflow.import_call_target(src, call, imports)
                if target is None:
                    continue
                passed = self._tainted_params(src, call, tainted,
                                              target[0], target[1])
                if not passed:
                    continue
                hit = self._coerced_in_helper(target[0], target[1],
                                              passed, depth=0, seen=set())
                if hit is not None:
                    coercer, where = hit
                    out.append(self.violation(
                        src, call,
                        f"{coercer} on a value tainted from a traced "
                        f"parameter, hidden inside helper {target[1]}() "
                        f"({where}) — a host sync the syntactic scan "
                        "cannot see"))
        return out

    def _tainted_params(self, src: SourceFile, call: ast.Call,
                        tainted: set[str], path: str,
                        fname: str) -> frozenset[str]:
        """Callee parameter names that receive a tainted argument."""
        helper = dataflow.LOADER.load(path)
        if helper is None:
            return frozenset()
        fdef = _find_def(helper, fname)
        if fdef is None:
            return frozenset()
        params = dataflow.param_names(fdef)
        hit: set[str] = set()
        for i, arg in enumerate(call.args):
            if i < len(params) and dataflow.expr_tainted(arg, tainted):
                hit.add(params[i])
        for kw in call.keywords:
            if (kw.arg is not None and kw.arg in params
                    and dataflow.expr_tainted(kw.value, tainted)):
                hit.add(kw.arg)
        return frozenset(hit)

    def _coerced_in_helper(self, path: str, fname: str,
                           seeds: frozenset[str], depth: int,
                           seen: set) -> tuple[str, str] | None:
        """Does `fname` at `path` coerce a value derived from `seeds` with
        float()/bool()/int()? Returns (coercer, 'rel:lineno') or None.
        Compiled-scope helpers are skipped (the syntactic rule already
        patrols them); drain points and explicit G001 disables in the
        helper stop the traversal, same contract as G007."""
        key = (path, fname, seeds)
        if depth > self._MAX_TAINT_DEPTH or key in seen:
            return None
        seen.add(key)
        helper = dataflow.LOADER.load(path)
        if helper is None or helper.rel.startswith(_COMPILED_SCOPE):
            return None
        fdef = _find_def(helper, fname)
        if fdef is None:
            return None
        if any(f.qualname == fname and f.drain_point
               for f in helper.functions):
            return None  # a declared sanctioned sync boundary
        tainted = dataflow.tainted_names(fdef, set(seeds))
        imports = None
        for call in dataflow.walk_in_function(fdef):
            if not isinstance(call, ast.Call):
                continue
            if helper.directives.disabled(self.code, call.lineno):
                continue
            if helper.in_drain_point(call.lineno):
                continue
            if (isinstance(call.func, ast.Name)
                    and call.func.id in ("float", "bool", "int")
                    and len(call.args) == 1
                    and dataflow.expr_tainted(call.args[0], tainted)):
                return (f"{call.func.id}()",
                        f"{helper.rel}:{call.lineno}")
            # taint flowing one helper deeper: same-module Name call or a
            # further import binding
            nxt: tuple[str, str] | None = None
            if isinstance(call.func, ast.Name) and any(
                    f.qualname == call.func.id for f in helper.functions):
                nxt = (path, call.func.id)
            else:
                if imports is None:
                    imports = dataflow.import_bindings(helper)
                nxt = dataflow.import_call_target(helper, call, imports)
            if nxt is None:
                continue
            passed = self._tainted_params(helper, call, tainted,
                                          nxt[0], nxt[1])
            if passed:
                hit = self._coerced_in_helper(nxt[0], nxt[1], passed,
                                              depth + 1, seen)
                if hit is not None:
                    return hit
        return None

    def _classify(self, src: SourceFile, node: ast.Call,
                  compiled: bool) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted in _SYNC_CALLS:
            return (f"{dotted}() is a host-device synchronization on the "
                    "round path, outside any declared drain point")
        # <expr>.item() / <expr>.block_until_ready()
        if (isinstance(node.func, ast.Attribute) and not node.args
                and not node.keywords
                and node.func.attr in ("item", "block_until_ready")):
            return (f".{node.func.attr}() forces a device round-trip on the "
                    "round path, outside any declared drain point")
        # numpy conversions materialize traced/device values on host
        if dotted is not None:
            head, _, attr = dotted.rpartition(".")
            if head == "numpy" and attr in _NUMPY_SYNC_ATTRS:
                return (f"np.{attr}() on the round path copies its argument "
                        "to host (a hidden sync when the value is a device "
                        "array)")
        # float()/bool() on a non-literal in compiled-code modules
        if (compiled and isinstance(node.func, ast.Name)
                and node.func.id in ("float", "bool") and len(node.args) == 1
                and isinstance(node.args[0],
                               (ast.Name, ast.Attribute, ast.Subscript))):
            return (f"{node.func.id}() on a value in compiled-scope code "
                    "forces concretization — a host sync under jit tracing")
        return None


_BLOCKING_CALLS = {
    "time.sleep": "time.sleep() stalls the dispatch/prefetch thread",
    "os.system": "os.system() is blocking sync IO on the dispatch path",
    "open": "synchronous file IO on the dispatch path",
    "socket.create_connection": "socket.create_connection() is a blocking "
                                "network round-trip on the dispatch path",
}

# entry points of the dispatch/prefetch path; reachability is computed over
# the package-level call graph from these roots (serve_round/submit are the
# serving layer's dispatch-path entries)
_ROOT_NAMES = {"run_loop", "next", "prepare_round", "dispatch_round",
               "dispatch_block", "serve_round", "submit"}

# cross-module traversal bound: hops of `from .helper import fn` / `mod.fn()`
# indirection followed before giving up (a sleep buried deeper than this
# behind imports is beyond honest static reach — raise it if one ever is)
_MAX_IMPORT_DEPTH = 4


class BlockingCallOnDispatchThread(Rule):
    code = "G007"
    name = "blocking-call-on-dispatch-thread"
    fixit = ("move the blocking work to the writer/watchdog thread or an "
             "exit path; drain points and fault-injection sites carry "
             "`# graftlint: drain-point` / an explicit disable")

    SCOPE = (f"{PACKAGE}/runner/", f"{PACKAGE}/serve/")
    # the async writer runs on its own dedicated thread by design
    EXEMPT = (f"{PACKAGE}/runner/writer.py",)
    # overridable per subclass: G015 (rules_reactor.py) reuses this whole
    # reachability machine with the event loop's own roots
    ROOTS = _ROOT_NAMES

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE) and rel not in self.EXEMPT

    def check(self, src: SourceFile) -> list[Violation]:
        reachable = self._reachable(src)
        imports = _import_bindings(src)
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            sym = src.enclosing_symbol(node.lineno)
            if sym not in reachable:
                continue
            if src.in_drain_point(node.lineno):
                continue
            msg = self._blocking(src, node)
            if msg:
                out.append(self.violation(
                    src, node,
                    f"{msg} (reachable from the dispatch path via {sym})"))
                continue
            # package-level: a call into an IMPORTED helper whose body (or
            # transitive same-package callees) blocks — the "sleep smuggled
            # behind a helper import" a module-local graph cannot see
            imported = self._imported_blocking(src, node, imports)
            if imported:
                out.append(self.violation(
                    src, node,
                    f"{imported} (reachable from the dispatch path via "
                    f"{sym}, through a helper import)"))
        return out

    def _blocking(self, src: SourceFile, node: ast.Call) -> str | None:
        dotted = src.resolve_dotted(node.func)
        if dotted in _BLOCKING_CALLS:
            return _BLOCKING_CALLS[dotted]
        if dotted and dotted.startswith("subprocess."):
            return f"{dotted}() launches a blocking subprocess on the " \
                   "dispatch path"
        return None

    # -- package-level traversal ---------------------------------------------

    def _imported_blocking(self, src: SourceFile, node: ast.Call,
                           imports: dict) -> str | None:
        """Resolve `fn()` / `mod.fn()` through the file's import bindings to
        a function in another module of this package (or the fixture's
        directory) and report the first blocking call reachable from it."""
        target: tuple[str, str] | None = None
        if isinstance(node.func, ast.Name):
            target = imports.get(node.func.id)
        elif (isinstance(node.func, ast.Attribute)
              and isinstance(node.func.value, ast.Name)):
            mod = imports.get(node.func.value.id)
            if mod is not None and mod[1] == "*module*":
                target = (mod[0], node.func.attr)
        if target is None:
            return None
        path, func = target
        return self._func_blocks(path, func, depth=0, seen=set())

    def _func_blocks(self, path: str, func: str, depth: int,
                     seen: set) -> str | None:
        """Does module-level function `func` in the module at `path` reach a
        blocking call (its own body, same-module callees, or further
        imports, depth-bounded)? Declared drain points — the sanctioned
        blocking boundaries — and explicit G007 disables stop the
        traversal."""
        if depth > _MAX_IMPORT_DEPTH or (path, func) in seen:
            return None
        seen.add((path, func))
        helper = self._load_helper(path)
        if helper is None:
            return None
        fns = [f for f in helper.functions if f.qualname == func]
        if not fns or any(f.drain_point for f in fns):
            return None  # undefined here, or a declared sanctioned boundary
        spans = [(f.start, f.end) for f in fns]
        imports = _import_bindings(helper)
        for node in ast.walk(helper.tree):
            if not isinstance(node, ast.Call):
                continue
            if not any(s <= node.lineno <= e for s, e in spans):
                continue
            if helper.enclosing_symbol(node.lineno) != func:
                # a nested def inside the helper is its own (possibly
                # thread-targeted) context — don't charge it to the caller
                continue
            if helper.in_drain_point(node.lineno):
                continue
            if helper.directives.disabled(self.code, node.lineno):
                continue
            msg = self._blocking(helper, node)
            if msg:
                return (f"{msg} — in {helper.rel}:{node.lineno} "
                        f"({func})")
            # same-module callee
            if isinstance(node.func, ast.Name):
                callee = node.func.id
                if any(f.qualname == callee for f in helper.functions):
                    hit = self._func_blocks(path, callee, depth + 1, seen)
                    if hit:
                        return hit
            # further imports
            target = None
            if isinstance(node.func, ast.Name):
                target = imports.get(node.func.id)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)):
                mod = imports.get(node.func.value.id)
                if mod is not None and mod[1] == "*module*":
                    target = (mod[0], node.func.attr)
            if target is not None:
                hit = self._func_blocks(target[0], target[1], depth + 1, seen)
                if hit:
                    return hit
        return None

    def _load_helper(self, path: str) -> SourceFile | None:
        # the shared parse cache: one SourceFile per helper per process,
        # whichever interprocedural rule asked first
        return dataflow.LOADER.load(path)

    def _reachable(self, src: SourceFile) -> set[str]:
        """Qualnames reachable from the dispatch-path roots over same-module
        calls (Name calls resolve innermost-nested-first, then module level;
        self.X calls resolve to any same-module method named X)."""
        by_last: dict[str, set[str]] = {}
        for f in src.functions:
            by_last.setdefault(f.qualname.rsplit(".", 1)[-1], set()).add(
                f.qualname)
        edges: dict[str, set[str]] = {f.qualname: set()
                                      for f in src.functions}
        # one walk: attribute calls and name calls per enclosing function
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = src.enclosing_symbol(node.lineno)
            if caller == "<module>":
                continue
            callee: str | None = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in ("self", "cls")):
                callee = node.func.attr
            if callee and callee in by_last:
                # prefer a nested function of the caller, else any match
                nested = {q for q in by_last[callee]
                          if q.startswith(f"{caller}.")}
                edges.setdefault(caller, set()).update(
                    nested or by_last[callee])
        roots = {f.qualname for f in src.functions
                 if f.qualname.rsplit(".", 1)[-1] in self.ROOTS}
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        # a nested function belongs to its parent's thread context
        for f in src.functions:
            if any(f.qualname.startswith(f"{r}.") for r in list(seen)):
                seen.add(f.qualname)
        return seen


def _find_def(helper: SourceFile,
              fname: str) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
    """First def named `fname` in the helper (module-level functions is
    the shape import bindings hand us; a shadowing nested def would have
    the same body anyway for taint purposes)."""
    for node in ast.walk(helper.tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == fname):
            return node
    return None


# import resolution lives in dataflow.py since the concurrency rules joined
# (G018/G019/G020 resolve imports identically); re-exported names keep the
# G015 subclass and the tests importing from here working
_package_root = dataflow.package_root
_import_bindings = dataflow.import_bindings
