"""G002 unordered-reduction-in-parity-scope and G003 reserved-leaf-access.

G002 is PR 3's sketch-merge rule made mechanical: modules under the
bit-parity contract (the federated round, mode transforms, sketch algebra)
may not introduce `lax.psum` / `psum_scatter` / unordered all-reduces — a
ring psum reassociates the floating-point reduce per topology and breaks the
mesh == single-device bit-identity the parity tests pin (arXiv:2007.07682's
linearity argument makes the ordered partial-sketch merge legal; it says
nothing about reassociated merges). The sanctioned merge is all_gather +
ordered sum: `csvec.merge_tables` / `modes.merge_partial_wires`.

G003 guards the `_valid` reserved batch leaf (PR 4): only
`engine.split_valid` may consume it (and the faults module, which injects
it). Direct reads of `_`-prefixed batch leaves anywhere else bypass the
pop-before-compute discipline and leak the control row into gradients.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

_UNORDERED = {"psum", "psum_scatter", "all_reduce"}


class UnorderedReduction(Rule):
    code = "G002"
    name = "unordered-reduction-in-parity-scope"
    fixit = ("merge partials with all_gather + ordered sum "
             "(csvec.merge_tables / modes.merge_partial_wires) — a psum "
             "reassociates fp and breaks the mesh==single-device parity pin")

    SCOPE = (
        f"{PACKAGE}/federated/",
        f"{PACKAGE}/modes/",
        f"{PACKAGE}/sketch/",
    )

    def applies(self, rel: str) -> bool:
        return rel.startswith(self.SCOPE)

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = src.resolve_dotted(node.func)
            if dotted is None:
                continue
            last = dotted.rsplit(".", 1)[-1]
            if last in _UNORDERED:
                out.append(self.violation(
                    src, node,
                    f"{last}() is an unordered cross-device reduction in a "
                    "module under the bit-parity contract"))
        return out


class ReservedLeafAccess(Rule):
    code = "G003"
    name = "reserved-leaf-access"
    fixit = ("consume the validity mask via engine.split_valid(batch) — it "
             "pops the leaf and returns (batch, valid) without mutating the "
             "caller's dict")

    # the one consumer and the one injector of reserved leaves
    ALLOWED_FUNCTIONS = {"split_valid"}
    ALLOWED_FILES = (f"{PACKAGE}/resilience/faults.py",)

    def applies(self, rel: str) -> bool:
        return rel.startswith(f"{PACKAGE}/") or not rel.startswith("tests/")

    def check(self, src: SourceFile) -> list[Violation]:
        if src.rel in self.ALLOWED_FILES:
            return []
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            key_node = self._reserved_key_read(src, node)
            if key_node is None:
                continue
            chain = {f.qualname.rsplit(".", 1)[-1]
                     for f in src.enclosing_functions(node.lineno)}
            if chain & self.ALLOWED_FUNCTIONS:
                continue
            out.append(self.violation(
                src, node,
                "direct read of a reserved `_`-prefixed batch leaf "
                f"({self._key_repr(key_node)}) outside split_valid/faults"))
        return out

    def _reserved_key_read(self, src: SourceFile,
                           node: ast.AST) -> ast.expr | None:
        """The key expression when `node` READS a reserved leaf: a
        Load-context subscript `x['_k']` / `x[VALID_KEY]`, or `.get('_k')` /
        `.pop('_k')`. Writes (Store/Del subscripts) are the injection side
        and stay legal — prepare_round installs the mask."""
        if isinstance(node, ast.Subscript):
            if not isinstance(node.ctx, ast.Load):
                return None
            return node.slice if self._is_reserved(src, node.slice) else None
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "pop") and node.args):
            key = node.args[0]
            return key if self._is_reserved(src, key) else None
        return None

    @staticmethod
    def _is_reserved(src: SourceFile, key: ast.expr) -> bool:
        if (isinstance(key, ast.Constant) and isinstance(key.value, str)
                and key.value.startswith("_")):
            return True
        # symbolic references to the reserved key constant
        if isinstance(key, ast.Name) and key.id == "VALID_KEY":
            return True
        if isinstance(key, ast.Attribute) and key.attr == "VALID_KEY":
            return True
        return False

    @staticmethod
    def _key_repr(key: ast.expr) -> str:
        if isinstance(key, ast.Constant):
            return repr(key.value)
        return ast.unparse(key)
