"""G016 per-submission-copy-in-fastpath.

The zero-copy fast path (``--serve_fastpath``) exists to make the
ingest-to-merge route touch each accepted table's bytes ONCE: the frame is
decoded straight into its pinned ring slot (serve/ring.py), the uploader
ships ring views to the device, and the merge consumes the device stack.
Its whole performance claim dies by a thousand cuts — one well-meaning
``np.frombuffer(...).copy()`` here, one per-item ``np.stack`` there — and
none of those regressions fail a test, because the bytes are identical
either way (the bitwise pin cannot see a copy). This rule is the
regression tripwire the tests cannot be.

Detection, in the declared fast-path modules (the transports, the batched
gauntlet, and the ring itself):

- any call resolving through the import table into ``base64.*`` — frame
  text decoding belongs to ``validate_payload`` (G011's boundary), never
  to the transport or gauntlet hot loop;
- ``numpy.stack`` — the slow path's per-round stack copy is exactly what
  the ring replaces; a stack call in fast-path scope is the old copy
  sneaking back in;
- ``.copy()`` chained directly onto a ``numpy.frombuffer(...)`` call —
  the classic "defensive" per-submission duplication of freshly decoded
  frame bytes.

The ONE sanctioned per-submission copy — the write into the pinned ring
slot (``serve.ring.RingSlot.write``) — is declared with ``# graftlint:
ring-write`` on the line above its ``def`` and is exempt. Everything else
in scope must move views, not bytes.
"""

from __future__ import annotations

import ast

from .core import PACKAGE, Rule, SourceFile, Violation

# the declared fast-path modules: every function here is on (or one call
# from) the per-submission hot loop
_FASTPATH_MODULES = (
    f"{PACKAGE}/serve/ring.py",
    f"{PACKAGE}/serve/gauntlet.py",
    f"{PACKAGE}/serve/transport.py",
    f"{PACKAGE}/serve/scale/eventloop.py",
)


class PerSubmissionCopyInFastpath(Rule):
    code = "G016"
    name = "per-submission-copy-in-fastpath"
    fixit = ("move views, not bytes: decode into the submission's pinned "
             "ring slot (serve.ring.RingSlot.write, the declared "
             "`# graftlint: ring-write` boundary) or hand the raw frame to "
             "validate_payload — never re-copy or re-stack per-submission "
             "data in fast-path scope")

    def applies(self, rel: str) -> bool:
        return rel in _FASTPATH_MODULES

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if src.in_ring_write(node.lineno):
                continue
            dotted = src.resolve_dotted(node.func)
            if dotted is not None and (dotted == "base64"
                                       or dotted.startswith("base64.")):
                out.append(self.violation(
                    src, node,
                    f"{dotted}() decodes frame text on the fast path — "
                    "frame decoding is validate_payload's job (G011 "
                    "boundary), not the transport/gauntlet hot loop"))
            elif dotted == "numpy.stack":
                out.append(self.violation(
                    src, node,
                    "np.stack() re-materializes a per-round table copy in "
                    "fast-path scope — the pinned ring replaces exactly "
                    "this copy; build views over ring blocks instead"))
            elif self._frombuffer_copy(src, node):
                out.append(self.violation(
                    src, node,
                    "np.frombuffer(...).copy() duplicates freshly decoded "
                    "frame bytes per submission — write them once into "
                    "the ring slot instead"))
        return out

    @staticmethod
    def _frombuffer_copy(src: SourceFile, node: ast.Call) -> bool:
        """`.copy()` chained directly onto a numpy.frombuffer(...) call."""
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "copy"):
            return False
        inner = f.value
        return (isinstance(inner, ast.Call)
                and src.resolve_dotted(inner.func) == "numpy.frombuffer")
