"""graftlint output: human text and `--json` (CI / archival next to the
bench JSONs)."""

from __future__ import annotations

import collections
import json
from typing import IO

from .core import RunResult


def render_text(result: RunResult, out: IO[str]) -> None:
    for v in result.violations:
        out.write(v.format() + "\n")
    if result.stale_baseline:
        out.write(
            f"note: {len(result.stale_baseline)} stale baseline entr"
            f"{'y' if len(result.stale_baseline) == 1 else 'ies'} matched "
            "nothing (fixed or moved — prune with --write-baseline):\n")
        for e in result.stale_baseline:
            out.write(f"    {e['path']}: {e['code']}: {e['line']}\n")
    counts = collections.Counter(v.code for v in result.violations)
    summary = ", ".join(f"{c}={n}" for c, n in sorted(counts.items()))
    out.write(
        f"graftlint: {len(result.violations)} violation(s)"
        + (f" ({summary})" if summary else "")
        + f", {len(result.baselined)} baselined, {result.suppressed} "
        f"suppressed, {result.files_checked} file(s) checked\n")


def render_json(result: RunResult, out: IO[str]) -> None:
    counts: collections.Counter[str] = collections.Counter(
        v.code for v in result.violations)
    doc = {
        "version": 1,
        "ok": result.ok,
        "files_checked": result.files_checked,
        "counts": dict(sorted(counts.items())),
        "violations": [v.as_json() for v in result.violations],
        "baselined": [v.as_json() for v in result.baselined],
        "suppressed": result.suppressed,
        "stale_baseline": result.stale_baseline,
    }
    json.dump(doc, out, indent=2)
    out.write("\n")
