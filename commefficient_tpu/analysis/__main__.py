"""CLI: `python -m commefficient_tpu.analysis [paths] [--json] ...`.

Exit status: 0 clean (after suppressions + baseline), 1 violations found,
2 usage/internal error. `--write-baseline` grandfathers the CURRENT
findings (G002/G003/G004 refuse grandfathering — those contracts admit
none) and exits 0.
"""

from __future__ import annotations

import argparse
import sys

from . import ALL_RULES, RULE_CODES
from .baseline import DEFAULT_BASELINE, Baseline
from .core import Analyzer
from .report import render_json, render_text

# contracts that admit NO grandfathering: parity, reserved leaf, raw
# checkpoint writes — a violation is a bug today, not debt
NO_BASELINE_CODES = ("G002", "G003", "G004")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m commefficient_tpu.analysis",
        description="graftlint: project-aware static analysis "
                    f"({', '.join(RULE_CODES)})",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to analyze (default: the "
                        "commefficient_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report grandfathered sites)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather current findings into --baseline "
                        "and exit 0 (G002/G003/G004 are never written)")
    p.add_argument("--select", default="",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--report-json", default="", metavar="PATH",
                   help="additionally write the JSON report to PATH (one "
                        "analysis run serves both the human text and the "
                        "archived report)")
    args = p.parse_args(argv)

    if args.write_baseline and args.select:
        # a partial-rule rewrite would silently discard every OTHER rule's
        # grandfathered entries (Baseline.write replaces the whole file)
        print("--write-baseline cannot be combined with --select: the "
              "baseline is rewritten whole", file=sys.stderr)
        return 2

    rules = list(ALL_RULES)
    if args.select:
        wanted = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = wanted - set(RULE_CODES)
        if unknown:
            print(f"unknown rule code(s): {', '.join(sorted(unknown))} "
                  f"(valid: {', '.join(RULE_CODES)})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.code in wanted]

    paths = args.paths or None
    if not paths:
        import os

        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]

    baseline = (Baseline.empty() if args.no_baseline or args.write_baseline
                else Baseline.load(args.baseline))
    try:
        result = Analyzer(rules=rules, baseline=baseline).run(paths)
    except (OSError, ValueError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        keep = [v for v in result.violations
                if v.code not in NO_BASELINE_CODES and v.code != "G000"]
        refused = len(result.violations) - len(keep)
        Baseline.write(args.baseline, keep)
        print(f"graftlint: wrote {len(keep)} baseline entr"
              f"{'y' if len(keep) == 1 else 'ies'} to {args.baseline}"
              + (f" (refused {refused}: G000/G002/G003/G004 must be fixed, "
                 "not grandfathered)" if refused else ""))
        return 0

    if args.report_json:
        with open(args.report_json, "w", encoding="utf-8") as f:
            render_json(result, f)
    if args.as_json:
        render_json(result, sys.stdout)
    else:
        render_text(result, sys.stdout)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
