"""CLI: `python -m commefficient_tpu.analysis [paths] [--json] ...`.

Exit status: 0 clean (after suppressions + baseline), 1 violations found,
2 usage/internal error. `--write-baseline` grandfathers the CURRENT
findings (G002/G003/G004 refuse grandfathering — those contracts admit
none) and exits 0.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from . import ALL_RULES, RULE_CODES
from .baseline import DEFAULT_BASELINE, Baseline
from .core import Analyzer
from .report import render_json, render_text

# contracts that admit NO grandfathering: parity, reserved leaf, raw
# checkpoint writes — a violation is a bug today, not debt
NO_BASELINE_CODES = ("G002", "G003", "G004")


def _staged_files() -> list[str] | None:
    """Repo-relative paths staged for commit, or None outside git.
    ACMR: added/copied/modified/renamed — deletions have nothing to lint."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        out = subprocess.run(
            ["git", "diff", "--cached", "--name-only", "--diff-filter=ACMR"],
            capture_output=True, text=True, check=True, cwd=top,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return None
    os.chdir(top)
    return [ln for ln in out.splitlines() if ln]


def _lintable(rel: str) -> bool:
    return rel.endswith(".py") and (
        rel.startswith("commefficient_tpu/")
        or rel in ("cv_train.py", "gpt2_train.py", "bench.py")
    )


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m commefficient_tpu.analysis",
        description="graftlint: project-aware static analysis "
                    f"({', '.join(RULE_CODES)})",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to analyze (default: the "
                        "commefficient_tpu package)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report grandfathered sites)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather current findings into --baseline "
                        "and exit 0 (G002/G003/G004 are never written)")
    p.add_argument("--select", default="",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--report-json", default="", metavar="PATH",
                   help="additionally write the JSON report to PATH (one "
                        "analysis run serves both the human text and the "
                        "archived report)")
    p.add_argument("--jobs", type=int, default=0, metavar="N",
                   help="analyze files across N worker processes "
                        "(default: CPU count; 1 forces serial; the report "
                        "is byte-identical either way)")
    p.add_argument("--changed-only", action="store_true",
                   help="analyze only the staged .py files (git diff "
                        "--cached); falls back to the whole package when "
                        "an analysis/ file itself is staged")
    args = p.parse_args(argv)

    if args.write_baseline and args.select:
        # a partial-rule rewrite would silently discard every OTHER rule's
        # grandfathered entries (Baseline.write replaces the whole file)
        print("--write-baseline cannot be combined with --select: the "
              "baseline is rewritten whole", file=sys.stderr)
        return 2

    rules = list(ALL_RULES)
    if args.select:
        wanted = {c.strip() for c in args.select.split(",") if c.strip()}
        unknown = wanted - set(RULE_CODES)
        if unknown:
            print(f"unknown rule code(s): {', '.join(sorted(unknown))} "
                  f"(valid: {', '.join(RULE_CODES)})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.code in wanted]

    if args.changed_only and args.paths:
        print("--changed-only derives its file list from the git index; "
              "explicit paths would be ignored — pass one or the other",
              file=sys.stderr)
        return 2

    paths = args.paths or None
    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]

    if args.changed_only:
        staged = _staged_files()
        if staged is None:
            print("graftlint: --changed-only requires a git checkout",
                  file=sys.stderr)
            return 2
        if any(s.startswith("commefficient_tpu/analysis/") for s in staged):
            print("graftlint: an analysis/ file is staged — the rules "
                  "themselves changed, linting the whole package",
                  file=sys.stderr)
        else:
            lintable = [s for s in staged if _lintable(s)]
            if not lintable:
                print("graftlint: nothing staged to lint")
                return 0
            paths = [s for s in lintable if os.path.isfile(s)]
            if not paths:
                print("graftlint: nothing staged to lint")
                return 0

    jobs = args.jobs if args.jobs > 0 else (os.cpu_count() or 1)
    baseline = (Baseline.empty() if args.no_baseline or args.write_baseline
                else Baseline.load(args.baseline))
    try:
        result = Analyzer(rules=rules, baseline=baseline).run(paths,
                                                              jobs=jobs)
    except (OSError, ValueError) as e:
        print(f"graftlint: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        keep = [v for v in result.violations
                if v.code not in NO_BASELINE_CODES and v.code != "G000"]
        refused = len(result.violations) - len(keep)
        Baseline.write(args.baseline, keep)
        print(f"graftlint: wrote {len(keep)} baseline entr"
              f"{'y' if len(keep) == 1 else 'ies'} to {args.baseline}"
              + (f" (refused {refused}: G000/G002/G003/G004 must be fixed, "
                 "not grandfathered)" if refused else ""))
        return 0

    if args.report_json:
        with open(args.report_json, "w", encoding="utf-8") as f:
            render_json(result, f)
    if args.as_json:
        render_json(result, sys.stdout)
    else:
        render_text(result, sys.stdout)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
