"""graftlint engine: source model, rule protocol, and the analyzer driver.

Pure `ast` — no imports of the analyzed code, no jax, so the suite runs in a
bare CPU environment in seconds and can never be broken by a backend.

The unit a rule sees is a `SourceFile`: parsed tree, raw lines, directive
state, and a function index (qualnames, spans, enclosing-function lookup,
drain-point marks). Rules are stateless classes with `applies(rel)` scoping
and `check(src) -> [Violation]`; the `Analyzer` owns file loading, directive
suppression, and baseline matching, so a rule only ever reports raw findings.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Iterator

from . import directives
from .baseline import Baseline

PACKAGE = "commefficient_tpu"


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding. `rel` is the project-relative posix path (what the
    baseline and reports key on); `symbol` the enclosing function qualname
    (or '<module>')."""

    code: str
    name: str
    rel: str
    lineno: int
    col: int
    message: str
    fixit: str
    line_text: str
    symbol: str

    def format(self) -> str:
        return (f"{self.rel}:{self.lineno}:{self.col}: {self.code} "
                f"[{self.name}] {self.message}\n"
                f"    {self.line_text.strip()}\n"
                f"    fix: {self.fixit}")

    def as_json(self) -> dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FunctionInfo:
    qualname: str
    start: int  # first decorator line (or the def line)
    def_lineno: int
    end: int
    drain_point: bool
    sketch_boundary: bool = False
    payload_boundary: bool = False
    robust_merge: bool = False
    staleness_fold: bool = False
    ledger_commit: bool = False
    ring_write: bool = False


class SourceFile:
    """A parsed module plus everything rules commonly need from it."""

    def __init__(self, path: str, rel: str, text: str,
                 valid_codes: frozenset[str]):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.directives = directives.parse(text, valid_codes)
        if self.directives.module_override:
            rel = self.directives.module_override
        self.rel = rel.replace(os.sep, "/")
        self.functions = self._index_functions()
        self.module_aliases = self._index_imports()

    # -- function index ------------------------------------------------------

    def _index_functions(self) -> list[FunctionInfo]:
        out: list[FunctionInfo] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    start = min(
                        [child.lineno]
                        + [d.lineno for d in child.decorator_list]
                    )
                    end = child.end_lineno or child.lineno
                    # drain-point / sketch-boundary: marker on the def/
                    # decorator lines or in the contiguous comment block
                    # directly above them
                    cand = set(range(start, child.lineno + 1))
                    ln = start - 1
                    while ln >= 1 and self.line(ln).lstrip().startswith("#"):
                        cand.add(ln)
                        ln -= 1
                    drain = bool(cand & self.directives.drain_linenos)
                    sketch = bool(
                        cand & self.directives.sketch_boundary_linenos)
                    payload = bool(
                        cand & self.directives.payload_boundary_linenos)
                    robust = bool(
                        cand & self.directives.robust_merge_linenos)
                    stale = bool(
                        cand & self.directives.staleness_fold_linenos)
                    ledg = bool(
                        cand & self.directives.ledger_commit_linenos)
                    ring = bool(
                        cand & self.directives.ring_write_linenos)
                    out.append(FunctionInfo(qual, start, child.lineno, end,
                                            drain, sketch, payload, robust,
                                            stale, ledg, ring))
                    visit(child, f"{qual}.")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(self.tree, "")
        return out

    def enclosing_functions(self, lineno: int) -> list[FunctionInfo]:
        """Every function whose span contains `lineno`, outermost first."""
        chain = [f for f in self.functions if f.start <= lineno <= f.end]
        chain.sort(key=lambda f: f.start)
        return chain

    def enclosing_symbol(self, lineno: int) -> str:
        chain = self.enclosing_functions(lineno)
        return chain[-1].qualname if chain else "<module>"

    def in_drain_point(self, lineno: int) -> bool:
        """True when any enclosing function is a declared drain point."""
        return any(f.drain_point for f in self.enclosing_functions(lineno))

    def in_payload_boundary(self, lineno: int) -> bool:
        """True when any enclosing function is the declared wire-payload
        deserialization boundary (G011's sanctioned sites)."""
        return any(f.payload_boundary
                   for f in self.enclosing_functions(lineno))

    def in_sketch_boundary(self, lineno: int) -> bool:
        """True when any enclosing function is a declared flat/ravel
        boundary of the sketch path (G010's sanctioned sites)."""
        return any(f.sketch_boundary
                   for f in self.enclosing_functions(lineno))

    def in_robust_merge(self, lineno: int) -> bool:
        """True when any enclosing function is the declared robust-merge
        boundary (G012's sanctioned order-statistics site)."""
        return any(f.robust_merge
                   for f in self.enclosing_functions(lineno))

    def in_staleness_fold(self, lineno: int) -> bool:
        """True when any enclosing function is the declared staleness-fold
        boundary (G013's sanctioned stale-wire arithmetic site)."""
        return any(f.staleness_fold
                   for f in self.enclosing_functions(lineno))

    def in_ledger_commit(self, lineno: int) -> bool:
        """True when any enclosing function is the declared ledger-commit
        boundary (G014's sanctioned round-ledger append site)."""
        return any(f.ledger_commit
                   for f in self.enclosing_functions(lineno))

    def in_ring_write(self, lineno: int) -> bool:
        """True when any enclosing function is the declared ring-slot
        write boundary (G016's sanctioned per-submission copy site)."""
        return any(f.ring_write
                   for f in self.enclosing_functions(lineno))

    # -- import index --------------------------------------------------------

    def _index_imports(self) -> dict[str, str]:
        """alias -> full module name, for `import x.y as z` and
        `from x import y` (module-ish targets only). Lets rules resolve
        `np.asarray` vs `jnp.asarray` without importing anything."""
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve_dotted(self, node: ast.expr) -> str | None:
        """Dotted name of a call target with the FIRST segment resolved
        through the import table: `jnp.asarray` -> 'jax.numpy.asarray',
        `lax.psum` -> 'jax.lax.psum', plain `open` -> 'open'."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        parts.append(cur.id)
        parts.reverse()
        parts[0] = self.module_aliases.get(parts[0], parts[0])
        return ".".join(parts)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Rule:
    """Base rule. Subclasses set `code`/`name`/`fixit` and implement
    `check`; `applies` scopes by project-relative path (default: the whole
    package)."""

    code: str = ""
    name: str = ""
    fixit: str = ""

    def applies(self, rel: str) -> bool:
        return rel.startswith(f"{PACKAGE}/") or rel.endswith(".py")

    def check(self, src: SourceFile) -> list[Violation]:  # pragma: no cover
        raise NotImplementedError

    def violation(self, src: SourceFile, node: ast.AST, message: str,
                  fixit: str | None = None) -> Violation:
        lineno = getattr(node, "lineno", 1)
        return Violation(
            code=self.code, name=self.name, rel=src.rel, lineno=lineno,
            col=getattr(node, "col_offset", 0), message=message,
            fixit=fixit or self.fixit, line_text=src.line(lineno),
            symbol=src.enclosing_symbol(lineno),
        )


@dataclasses.dataclass
class RunResult:
    violations: list[Violation]       # unsuppressed, unbaselined — failures
    baselined: list[Violation]        # matched a baseline entry
    suppressed: int                   # killed by inline/file directives
    stale_baseline: list[dict[str, str]]  # entries that matched nothing
    files_checked: int

    @property
    def ok(self) -> bool:
        return not self.violations


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif p.endswith(".py") and os.path.isfile(p):
            yield p
        else:
            # a typoed path must fail the gate loudly — silently checking
            # zero files would leave a permanently-green lint gate
            raise ValueError(
                f"not a directory or existing .py file: {p!r}")


def project_rel(path: str) -> str:
    """Project-relative path: anchored at the `commefficient_tpu` package
    when the path contains it, else the basename. Fixture files override
    this with a `# graftlint: module=` directive."""
    norm = os.path.abspath(path).replace(os.sep, "/")
    marker = f"/{PACKAGE}/"
    if marker in norm:
        return PACKAGE + "/" + norm.split(marker, 1)[1]
    return os.path.basename(norm)


class Analyzer:
    """Load files, run every applicable rule, apply directive suppressions
    and the baseline. `rules` defaults to ALL_RULES (late import: rule
    modules import this one)."""

    def __init__(self, rules: Iterable[type[Rule]] | None = None,
                 baseline: Baseline | None = None):
        if rules is None:
            from . import ALL_RULES

            rules = ALL_RULES
        self._rule_classes: list[type[Rule]] = list(rules)
        self.rules: list[Rule] = [r() for r in self._rule_classes]
        self.valid_codes = frozenset(r.code for r in self.rules)
        self.baseline = baseline if baseline is not None else Baseline.empty()
        self._suppressed = 0

    def check_file(self, path: str) -> list[Violation]:
        """All raw findings for one file (directive errors included);
        suppressions and baseline are applied by `run`."""
        with open(path, encoding="utf-8") as f:
            text = f.read()
        src = SourceFile(path, project_rel(path), text, self.valid_codes)
        out: list[Violation] = []
        for lineno, msg in src.directives.errors:
            out.append(Violation(
                code=directives.DIRECTIVE_ERROR_CODE, name="bad-directive",
                rel=src.rel, lineno=lineno, col=0, message=msg,
                fixit="name a valid rule code (see README rule table)",
                line_text=src.line(lineno),
                symbol=src.enclosing_symbol(lineno),
            ))
        for rule in self.rules:
            if rule.applies(src.rel):
                out.extend(rule.check(src))
        # suppressions (G000 is never suppressible: a broken directive must
        # not be silenced by the directive mechanism itself)
        kept: list[Violation] = []
        for v in out:
            if (v.code != directives.DIRECTIVE_ERROR_CODE
                    and src.directives.disabled(v.code, v.lineno)):
                self._suppressed += 1
                continue
            kept.append(v)
        return kept

    def _check_file_counted(self, path: str) -> tuple[list[Violation], int]:
        """check_file plus the per-file suppression count — the unit of
        work the parallel fan-out ships between processes. SyntaxError
        becomes a G000 finding here so workers never raise."""
        before = self._suppressed
        try:
            found = self.check_file(path)
        except SyntaxError as e:
            return ([_parse_error_violation(path, e)], 0)
        return (found, self._suppressed - before)

    def run(self, paths: Iterable[str], jobs: int = 1) -> RunResult:
        """Analyze `paths`. With jobs > 1, files fan out over a process
        pool (per-worker Analyzer rebuilt from the rule CLASSES — rule
        instances hold unpicklable caches); the report is byte-identical
        either way because baseline matching and the final sort happen
        here in the parent, over the same per-file findings."""
        self._suppressed = 0
        files = list(iter_py_files(paths))
        if jobs > 1 and len(files) > 1:
            per_file = self._map_parallel(files, jobs)
        else:
            per_file = [self._check_file_counted(p) for p in files]
        failures: list[Violation] = []
        baselined: list[Violation] = []
        suppressed = 0
        for found, supp in per_file:
            suppressed += supp
            for v in found:
                if self.baseline.matches(v):
                    baselined.append(v)
                else:
                    failures.append(v)
        failures.sort(key=lambda v: (v.rel, v.lineno, v.col, v.code))
        return RunResult(
            violations=failures, baselined=baselined,
            suppressed=suppressed,
            stale_baseline=self.baseline.stale(),
            files_checked=len(files),
        )

    def _map_parallel(self, files: list[str],
                      jobs: int) -> list[tuple[list[Violation], int]]:
        import concurrent.futures as cf

        workers = max(2, min(jobs, len(files)))
        # big-ish chunks amortize the per-task IPC AND let the per-worker
        # rule caches (the G018 scope graph, the shared module loader)
        # serve several files per round trip
        chunk = max(1, len(files) // (workers * 4))
        try:
            with cf.ProcessPoolExecutor(
                    max_workers=workers, initializer=_pool_init,
                    initargs=(tuple(self._rule_classes),)) as ex:
                return list(ex.map(_pool_check, files, chunksize=chunk))
        except Exception:
            # no usable multiprocessing (sandboxed container, unpicklable
            # test-local rule subclass, broken pool): the serial path is
            # always correct, and a genuine rule crash reproduces there
            return [self._check_file_counted(p) for p in files]


def _parse_error_violation(path: str, e: SyntaxError) -> Violation:
    return Violation(
        code="G000", name="parse-error", rel=project_rel(path),
        lineno=e.lineno or 1, col=e.offset or 0,
        message=f"could not parse: {e.msg}",
        fixit="fix the syntax error", line_text="",
        symbol="<module>",
    )


# -- process-pool plumbing (module-level: must be picklable by reference) -----

_WORKER_ANALYZER: Analyzer | None = None


def _pool_init(rule_classes: tuple[type[Rule], ...]) -> None:
    global _WORKER_ANALYZER
    _WORKER_ANALYZER = Analyzer(rules=rule_classes)


def _pool_check(path: str) -> tuple[list[Violation], int]:
    assert _WORKER_ANALYZER is not None
    return _WORKER_ANALYZER._check_file_counted(path)
