"""G017 fork-unsafe-import-in-shard-worker.

The process-sharded ingest (``--serve_shard_mode process``) spawns its
worker processes with the multiprocessing "spawn" start method, and each
worker re-imports its entry module (serve/scale/procshard_worker.py) plus
everything that module pulls in at module level. That import chain must
stay numpy/stdlib-only:

- a worker that imports jax initializes a SECOND copy of the accelerator
  runtime per shard — on TPU that is a hard failure (the device is owned
  by the root process), on CPU it silently multiplies startup cost and
  memory by the shard count;
- the workers are the scale-out story: they move bytes and verdicts,
  never arithmetic. A jax import creeping into the worker chain is the
  first step of arithmetic creeping in after it, which would break the
  served==batch bitwise contract the process shards are pinned to.

The runtime guard (the spawn smoke asserting ``jax`` absent from
``sys.modules``) only fires when someone runs it; this rule is the static
tripwire. Detection, from each declared worker-entry module:

- any MODULE-LEVEL import whose top-level package is fork-unsafe (jax,
  jaxlib, flax, optax) is a direct violation;
- module-level imports into this package are followed transitively —
  through the imported module files AND every package ``__init__.py`` on
  their dotted path (importing ``a.b.c`` executes ``a/__init__`` and
  ``a/b/__init__`` too; that is exactly why serve/ and sketch/ carry lazy
  PEP 562 ``__init__``s) — and a chain that reaches a fork-unsafe import
  is reported at the root import with the path spelled out.

Function-local imports are exempt on both ends: a lazy import inside a
function that only the ROOT process calls is the sanctioned way to keep
device-touching helpers next to worker-safe code (PEP 562 ``__getattr__``
bodies are exactly this shape).
"""

from __future__ import annotations

import ast
import os

from .core import PACKAGE, Rule, SourceFile, Violation

# the declared worker-entry modules: everything importable from these at
# module level runs inside a spawned shard worker / loadgen client process
_WORKER_ENTRY_MODULES = (
    f"{PACKAGE}/serve/scale/procshard_worker.py",
    f"{PACKAGE}/serve/scale/shmring.py",
    f"{PACKAGE}/serve/scale/loadgen.py",
)

# top-level packages whose import initializes an accelerator runtime (or
# transitively always does) — never allowed in a spawned worker's chain
_FORK_UNSAFE = ("jax", "jaxlib", "flax", "optax")

# transitive traversal bound — measured in modules visited, not hops; the
# seen-set makes the walk terminate anyway, this caps pathological trees
_MAX_MODULES = 256


def _top(name: str) -> str:
    return name.split(".")[0]


def _package_root(start: str) -> str | None:
    """Nearest ancestor directory CONTAINING the package dir (same contract
    as rules_sync's resolver — works for real modules and for fixture files
    living outside the package tree)."""
    cur = os.path.dirname(os.path.abspath(start))
    for _ in range(12):
        if os.path.isdir(os.path.join(cur, PACKAGE)):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt
    return None


def _ancestor_inits(root: str, mod_file: str) -> list[str]:
    """Every package __init__.py ON the dotted path to `mod_file` under
    `root` — importing the module executes all of them, so a fork-unsafe
    import in any ancestor __init__ poisons the whole subtree."""
    out: list[str] = []
    rel = os.path.relpath(os.path.abspath(mod_file), root)
    parts = rel.replace(os.sep, "/").split("/")[:-1]
    cur = root
    for p in parts:
        cur = os.path.join(cur, p)
        init = os.path.join(cur, "__init__.py")
        if os.path.isfile(init):
            out.append(init)
    return out


class ForkUnsafeImportInShardWorker(Rule):
    code = "G017"
    name = "fork-unsafe-import-in-shard-worker"
    fixit = ("keep the worker-entry import chain numpy/stdlib-only: move "
             "the device-touching import behind a function body the worker "
             "never calls, or behind a lazy PEP 562 __getattr__ in the "
             "package __init__ (how serve/ and sketch/ stay importable "
             "from spawned shard workers)")

    def __init__(self) -> None:
        # abspath -> SourceFile | None, cached across the analyzer run
        self._modules: dict[str, SourceFile | None] = {}

    def applies(self, rel: str) -> bool:
        return rel in _WORKER_ENTRY_MODULES

    def check(self, src: SourceFile) -> list[Violation]:
        out: list[Violation] = []
        for node in self._module_level_imports(src):
            direct = self._direct_unsafe(node)
            if direct:
                out.append(self.violation(
                    src, node,
                    f"module-level `import {direct}` in a worker-entry "
                    "module — a spawned shard worker re-imports this chain "
                    "and would initialize the accelerator runtime per "
                    "shard"))
                continue
            for mod_file, label in self._in_package_targets(src, node):
                hit = self._chain_unsafe(src.path, mod_file, [label])
                if hit is not None:
                    chain, unsafe = hit
                    out.append(self.violation(
                        src, node,
                        f"worker-entry import chain reaches `import "
                        f"{unsafe}` via {' -> '.join(chain)} — the spawned "
                        "shard worker would pull the accelerator runtime "
                        "in at module import"))
                    break  # one report per root import is enough
        return out

    # -- per-file scanning -----------------------------------------------------

    @staticmethod
    def _module_level_imports(src: SourceFile):
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if src.enclosing_symbol(node.lineno) != "<module>":
                continue  # function-local imports are the sanctioned shape
            yield node

    @staticmethod
    def _direct_unsafe(node: ast.Import | ast.ImportFrom) -> str | None:
        if isinstance(node, ast.Import):
            for a in node.names:
                if _top(a.name) in _FORK_UNSAFE:
                    return a.name
        elif node.level == 0 and node.module:
            if _top(node.module) in _FORK_UNSAFE:
                return node.module
        return None

    def _in_package_targets(self, src: SourceFile,
                            node: ast.Import | ast.ImportFrom):
        """Module FILES a module-level import statement executes: the
        imported module(s) themselves plus every package __init__ on their
        dotted path. Relative imports resolve against the file's REAL
        directory (fixture helpers included); absolute imports resolve
        only within this package."""
        here = os.path.dirname(os.path.abspath(src.path))
        root = _package_root(src.path)
        files: list[tuple[str, str]] = []

        def add(mod_file: str, label: str) -> None:
            if root is not None:
                for init in _ancestor_inits(root, mod_file):
                    files.append((init, _display(root, init)))
            files.append((mod_file, label))

        if isinstance(node, ast.Import):
            for a in node.names:
                if _top(a.name) != PACKAGE or root is None:
                    continue
                parts = a.name.split(".")
                mod_file = os.path.join(root, *parts) + ".py"
                pkg_init = os.path.join(root, *parts, "__init__.py")
                if os.path.isfile(mod_file):
                    add(mod_file, a.name)
                elif os.path.isfile(pkg_init):
                    add(pkg_init, a.name)
            return files
        # ImportFrom: resolve the base, then each name as a submodule (or
        # fall back to the base module file holding the attribute)
        if node.level > 0:
            base = here
            for _ in range(node.level - 1):
                base = os.path.dirname(base)
        elif node.module and _top(node.module) == PACKAGE and root is not None:
            base = root
        else:
            return files
        if node.module:
            base = os.path.join(base, *node.module.split("."))
        for a in node.names:
            if a.name == "*":
                continue
            sub = os.path.join(base, a.name + ".py")
            mod_file = base + ".py"
            pkg_init = os.path.join(base, "__init__.py")
            if os.path.isfile(sub):
                add(sub, _display(root, sub) if root else a.name)
            elif os.path.isfile(mod_file):
                add(mod_file, _display(root, mod_file) if root else a.name)
            elif os.path.isfile(pkg_init):
                add(pkg_init, _display(root, pkg_init) if root else a.name)
        return files

    # -- transitive chain ------------------------------------------------------

    def _chain_unsafe(self, entry_path: str, mod_file: str,
                      chain: list[str]) -> tuple[list[str], str] | None:
        """BFS over module-level imports from `mod_file`; returns the first
        (chain, unsafe-import) found, or None. Explicit G017 disables on
        the offending import line in the HELPER stop the traversal — the
        declared escape hatch for host-only modules that are provably
        never imported by a worker."""
        seen: set[str] = {os.path.abspath(entry_path)}
        frontier: list[tuple[str, list[str]]] = [(mod_file, chain)]
        visited = 0
        while frontier and visited < _MAX_MODULES:
            path, trail = frontier.pop(0)
            apath = os.path.abspath(path)
            if apath in seen:
                continue
            seen.add(apath)
            visited += 1
            helper = self._load(path)
            if helper is None:
                continue
            for node in self._module_level_imports(helper):
                if helper.directives.disabled(self.code, node.lineno):
                    continue
                direct = self._direct_unsafe(node)
                if direct:
                    return trail, direct
                for nxt_file, nxt_label in self._in_package_targets(
                        helper, node):
                    if os.path.abspath(nxt_file) not in seen:
                        frontier.append((nxt_file, trail + [nxt_label]))
        return None

    def _load(self, path: str) -> SourceFile | None:
        apath = os.path.abspath(path)
        if apath in self._modules:
            return self._modules[apath]
        src: SourceFile | None = None
        try:
            with open(apath, encoding="utf-8") as f:
                text = f.read()
            from .core import project_rel

            src = SourceFile(apath, project_rel(apath), text,
                             frozenset({self.code}))
        except (OSError, SyntaxError, ValueError):
            src = None  # unreadable: out of static reach
        self._modules[apath] = src
        return src


def _display(root: str | None, path: str) -> str:
    if root is None:
        return os.path.basename(path)
    return os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
