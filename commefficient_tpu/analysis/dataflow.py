"""Shared dataflow for the flow-sensitive rules.

Two layers, grown in two stages:

1. Lightweight intra-module event ordering for G005/G006
   (donation-after-use, RNG-key-reuse): "within one function, order the
   events touching a local name and look at what happens between two of
   them". Source order is used as the event order — exact for
   straight-line code, an approximation inside branches (documented per
   rule; the repo's round-path code is straight-line where these rules
   bite).

2. The interprocedural substrate the concurrency rules (G018 lock-order,
   G019 unlocked-shared-state, G020 signal-unsafe-handler) and the G001
   taint pass stand on:

   - `ModuleLoader`: parse-once cache over helper modules (keyed by
     path+mtime+size so edited files re-parse), shared by every
     import-following rule in one analyzer run;
   - `import_bindings` / `package_root`: the G007/G015 import-resolution
     machine, moved here from rules_sync so every interprocedural rule
     resolves `from .helper import fn` / `mod.fn()` identically;
   - `lock_bindings` / `flow_events`: discover `threading.Lock()/RLock()/
     Condition()` bindings (module-level names and `self._x` instance
     attributes) and walk a module emitting acquire/call/mutate events
     annotated with WHICH declared locks are held at that point
     (`with`-statement tracking; a nested `def` resets the held set —
     its body runs later, on whatever thread calls it);
   - `local_call_targets`: the shared same-module call resolver
     (nested-first Name lookup, self/cls method dispatch, and
     unique-match `obj.m()` resolution guarded by a generic-name
     denylist);
   - `tainted_names` / `expr_tainted`: fixed-point argument-taint
     propagation that deliberately does NOT flow through `.shape`/
     `.dtype`/`.ndim`/`.size`/`len()` — static metadata is host-safe
     even on traced values.

Still pure `ast`: nothing here imports the analyzed code.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterator

from .core import PACKAGE, SourceFile, project_rel

Pos = tuple[int, int]  # (lineno, col_offset) — source-order event position


def node_pos(node: ast.AST) -> Pos:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def node_end(node: ast.AST) -> Pos:
    return (getattr(node, "end_lineno", 0) or 0,
            getattr(node, "end_col_offset", 0) or 0)


@dataclasses.dataclass(frozen=True)
class NameEvent:
    pos: Pos
    name: str
    is_store: bool
    node: ast.Name


def name_events(func: ast.AST) -> list[NameEvent]:
    """Every Name load/store in `func` (nested defs included — a closure
    capturing a donated buffer is still a use), in source order."""
    out: list[NameEvent] = []
    for node in ast.walk(func):
        if isinstance(node, ast.Name):
            out.append(NameEvent(
                node_pos(node), node.id,
                isinstance(node.ctx, (ast.Store, ast.Del)), node))
    out.sort(key=lambda e: e.pos)
    return out


def direct_functions(func: ast.AST) -> Iterator[ast.AST]:
    """Child statements of `func` excluding nested function bodies — for
    walks that must stay within one function's own straight-line code."""
    for child in ast.iter_child_nodes(func):
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
            yield child


def walk_in_function(func: ast.AST) -> Iterator[ast.AST]:
    """ast.walk over `func`'s own body, NOT descending into nested
    functions/lambdas (their locals are a different scope)."""
    stack: list[ast.AST] = list(direct_functions(func))
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                stack.append(child)


def assign_target_key(node: ast.expr) -> str | None:
    """Registry key for an assignment target we can track: a plain Name
    ('step') or a self/cls attribute ('self._step')."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return f"{node.value.id}.{node.attr}"
    return None


def call_target_key(node: ast.expr) -> str | None:
    """The same key space for a call's target expression."""
    return assign_target_key(node)


def loop_spans(func: ast.AST) -> list[tuple[Pos, Pos]]:
    """(start, end) source spans of every for/while loop in the function's
    own body (comprehensions excluded — their targets rebind per iteration
    in their own scope)."""
    spans = []
    for node in walk_in_function(func):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            spans.append((node_pos(node), node_end(node)))
    return spans


def inside_any(pos: Pos, spans: list[tuple[Pos, Pos]]) -> bool:
    return any(lo <= pos <= hi for lo, hi in spans)


def int_or_tuple_literal(node: ast.expr) -> tuple[int, ...] | None:
    """Evaluate a donate_argnums-style literal: int or tuple/list of ints."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals: list[int] = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None


# == interprocedural substrate (G018/G019/G020 + the G001 taint pass) =========


class ModuleLoader:
    """Parse-once cache over helper modules for the import-following rules.

    Keyed by (abspath, mtime_ns, size) so a file edited between runs (the
    tempfile-rewrite pattern the directive tests use) re-parses, while the
    forty-odd serve/runner/obs modules the concurrency rules sweep parse
    exactly once per process. Unreadable/unparsable modules cache as None —
    out of static reach, never an error."""

    def __init__(self) -> None:
        self._cache: dict[str, tuple[tuple, SourceFile | None]] = {}

    def load(self, path: str) -> SourceFile | None:
        apath = os.path.abspath(path)
        try:
            st = os.stat(apath)
        except OSError:
            return None
        key = (st.st_mtime_ns, st.st_size)
        hit = self._cache.get(apath)
        if hit is not None and hit[0] == key:
            return hit[1]
        src: SourceFile | None = None
        try:
            with open(apath, encoding="utf-8") as f:
                text = f.read()
            src = SourceFile(apath, project_rel(apath), text, _valid_codes())
        except (OSError, SyntaxError, ValueError):
            src = None
        self._cache[apath] = (key, src)
        return src


def _valid_codes() -> frozenset[str]:
    # late import: the package __init__ imports rule modules which import us
    from . import RULE_CODES

    return frozenset(RULE_CODES)


# one shared loader per process: the concurrency rules all sweep the same
# serve/runner/obs files, and parallel workers each get their own copy
LOADER = ModuleLoader()


def package_root(start: str) -> str | None:
    """Nearest ancestor directory CONTAINING the package dir — resolves
    absolute `commefficient_tpu.*` imports from real modules and from
    fixture files living outside the package tree alike."""
    cur = os.path.dirname(os.path.abspath(start))
    for _ in range(12):
        if os.path.isdir(os.path.join(cur, PACKAGE)):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt
    return None


def import_bindings(src: SourceFile) -> dict[str, tuple[str, str]]:
    """name -> (module file path, target) for every import that resolves to
    a file we can statically follow: target is a function name for
    `from .mod import fn`, or the sentinel "*module*" for module bindings
    (`from . import mod`, `import pkg.mod as m`) whose attributes are
    resolved at the call site. Relative imports resolve against the file's
    REAL directory (which makes fixture-local helper modules work); absolute
    imports resolve only within this package."""
    out: dict[str, tuple[str, str]] = {}
    here = os.path.dirname(os.path.abspath(src.path))

    def module_base(level: int, module: str | None) -> str | None:
        if level > 0:
            base = here
            for _ in range(level - 1):
                base = os.path.dirname(base)
        else:
            if not module or module.split(".")[0] != PACKAGE:
                return None
            root = package_root(src.path)
            if root is None:
                return None
            base = root
        if module:
            base = os.path.join(base, *module.split("."))
        return base

    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom):
            base = module_base(node.level, node.module)
            if base is None:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                sub = os.path.join(base, a.name + ".py")
                mod_file = base + ".py"
                pkg_init = os.path.join(base, "__init__.py")
                if os.path.isfile(sub):
                    out[bound] = (sub, "*module*")
                elif os.path.isfile(mod_file):
                    out[bound] = (mod_file, a.name)
                elif os.path.isfile(pkg_init):
                    out[bound] = (pkg_init, a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                parts = a.name.split(".")
                if parts[0] != PACKAGE:
                    continue  # stdlib/third-party: per-rule tables cover it
                root = package_root(src.path)
                if root is None:
                    continue
                mod_file = os.path.join(root, *parts) + ".py"
                pkg_init = os.path.join(root, *parts, "__init__.py")
                bound = a.asname or parts[0]
                if a.asname is None:
                    continue  # dotted access via the bare package name is
                    # not a call-site shape resolve_dotted feeds us
                if os.path.isfile(mod_file):
                    out[bound] = (mod_file, "*module*")
                elif os.path.isfile(pkg_init):
                    out[bound] = (pkg_init, "*module*")
    return out


# -- lock bindings and held-lock flow -----------------------------------------

# constructors whose result is a held-via-`with` synchronization primitive;
# the kind decides reentrancy (G020 exempts RLock) and is named in reports
LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
    "multiprocessing.Lock": "Lock",
    "multiprocessing.RLock": "RLock",
    "multiprocessing.Condition": "Condition",
}

REENTRANT_KINDS = ("RLock",)


@dataclasses.dataclass(frozen=True)
class LockBinding:
    """One discovered lock/condition binding.

    `key` is globally unique across a scope sweep: "{rel}:{Class}.{attr}"
    for instance attributes (`self._cv = threading.Condition()` in class C
    -> "serve/ingest.py:C._cv"), "{rel}:{NAME}" for module-level names.
    `order_name`, when declared via `# graftlint: lock-order <name>` on or
    above the binding assignment, places the lock in the sanctioned global
    acquisition order (names compare lexicographically)."""

    key: str
    kind: str
    rel: str
    lineno: int
    attr: str
    order_name: str | None


def _marker_above(lines: dict[int, str] | set[int], src: SourceFile,
                  lineno: int):
    """Directive marker attached to `lineno`: on the line itself or in the
    contiguous comment block directly above (the def-marker convention)."""
    cand = [lineno]
    ln = lineno - 1
    while ln >= 1 and src.line(ln).lstrip().startswith("#"):
        cand.append(ln)
        ln -= 1
    if isinstance(lines, dict):
        for c in cand:
            if c in lines:
                return lines[c]
        return None
    return any(c in lines for c in cand)


def lock_bindings(src: SourceFile) -> dict[str, LockBinding]:
    """Every lock/condition binding assignment in the module, keyed by the
    lookup key `flow_events` emits (see LockBinding.key)."""
    out: dict[str, LockBinding] = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        kind = LOCK_FACTORIES.get(src.resolve_dotted(value.func) or "")
        if kind is None:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else \
            [node.target]
        order = _marker_above(src.directives.lock_order_names, src,
                              node.lineno)
        for t in targets:
            key = attr = None
            if isinstance(t, ast.Name):
                if src.enclosing_symbol(node.lineno) == "<module>":
                    key, attr = f"{src.rel}:{t.id}", t.id
            elif (isinstance(t, ast.Attribute)
                  and isinstance(t.value, ast.Name)
                  and t.value.id in ("self", "cls")):
                qual = src.enclosing_symbol(node.lineno)
                if "." in qual:  # a method: the class is the prefix
                    cls = qual.rsplit(".", 1)[0]
                    key, attr = f"{src.rel}:{cls}.{t.attr}", t.attr
            if key is not None:
                out[key] = LockBinding(key, kind, src.rel, node.lineno,
                                       attr, order)
    return out


@dataclasses.dataclass(frozen=True)
class FlowEvent:
    """One acquire/call/mutate event with the held-lock context.

    `held` is the tuple of lock-binding keys held (outermost first) when
    the event fires; `symbol` the enclosing function qualname (matching
    SourceFile.functions) or '<module>'. For "mutate", `key` is the
    attribute key "{rel}:{Class}.{attr}"; for "acquire" the lock key; for
    "call" it is empty — the rule resolves the callee from `node`."""

    kind: str
    key: str
    node: ast.AST
    held: tuple[str, ...]
    symbol: str


def _lock_expr_key(node: ast.expr, cls: str | None, rel: str) -> str | None:
    if isinstance(node, ast.Name):
        return f"{rel}:{node.id}"
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls") and cls is not None):
        return f"{rel}:{cls}.{node.attr}"
    return None


def _mutate_key(target: ast.expr, cls: str | None, rel: str) -> str | None:
    """Attribute key a store/del target mutates: `self.x = ...`,
    `self.x += 1`, `self.buf[i] = v` (a store through the subscript still
    mutates the shared object behind self.buf). Plain-name and non-self
    targets are out of scope — G019 is about instance state shared across
    thread roots."""
    base = target
    while isinstance(base, ast.Subscript):
        base = base.value
    if (isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name)
            and base.value.id in ("self", "cls") and cls is not None):
        return f"{rel}:{cls}.{base.attr}"
    return None


def flow_events(src: SourceFile,
                bindings: dict[str, LockBinding]) -> list[FlowEvent]:
    """Walk the module emitting acquire/call/mutate events annotated with
    the locks held at each point. `with lock:` tracking only — the repo
    idiom; bare .acquire()/.release() pairs are per-rule concerns. A nested
    def/lambda resets the held set: its body runs later, on whatever thread
    calls it, not under the locks lexically surrounding the definition."""
    events: list[FlowEvent] = []

    def walk(node: ast.AST, qual: str, cls: str | None,
             held: list[str], symbol: str) -> None:
        if isinstance(node, ast.ClassDef):
            for c in ast.iter_child_nodes(node):
                walk(c, f"{qual}{node.name}.", node.name, held, symbol)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fq = f"{qual}{node.name}"
            for c in ast.iter_child_nodes(node):
                walk(c, f"{fq}.", cls, [], fq)
            return
        if isinstance(node, ast.Lambda):
            walk(node.body, qual, cls, [], symbol)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                key = _lock_expr_key(item.context_expr, cls, src.rel)
                if key is not None and key in bindings:
                    events.append(FlowEvent("acquire", key,
                                            item.context_expr,
                                            tuple(inner), symbol))
                    inner = inner + [key]
                else:
                    walk(item.context_expr, qual, cls, inner, symbol)
            for c in node.body:
                walk(c, qual, cls, inner, symbol)
            return
        if isinstance(node, ast.Call):
            events.append(FlowEvent("call", "", node, tuple(held), symbol))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                               ast.Delete)):
            targets = (node.targets if isinstance(node, (ast.Assign,
                                                         ast.Delete))
                       else [node.target])
            for t in targets:
                mk = _mutate_key(t, cls, src.rel)
                if mk is not None:
                    events.append(FlowEvent("mutate", mk, t, tuple(held),
                                            symbol))
        for c in ast.iter_child_nodes(node):
            walk(c, qual, cls, held, symbol)

    for child in ast.iter_child_nodes(src.tree):
        walk(child, "", None, [], "<module>")
    return events


# -- shared same-module call resolution ---------------------------------------

# method names too generic to resolve by-name through an arbitrary receiver:
# `q.put()` matching a local method `put` would wire unrelated code together
GENERIC_CALL_NAMES = frozenset({
    "get", "set", "put", "append", "pop", "close", "open", "send", "recv",
    "read", "write", "start", "stop", "run", "join", "items", "keys",
    "values", "update", "add", "remove", "clear", "copy", "next", "submit",
    "wait", "notify", "notify_all", "acquire", "release", "encode",
    "decode", "split", "strip", "format", "flush", "seek", "tell",
})


def functions_by_last(src: SourceFile) -> dict[str, set[str]]:
    """last-name-segment -> qualnames, the lookup table local resolution
    keys on."""
    out: dict[str, set[str]] = {}
    for f in src.functions:
        out.setdefault(f.qualname.rsplit(".", 1)[-1], set()).add(f.qualname)
    return out


def local_call_targets(src: SourceFile, node: ast.Call, caller: str,
                       by_last: dict[str, set[str]]) -> set[str]:
    """Same-module qualnames a call may dispatch to. Name calls prefer a
    nested function of the caller; `self.m()`/`cls.m()` prefers methods of
    the caller's own class, else any method named m; `obj.m()` through a
    plain local name resolves only on a UNIQUE match with a non-generic
    name (the honest limit of by-name dispatch)."""
    fn = node.func
    if isinstance(fn, ast.Name):
        cands = by_last.get(fn.id, set())
        if not cands:
            return set()
        nested = {q for q in cands if q.startswith(f"{caller}.")}
        # a Name call cannot dispatch to a method that needs a receiver
        flat = {q for q in cands if "." not in q}
        return nested or flat or set()
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        cands = by_last.get(fn.attr, set())
        if not cands:
            return set()
        if fn.value.id in ("self", "cls"):
            if "." in caller:
                own = caller.rsplit(".", 1)[0]
                same_cls = {q for q in cands
                            if q.rsplit(".", 1)[0] == own}
                if same_cls:
                    return same_cls
            return {q for q in cands if "." in q} or cands
        # plain receiver: only a unique, distinctive name is trustworthy
        if (fn.attr not in GENERIC_CALL_NAMES and len(cands) == 1
                and fn.value.id not in src.module_aliases):
            return cands
    return set()


def import_call_target(src: SourceFile, node: ast.Call,
                       imports: dict[str, tuple[str, str]],
                       ) -> tuple[str, str] | None:
    """(module path, function name) for a call that resolves through the
    file's import bindings — `fn()` from `from .mod import fn`, `mod.fn()`
    from `from . import mod` — or None."""
    fn = node.func
    if isinstance(fn, ast.Name):
        tgt = imports.get(fn.id)
        if tgt is not None and tgt[1] != "*module*":
            return tgt
        return None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        mod = imports.get(fn.value.id)
        if mod is not None and mod[1] == "*module*":
            return (mod[0], fn.attr)
    return None


# -- argument-taint propagation (the G001 interprocedural pass) ---------------

# attribute reads that yield STATIC metadata, host-safe even on a traced
# array — taint must not flow through them (float(x.shape[0]) is fine)
METADATA_ATTRS = frozenset({"shape", "dtype", "ndim", "size"})


def expr_tainted(node: ast.expr, tainted: set[str]) -> bool:
    """Is the value of `node` derived from a tainted name? Structural
    recursion, NOT ast.walk: `.shape`/`.dtype`/`.ndim`/`.size` access and
    `len()` launder taint (static metadata), which a flat walk over Names
    could not express."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in METADATA_ATTRS:
            return False
        return expr_tainted(node.value, tainted)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "len":
            return False
        if isinstance(fn, ast.Attribute) and expr_tainted(fn.value, tainted):
            return True  # method result on a tainted receiver
        args = list(node.args) + [k.value for k in node.keywords]
        return any(expr_tainted(a, tainted) for a in args)
    if isinstance(node, ast.Starred):
        return expr_tainted(node.value, tainted)
    return any(expr_tainted(c, tainted)
               for c in ast.iter_child_nodes(node)
               if isinstance(c, ast.expr))


def tainted_names(func: ast.AST, seeds: set[str]) -> set[str]:
    """Fixed point of local names derived from `seeds` within `func` (own
    body only — nested defs are their own scope). Assignments, augmented
    assignments, for-targets and with-as bindings propagate; metadata
    reads and len() do not (see expr_tainted)."""
    tainted = set(seeds)
    changed = True
    while changed:
        changed = False
        for node in walk_in_function(func):
            pairs: list[tuple[list[ast.expr], ast.expr]] = []
            if isinstance(node, ast.Assign):
                pairs.append((node.targets, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                pairs.append(([node.target], node.value))
            elif isinstance(node, ast.AugAssign):
                pairs.append(([node.target], node.value))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                pairs.append(([node.target], node.iter))
            elif isinstance(node, ast.withitem):
                if node.optional_vars is not None:
                    pairs.append(([node.optional_vars], node.context_expr))
            for targets, value in pairs:
                if not expr_tainted(value, tainted):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if (isinstance(n, ast.Name)
                                and isinstance(n.ctx, ast.Store)
                                and n.id not in tainted):
                            tainted.add(n.id)
                            changed = True
    return tainted


def param_names(func: ast.AST) -> list[str]:
    """Positional-or-keyword parameter names of a def, self/cls excluded
    (the taint seeds and the call-site binding order)."""
    if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    a = func.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names
