"""Lightweight intra-module dataflow for the G005/G006 rules.

Deliberately NOT a real dataflow framework: the two rules that need flow
information (donation-after-use, RNG-key-reuse) both reduce to "within one
function, order the events touching a local name and look at what happens
between two of them". Source order is used as the event order — exact for
straight-line code, an approximation inside branches (documented per rule;
the repo's round-path code is straight-line where these rules bite).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

Pos = tuple[int, int]  # (lineno, col_offset) — source-order event position


def node_pos(node: ast.AST) -> Pos:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def node_end(node: ast.AST) -> Pos:
    return (getattr(node, "end_lineno", 0) or 0,
            getattr(node, "end_col_offset", 0) or 0)


@dataclasses.dataclass(frozen=True)
class NameEvent:
    pos: Pos
    name: str
    is_store: bool
    node: ast.Name


def name_events(func: ast.AST) -> list[NameEvent]:
    """Every Name load/store in `func` (nested defs included — a closure
    capturing a donated buffer is still a use), in source order."""
    out: list[NameEvent] = []
    for node in ast.walk(func):
        if isinstance(node, ast.Name):
            out.append(NameEvent(
                node_pos(node), node.id,
                isinstance(node.ctx, (ast.Store, ast.Del)), node))
    out.sort(key=lambda e: e.pos)
    return out


def direct_functions(func: ast.AST) -> Iterator[ast.AST]:
    """Child statements of `func` excluding nested function bodies — for
    walks that must stay within one function's own straight-line code."""
    for child in ast.iter_child_nodes(func):
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
            yield child


def walk_in_function(func: ast.AST) -> Iterator[ast.AST]:
    """ast.walk over `func`'s own body, NOT descending into nested
    functions/lambdas (their locals are a different scope)."""
    stack: list[ast.AST] = list(direct_functions(func))
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                stack.append(child)


def assign_target_key(node: ast.expr) -> str | None:
    """Registry key for an assignment target we can track: a plain Name
    ('step') or a self/cls attribute ('self._step')."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return f"{node.value.id}.{node.attr}"
    return None


def call_target_key(node: ast.expr) -> str | None:
    """The same key space for a call's target expression."""
    return assign_target_key(node)


def loop_spans(func: ast.AST) -> list[tuple[Pos, Pos]]:
    """(start, end) source spans of every for/while loop in the function's
    own body (comprehensions excluded — their targets rebind per iteration
    in their own scope)."""
    spans = []
    for node in walk_in_function(func):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            spans.append((node_pos(node), node_end(node)))
    return spans


def inside_any(pos: Pos, spans: list[tuple[Pos, Pos]]) -> bool:
    return any(lo <= pos <= hi for lo, hi in spans)


def int_or_tuple_literal(node: ast.expr) -> tuple[int, ...] | None:
    """Evaluate a donate_argnums-style literal: int or tuple/list of ints."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals: list[int] = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            vals.append(elt.value)
        return tuple(vals)
    return None
