"""ResNet-9, cifar10-fast bag-of-tricks lineage (SURVEY.md L0b: the
reference's CV model for CIFAR-10/100).

Structure: prep conv -> (conv+pool) layer with residual -> middle conv+pool ->
(conv+pool) layer with residual -> global maxpool -> linear, with batch norm
after every conv and logits scaled by 0.125.  Written as flax NNX-free linen
for a clean `{"params", "batch_stats"}` split that the federated engine
threads through its `net_state`.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, (3, 3), padding=1, use_bias=False)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5)(x)
        return nn.relu(x)


class Residual(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x, train: bool):
        y = ConvBN(self.features)(x, train)
        y = ConvBN(self.features)(y, train)
        return x + y


class ResNet9(nn.Module):
    num_classes: int = 10
    logit_scale: float = 0.125

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = ConvBN(64)(x, train)  # prep
        x = ConvBN(128)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = Residual(128)(x, train)
        x = ConvBN(256)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBN(512)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = Residual(512)(x, train)
        x = nn.max_pool(x, (4, 4), strides=(4, 4))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes)(x)
        return x * self.logit_scale
