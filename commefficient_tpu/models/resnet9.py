"""ResNet-9, cifar10-fast bag-of-tricks lineage (SURVEY.md L0b: the
reference's CV model for CIFAR-10/100).

Structure: prep conv -> (conv+pool) layer with residual -> middle conv+pool ->
(conv+pool) layer with residual -> global maxpool -> linear, with batch norm
after every conv and logits scaled by 0.125.  Written as flax NNX-free linen
for a clean `{"params", "batch_stats"}` split that the federated engine
threads through its `net_state`.

`dtype` selects the compute dtype for convs/dense (bfloat16 on TPU puts the
convs on the MXU at full rate — the cifar10-fast lineage itself trains in
half precision); params, BN statistics, and logits stay float32 (BN in f32
for stable running stats, logits in f32 for a stable softmax), matching the
GPT-2 path's mixed-precision convention (models/gpt2.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        # BN computes its statistics in float32 regardless of input dtype
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5, dtype=jnp.float32
        )(x)
        return nn.relu(x).astype(self.dtype)


class Residual(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        y = ConvBN(self.features, self.dtype)(x, train)
        y = ConvBN(self.features, self.dtype)(y, train)
        return x + y


class ResNet9(nn.Module):
    num_classes: int = 10
    logit_scale: float = 0.125
    dtype: str = "float32"  # compute dtype: "float32" | "bfloat16"

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32
        x = x.astype(dt)
        x = ConvBN(64, dt)(x, train)  # prep
        x = ConvBN(128, dt)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = Residual(128, dt)(x, train)
        x = ConvBN(256, dt)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBN(512, dt)(x, train)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = Residual(512, dt)(x, train)
        x = nn.max_pool(x, (4, 4), strides=(4, 4))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=dt)(x)
        # logits in float32 for a stable softmax
        return x.astype(jnp.float32) * self.logit_scale
