"""Pretrained GPT-2 weight loader: HuggingFace checkpoint -> flax param tree.

The reference fine-tunes HF's pretrained torch GPT-2-small on PersonaChat
(SURVEY.md §2 Models, §3.2); its PPL targets (BASELINE.md row 3) assume that
initialisation. This maps an HF GPT-2 checkpoint directory (config.json +
pytorch_model.bin or model.safetensors — a local cache dir; there is no
network here) onto `models.gpt2.GPT2LMHead`'s parameter tree.

Layout facts the mapping relies on (verified by the logit-parity test in
tests/test_gpt2_loader.py against HF's torch implementation):
- HF GPT-2 uses Conv1D with weight [in, out] — the same orientation as flax
  Dense kernels, so weights copy without transposes;
- c_attn packs Q|K|V contiguously on the output axis, matching gpt2.py's
  `jnp.split(qkv, 3, axis=-1)`;
- the LM head is tied to wte (no separate weight to load);
- layer-norm epsilon is 1e-5 (GPT2Config.ln_eps default).

Vocab resize (for the PersonaChat special tokens): new wte rows are
initialised to the mean of the pretrained embeddings plus small deterministic
noise — the standard trick so new tokens start "average" instead of far out
of distribution.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np

from .gpt2 import GPT2Config


def _read_state_dict(path: str) -> dict[str, np.ndarray]:
    """{name: float32 ndarray} from a checkpoint file or directory."""
    if os.path.isdir(path):
        for name in ("pytorch_model.bin", "model.safetensors", "flax_model.msgpack"):
            cand = os.path.join(path, name)
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no pytorch_model.bin / model.safetensors under {path}"
            )
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file  # optional dep; gated

        raw = load_file(path)
        return {k: np.asarray(v, dtype=np.float32) for k, v in raw.items()}
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(raw, dict) and "state_dict" in raw:
        raw = raw["state_dict"]
    return {k: v.to(torch.float32).numpy() for k, v in raw.items()}


def _read_config(path: str) -> dict:
    if os.path.isdir(path):
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                return json.load(f)
    return {}


def load_hf_gpt2(
    path: str,
    target_vocab_size: int | None = None,
    n_positions: int | None = None,
    dtype: str = "float32",
) -> tuple[dict, GPT2Config]:
    """Load an HF GPT-2 checkpoint into (flax params, GPT2Config).

    `target_vocab_size` > checkpoint vocab appends mean-initialised rows to
    wte (PersonaChat special tokens); `n_positions` <= checkpoint positions
    slices wpe (shorter contexts compile smaller graphs). Raises on
    shrinking the vocab or growing positions — both silently corrupt a
    pretrained model.
    """
    sd = _read_state_dict(path)
    # strip HF's "transformer." prefix (GPT2LMHeadModel) if present
    sd = {k.removeprefix("transformer."): v for k, v in sd.items()}
    hf_cfg = _read_config(path)

    wte, wpe = sd["wte.weight"], sd["wpe.weight"]
    vocab, n_embd = wte.shape
    layers = sorted(
        {int(k.split(".")[1]) for k in sd if k.startswith("h.")}
    )
    n_layer = len(layers)
    if layers != list(range(n_layer)):
        raise ValueError(f"non-contiguous layer indices in checkpoint: {layers}")
    n_head = int(hf_cfg.get("n_head", 12))
    ln_eps = float(hf_cfg.get("layer_norm_epsilon", 1e-5))

    if target_vocab_size is None:
        target_vocab_size = vocab
    if target_vocab_size < vocab:
        raise ValueError(f"cannot shrink vocab {vocab} -> {target_vocab_size}")
    if target_vocab_size > vocab:
        extra = target_vocab_size - vocab
        mean = wte.mean(axis=0, keepdims=True)
        noise_rng = np.random.RandomState(0)  # deterministic: same init every load
        new_rows = mean + 0.02 * noise_rng.standard_normal((extra, n_embd)).astype(np.float32)
        wte = np.concatenate([wte, new_rows], axis=0)

    if n_positions is None:
        n_positions = wpe.shape[0]
    if n_positions > wpe.shape[0]:
        raise ValueError(
            f"cannot extend positions {wpe.shape[0]} -> {n_positions}: GPT-2's "
            "learned wpe has no values there"
        )
    wpe = wpe[:n_positions]

    cfg = GPT2Config(
        vocab_size=target_vocab_size, n_positions=n_positions, n_embd=n_embd,
        n_layer=n_layer, n_head=n_head, ln_eps=ln_eps, dtype=dtype,
    )

    def ln(prefix):
        return {"scale": jnp.asarray(sd[f"{prefix}.weight"]),
                "bias": jnp.asarray(sd[f"{prefix}.bias"])}

    def dense(prefix):
        return {"kernel": jnp.asarray(sd[f"{prefix}.weight"]),
                "bias": jnp.asarray(sd[f"{prefix}.bias"])}

    params: dict = {"wte": jnp.asarray(wte), "wpe": jnp.asarray(wpe),
                    "ln_f": ln("ln_f")}
    for i in range(n_layer):
        params[f"h_{i}"] = {
            "ln_1": ln(f"h.{i}.ln_1"),
            "ln_2": ln(f"h.{i}.ln_2"),
            "attn": {"c_attn": dense(f"h.{i}.attn.c_attn"),
                     "c_proj": dense(f"h.{i}.attn.c_proj")},
            "mlp": {"c_fc": dense(f"h.{i}.mlp.c_fc"),
                    "c_proj": dense(f"h.{i}.mlp.c_proj")},
        }
    return params, cfg
