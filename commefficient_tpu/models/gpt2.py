"""GPT-2 in flax (SURVEY.md L0b: the reference wraps HuggingFace's torch
GPT-2-small for PersonaChat federated fine-tuning; here the model is native
flax so the whole client step stays inside one XLA program).

TPU-first choices:
- einsum attention with a static causal mask, optionally computed in bfloat16
  (`dtype`) with float32 params and logits;
- optional per-block rematerialisation (`remat`) to trade FLOPs for HBM;
- weights laid out Megatron-style so `parallel.tp.gpt2_partition_specs` can
  shard attention heads / MLP hidden over a 'model' mesh axis;
- optional ring attention (`attn_impl="ring"`) for sequence lengths beyond a
  single chip's HBM — see ops/ring_attention.py.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.1
    dtype: str = "float32"  # compute dtype for activations ("bfloat16" on TPU)
    remat: bool = False
    attn_impl: str = "dense"  # "dense" | "ring" (ring needs a 'seq' mesh axis)
    ring_axis: str = "seq"  # mesh axis ring attention shards T over (the mesh
    # itself comes from jax.set_mesh or an explicit arg — ops/ring_attention)
    with_mc_head: bool = False  # next-utterance-classification head (the
    # transfer-learning-conv-ai double-head the reference inherits: hidden
    # state at each candidate's last token -> linear -> candidate score;
    # SURVEY.md §3.2 "possibly + next-utterance-classification head")
    moe_experts: int = 0  # > 0 replaces every `moe_every`-th block's MLP
    # with a Switch-style top-1 MoE of this many experts (ops/moe.py);
    # shard their [E, ...] leading axis over an 'expert' mesh axis for EP.
    # The reference has no MoE — this is rebuild-side scale headroom.
    moe_every: int = 2  # Switch convention: MoE in every 2nd block
    moe_capacity: float = 1.25  # capacity factor (tokens/expert cap)
    ln_eps: float = 1e-5  # GPT-2 uses 1e-5; needed for pretrained logit parity

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32


def gather_at(x: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """[B, ...rest] rows of x[B, T, ...rest] at per-row positions pos[B]."""
    idx = pos.astype(jnp.int32).reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.take_along_axis(x, idx, axis=1)[:, 0]


TINY = GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=2, dropout=0.0)
SMALL = GPT2Config()  # GPT-2 small: 124M params, the reference's NLP model


class Attention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        B, T, C = x.shape
        qkv = nn.Dense(3 * C, dtype=cfg.compute_dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(B, T, cfg.n_head, cfg.head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.attn_impl == "ring":
            from ..ops.ring_attention import ring_attention

            y = ring_attention(q, k, v, causal=True, axis=cfg.ring_axis)
        else:
            scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, dtype=q.dtype))
            att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), dtype=bool))
            att = jnp.where(mask[None, None], att, jnp.finfo(att.dtype).min)
            att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(q.dtype)
            att = nn.Dropout(cfg.dropout, deterministic=not train)(att)
            y = jnp.einsum("bhqk,bkhd->bqhd", att, v)
        y = y.reshape(B, T, C)
        y = nn.Dense(C, dtype=cfg.compute_dtype, name="c_proj")(y)
        return nn.Dropout(cfg.dropout, deterministic=not train)(y)


class MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.compute_dtype, name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.n_embd, dtype=cfg.compute_dtype, name="c_proj")(h)
        return nn.Dropout(cfg.dropout, deterministic=not train)(h)


class MoEMLP(nn.Module):
    """Switch-style top-1 MoE replacement for the FFN (ops/moe.py). The
    load-balancing aux loss is sown under intermediates/moe_aux; loss
    adapters read it via mutable=['intermediates']."""

    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, train: bool):
        from ..ops import moe

        cfg = self.cfg
        B, T, C = x.shape
        E = cfg.moe_experts
        router = self.param("router", nn.initializers.normal(0.02), (C, E), jnp.float32)
        wi = self.param(
            "wi", nn.initializers.normal(0.02), (E, C, 4 * C), jnp.float32
        )
        wo = self.param(
            "wo", nn.initializers.normal(0.02 / (2 * cfg.n_layer) ** 0.5),
            (E, 4 * C, C), jnp.float32,
        )

        def expert_fn(p, h):
            # expert matmuls (the MoE block's dominant FLOPs) honor the
            # compute dtype like MLP's c_fc/c_proj; routing/dispatch stay f32
            h = h.astype(cfg.compute_dtype)
            y = nn.gelu(h @ p["wi"].astype(cfg.compute_dtype), approximate=True)
            return (y @ p["wo"].astype(cfg.compute_dtype)).astype(jnp.float32)

        y, aux = moe.moe_ffn(
            x.reshape(B * T, C), router, {"wi": wi, "wo": wo}, expert_fn,
            capacity_factor=cfg.moe_capacity,
        )
        self.sow("intermediates", "moe_aux", aux)
        y = y.reshape(B, T, C).astype(cfg.compute_dtype)
        return nn.Dropout(cfg.dropout, deterministic=not train)(y)


class Block(nn.Module):
    cfg: GPT2Config
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        eps = self.cfg.ln_eps
        x = x + Attention(self.cfg, name="attn")(nn.LayerNorm(epsilon=eps, name="ln_1")(x), train)
        mlp = MoEMLP(self.cfg, name="moe_mlp") if self.use_moe else MLP(self.cfg, name="mlp")
        x = x + mlp(nn.LayerNorm(epsilon=eps, name="ln_2")(x), train)
        return x


class GPT2LMHead(nn.Module):
    """Causal LM with tied input/output embeddings (as GPT-2); optional
    next-utterance-classification head (cfg.with_mc_head)."""

    cfg: GPT2Config

    @nn.compact
    def __call__(
        self, input_ids, train: bool = True, token_type_ids=None,
        mc_positions=None, logit_positions=None,
    ):
        cfg = self.cfg
        B, T = input_ids.shape
        wte = self.param(
            "wte", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.n_embd), jnp.float32
        )
        wpe = self.param(
            "wpe", nn.initializers.normal(0.01), (cfg.n_positions, cfg.n_embd), jnp.float32
        )
        x = wte[input_ids] + wpe[:T][None]
        if token_type_ids is not None:
            # dialog-segment embeddings looked up in wte (HF GPT-2 semantics;
            # the transfer-learning-conv-ai packing tags every token with its
            # speaker's special token — see data/personachat.py)
            x = x + wte[token_type_ids]
        x = x.astype(cfg.compute_dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.n_layer):
            use_moe = cfg.moe_experts > 0 and i % cfg.moe_every == cfg.moe_every - 1
            x = block(cfg, use_moe, name=f"h_{i}")(x, train)
        x = nn.LayerNorm(epsilon=cfg.ln_eps, name="ln_f")(x)
        if logit_positions is not None:
            # decode fast path (models/generate.py): logits at ONE position
            # per row — [B, V] instead of [B, T, V]. With GPT-2's 50k vocab
            # the per-step head einsum shrinks T-fold; everything upstream
            # (the transformer stack) is unchanged.
            x_at = gather_at(x, logit_positions)
            return jnp.einsum("bc,vc->bv", x_at.astype(jnp.float32), wte)
        # tied LM head; logits in float32 for a stable softmax
        lm_logits = jnp.einsum("btc,vc->btv", x.astype(jnp.float32), wte)
        if not cfg.with_mc_head:
            return lm_logits
        # declared unconditionally (init/apply must agree); consumed only
        # when the caller passes candidate-final positions
        mc_w = self.param(
            "mc_head", nn.initializers.normal(0.02), (cfg.n_embd,), jnp.float32
        )
        if mc_positions is None:
            return lm_logits
        h_last = gather_at(x.astype(jnp.float32), mc_positions)
        # [B, E] hidden at each sequence's mc token
        return lm_logits, h_last @ mc_w
