"""Loss-function adapters binding flax models to the engine's protocol
(engine.py: loss_fn(params, net_state, batch, rng) -> (loss, aux))."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_classification_loss(model, train: bool):
    """Masked softmax cross-entropy for image classifiers with BN state.

    batch = {"x": [B, H, W, C], "y": [B] int, "mask": [B] 0/1}. Metrics are
    sums (loss_sum, count, correct) so they aggregate across clients/batches.
    """

    def loss_fn(params, net_state, batch, rng):
        variables = {"params": params, **net_state}
        if train:
            logits, new_model_state = model.apply(
                variables, batch["x"], train=True, mutable=["batch_stats"]
            )
            new_net_state = dict(new_model_state)
        else:
            logits = model.apply(variables, batch["x"], train=False)
            new_net_state = net_state
        logp = jax.nn.log_softmax(logits)
        per_ex = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1)[:, 0]
        mask = batch["mask"].astype(per_ex.dtype)
        count = jnp.maximum(mask.sum(), 1.0)
        loss = (per_ex * mask).sum() / count
        correct = ((logits.argmax(-1) == batch["y"]) * mask).sum()
        return loss, {
            "net_state": new_net_state,
            "metrics": {
                "loss_sum": (per_ex * mask).sum(),
                "count": mask.sum(),
                "correct": correct,
            },
        }

    return loss_fn


def make_lm_loss(model, train: bool):
    """Next-token cross-entropy for causal LMs.

    batch = {"input_ids": [B, T] int, "labels": [B, T] int with -100 = ignore,
    optionally "token_type_ids": [B, T] int (PersonaChat speaker segments)}.
    Metrics: loss_sum / count (token-level) -> PPL = exp(loss_sum / count).
    """

    def loss_fn(params, net_state, batch, rng):
        logits = model.apply(
            {"params": params},
            batch["input_ids"],
            train=train,
            token_type_ids=batch.get("token_type_ids"),
            rngs={"dropout": rng} if (train and rng is not None) else None,
        )
        # shift: predict token t+1 from prefix ..t
        logits = logits[:, :-1]
        labels = batch["labels"][:, 1:]
        mask = (labels != -100).astype(logits.dtype)
        safe_labels = jnp.maximum(labels, 0)
        logp = jax.nn.log_softmax(logits)
        per_tok = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        count = jnp.maximum(mask.sum(), 1.0)
        loss = (per_tok * mask).sum() / count
        correct = ((logits.argmax(-1) == safe_labels) * mask).sum()
        return loss, {
            "net_state": net_state,
            "metrics": {
                "loss_sum": (per_tok * mask).sum(),
                "count": mask.sum(),
                "correct": correct,
            },
        }

    return loss_fn
