"""Loss-function adapters binding flax models to the engine's protocol
(engine.py: loss_fn(params, net_state, batch, rng) -> (loss, aux))."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_classification_loss(model, train: bool):
    """Masked softmax cross-entropy for image classifiers with BN state.

    batch = {"x": [B, H, W, C], "y": [B] int, "mask": [B] 0/1}. Metrics are
    sums (loss_sum, count, correct) so they aggregate across clients/batches.
    """

    def loss_fn(params, net_state, batch, rng):
        variables = {"params": params, **net_state}
        if train:
            logits, new_model_state = model.apply(
                variables, batch["x"], train=True, mutable=["batch_stats"]
            )
            new_net_state = dict(new_model_state)
        else:
            logits = model.apply(variables, batch["x"], train=False)
            new_net_state = net_state
        logp = jax.nn.log_softmax(logits)
        per_ex = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1)[:, 0]
        mask = batch["mask"].astype(per_ex.dtype)
        count = jnp.maximum(mask.sum(), 1.0)
        loss = (per_ex * mask).sum() / count
        correct = ((logits.argmax(-1) == batch["y"]) * mask).sum()
        return loss, {
            "net_state": new_net_state,
            "metrics": {
                "loss_sum": (per_ex * mask).sum(),
                "count": mask.sum(),
                "correct": correct,
            },
        }

    return loss_fn


def make_lm_mc_loss(model, train: bool, mc_coef: float = 1.0, pad_id: int = 0):
    """Joint LM + next-utterance-classification loss (the transfer-learning-
    conv-ai double-head objective the reference inherits — SURVEY.md §3.2).

    batch = {"input_ids": [B, C, T], "token_type_ids": [B, C, T],
    "labels": [B, C, T] (-100 = ignore; only the gold candidate carries
    reply labels), "mc_label": [B] int (gold candidate index; -100 = padded
    example)}. Every candidate runs through the transformer (flattened to
    [B*C, T]); the MC head scores each candidate's last non-pad token and a
    softmax CE over the C candidates is added with weight `mc_coef`.
    Metrics add mc_correct / mc_count (mc_acc = mc_correct / mc_count).
    """

    def loss_fn(params, net_state, batch, rng):
        ids = batch["input_ids"]
        B, C, T = ids.shape
        flat = lambda a: a.reshape(B * C, T)  # noqa: E731
        # last non-pad position of every candidate (pad is only ever a tail)
        lengths = jnp.maximum((flat(ids) != pad_id).sum(-1), 1)
        lm_logits, mc_logits = model.apply(
            {"params": params},
            flat(ids),
            train=train,
            token_type_ids=flat(batch["token_type_ids"]),
            mc_positions=lengths - 1,
            rngs={"dropout": rng} if (train and rng is not None) else None,
        )
        # LM term: only the gold candidate carries labels (distractors are
        # all -100 by construction), so gather it BEFORE the vocab softmax —
        # the [B*C, T, V] log_softmax would be C-fold wasted work/memory
        gold = jnp.maximum(batch["mc_label"], 0)  # [B]; pad rows -> 0 (masked)
        V = lm_logits.shape[-1]
        lm_lgt = jnp.take_along_axis(
            lm_logits.reshape(B, C, T, V), gold[:, None, None, None], axis=1
        )[:, 0, :-1]
        labels = jnp.take_along_axis(
            batch["labels"], gold[:, None, None], axis=1
        )[:, 0, 1:]
        mask = (labels != -100).astype(lm_lgt.dtype)
        safe = jnp.maximum(labels, 0)
        logp = jax.nn.log_softmax(lm_lgt)
        per_tok = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        count = jnp.maximum(mask.sum(), 1.0)
        lm_loss = (per_tok * mask).sum() / count
        lm_correct = ((lm_lgt.argmax(-1) == safe) * mask).sum()

        # MC term: softmax CE over candidates
        scores = mc_logits.reshape(B, C)
        mc_label = batch["mc_label"]
        mc_mask = (mc_label >= 0).astype(scores.dtype)
        safe_mc = jnp.maximum(mc_label, 0)
        mc_logp = jax.nn.log_softmax(scores, axis=-1)
        per_ex = -jnp.take_along_axis(mc_logp, safe_mc[:, None], axis=1)[:, 0]
        mc_count = jnp.maximum(mc_mask.sum(), 1.0)
        mc_loss = (per_ex * mc_mask).sum() / mc_count
        mc_correct = ((scores.argmax(-1) == safe_mc) * mc_mask).sum()

        loss = lm_loss + mc_coef * mc_loss
        return loss, {
            "net_state": net_state,
            "metrics": {
                "loss_sum": (per_tok * mask).sum(),
                "count": mask.sum(),
                "correct": lm_correct,
                "mc_loss_sum": (per_ex * mc_mask).sum(),
                "mc_count": mc_mask.sum(),
                "mc_correct": mc_correct,
            },
        }

    return loss_fn


def make_lm_loss(model, train: bool, moe_aux_coef: float = 0.0):
    """Next-token cross-entropy for causal LMs.

    batch = {"input_ids": [B, T] int, "labels": [B, T] int with -100 = ignore,
    optionally "token_type_ids": [B, T] int (PersonaChat speaker segments)}.
    Metrics: loss_sum / count (token-level) -> PPL = exp(loss_sum / count).
    `moe_aux_coef > 0` (MoE models) adds the Switch load-balancing aux sown
    by MoEMLP, averaged over MoE layers.
    """

    def loss_fn(params, net_state, batch, rng):
        kwargs = dict(
            train=train,
            token_type_ids=batch.get("token_type_ids"),
            rngs={"dropout": rng} if (train and rng is not None) else None,
        )
        moe_aux = jnp.float32(0.0)
        if moe_aux_coef > 0:
            logits, inter = model.apply(
                {"params": params}, batch["input_ids"],
                mutable=["intermediates"], **kwargs,
            )
            auxs = jax.tree.leaves(inter)
            moe_aux = sum(jnp.asarray(a).mean() for a in auxs) / max(len(auxs), 1)
        else:
            logits = model.apply({"params": params}, batch["input_ids"], **kwargs)
        # shift: predict token t+1 from prefix ..t
        logits = logits[:, :-1]
        labels = batch["labels"][:, 1:]
        mask = (labels != -100).astype(logits.dtype)
        safe_labels = jnp.maximum(labels, 0)
        logp = jax.nn.log_softmax(logits)
        per_tok = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
        count = jnp.maximum(mask.sum(), 1.0)
        loss = (per_tok * mask).sum() / count + moe_aux_coef * moe_aux
        correct = ((logits.argmax(-1) == safe_labels) * mask).sum()
        metrics = {
            "loss_sum": (per_tok * mask).sum(),
            "count": mask.sum(),
            "correct": correct,
        }
        if moe_aux_coef > 0:
            # sum + count pair: the engine SUMS metrics over clients/local
            # iters (and evaluate() over batches), so a bare mean would read
            # cohort-size-inflated — normalize via moe_aux_sum/moe_aux_count
            metrics["moe_aux_sum"] = moe_aux
            metrics["moe_aux_count"] = jnp.float32(1.0)
        return loss, {"net_state": net_state, "metrics": metrics}

    return loss_fn
