"""Autoregressive decoding for the PersonaChat eval path (SURVEY.md §2 "NLP
training CLI": the reference's eval is NLL/PPL "+ optionally F1/sampling" —
this supplies the sampling/F1 half; the transfer-learning-conv-ai lineage the
reference inherits evaluates generated replies with word-level F1).

TPU-idiomatic shape discipline: the decode loop is a `lax.scan` over a FIXED
number of steps on a FIXED [B, T] token buffer — no dynamic shapes, one
compiled program regardless of prompt lengths or early <eos>. Each step runs
a full forward over the buffer and reads the logits at every row's own
current position; positions past a finished row (<eos> emitted) keep <pad>.
A KV cache would cut per-step FLOPs ~T/2-fold, but eval decodes a handful of
examples per round — compile simplicity wins (the buffer forward is the same
XLA program the PPL eval already runs).

Sampling: temperature 0 = greedy argmax; otherwise nucleus (top-p) sampling
in sorted-logit space (sort desc, keep the smallest prefix with cumulative
probability >= top_p, always at least the mode, categorical over the kept
prefix, map back through the sort permutation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _nucleus_pick(logits, rng, temperature: float, top_p: float):
    """[B, V] logits -> [B] sampled token ids (greedy when temperature==0)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.float32(temperature)
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    order = jnp.argsort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep every token whose PRECEDING cumulative mass is < top_p (the mode's
    # preceding mass is 0, so at least one survives)
    keep = (cum - probs) < jnp.float32(top_p)
    filtered = jnp.where(keep, sorted_logits, -jnp.inf)
    pick = jax.random.categorical(rng, filtered, axis=-1)  # index in sorted space
    return jnp.take_along_axis(order, pick[:, None], axis=-1)[:, 0]


def make_generate(
    model,
    *,
    eos_id: int,
    pad_id: int,
    reply_type_id: int,
    max_new: int,
    temperature: float = 0.0,
    top_p: float = 0.9,
    last_logit_only: bool = True,
):
    """Build a jitted decode fn for a GPT2LMHead-style model.

        generate(params, ids, types, prompt_len, rng) -> (ids', lengths)

    - ids/types: [B, T] packed buffers; positions >= prompt_len[b] must be
      <pad> (they are overwritten as generation proceeds).
    - prompt_len: [B] int32, number of conditioning tokens per row (the reply
      speaker token included — generation continues the model's own turn).
    - ids' has up to `max_new` generated tokens written from prompt_len[b];
      lengths[b] = prompt_len[b] + number of tokens generated before <eos>
      (the <eos> itself is not counted, mirroring the packing where labels
      end at <eos>).
    """

    def step_logits(params, ids, types, pos):
        """[B, V] logits at each row's position `pos` (predicting pos+1).
        GPT2LMHead's logit_positions fast path computes the vocab einsum at
        the one needed position per row; models without that kwarg (e.g.
        test stubs) take last_logit_only=False and gather from [B, T, V]."""
        if last_logit_only:
            return model.apply(
                {"params": params}, ids, train=False, token_type_ids=types,
                logit_positions=pos,
            )
        out = model.apply({"params": params}, ids, train=False, token_type_ids=types)
        # with_mc_head models return just lm_logits when mc_positions is None
        out = out[0] if isinstance(out, tuple) else out
        from .gpt2 import gather_at

        return gather_at(out, pos)

    @jax.jit
    def generate(params, ids, types, prompt_len, rng):
        B, T = ids.shape
        rows = jnp.arange(B)

        def body(carry, step_rng):
            ids, types, cur, done = carry
            # logits at position cur-1 predict the token at cur
            nxt = _nucleus_pick(
                step_logits(params, ids, types, jnp.maximum(cur - 1, 0)),
                step_rng, temperature, top_p,
            ).astype(ids.dtype)
            in_range = cur < T
            write = (~done) & in_range
            nxt = jnp.where(write, nxt, pad_id)
            pos = jnp.minimum(cur, T - 1)
            ids = ids.at[rows, pos].set(jnp.where(write, nxt, ids[rows, pos]))
            types = types.at[rows, pos].set(
                jnp.where(write, reply_type_id, types[rows, pos])
            )
            done = done | (nxt == eos_id) | ~in_range
            cur = cur + write.astype(cur.dtype)
            return (ids, types, cur, done), None

        done0 = jnp.zeros((B,), bool)
        cur0 = prompt_len.astype(jnp.int32)
        (ids, types, cur, _), _ = jax.lax.scan(
            body, (ids, types, cur0, done0), jax.random.split(rng, max_new)
        )
        # lengths exclude a trailing <eos> if one was written
        wrote_eos = (ids[rows, jnp.maximum(cur - 1, 0)] == eos_id) & (
            cur > prompt_len
        )
        return ids, cur - wrote_eos.astype(cur.dtype)

    return generate


def decode_reply(tok, ids_row, prompt_len: int, length: int) -> str:
    """Detokenize the generated span of one row (host-side)."""
    span = [int(t) for t in ids_row[prompt_len:length]]
    return tok.decode(span)


@functools.lru_cache(maxsize=None)
def _norm_word(w: str) -> str:
    return "".join(ch for ch in w.lower() if ch.isalnum())


def word_f1(pred: str, gold: str) -> float:
    """ConvAI2-style word-level F1: bag-of-words overlap of the normalized
    (lowercased, punctuation-stripped) prediction vs the gold reply."""
    p = [w for w in (_norm_word(t) for t in pred.split()) if w]
    g = [w for w in (_norm_word(t) for t in gold.split()) if w]
    if not p or not g:
        return float(p == g)
    from collections import Counter

    common = Counter(p) & Counter(g)
    overlap = sum(common.values())
    if overlap == 0:
        return 0.0
    precision = overlap / len(p)
    recall = overlap / len(g)
    return 2 * precision * recall / (precision + recall)
