"""Small FEMNIST CNN (SURVEY.md L0b): the LEAF-standard 2-conv network for
62-class handwritten character recognition on 28x28 inputs.  `dtype` follows
the ResNet-9 convention: compute dtype only, params and logits float32."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class FEMNISTCNN(nn.Module):
    num_classes: int = 62
    dtype: str = "float32"  # compute dtype: "float32" | "bfloat16"

    @nn.compact
    def __call__(self, x, train: bool = True):
        dt = jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32
        x = x.astype(dt)
        x = nn.Conv(32, (5, 5), padding=2, dtype=dt)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding=2, dtype=dt)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(2048, dtype=dt)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=dt)(x).astype(jnp.float32)
