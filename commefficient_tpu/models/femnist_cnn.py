"""Small FEMNIST CNN (SURVEY.md L0b): the LEAF-standard 2-conv network for
62-class handwritten character recognition on 28x28 inputs."""

from __future__ import annotations

import flax.linen as nn


class FEMNISTCNN(nn.Module):
    num_classes: int = 62

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(32, (5, 5), padding=2)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding=2)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(2048)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
