"""Ring attention: causal attention with the sequence axis sharded over the
device mesh, K/V blocks rotating over ICI via `ppermute`.

Long-context support is absent from the reference (SURVEY.md §5 "Long-context
/ sequence parallelism: absent" — PersonaChat fits in GPT-2's window); it is
first-class here so the GPT-2 path scales past one chip's HBM.  Design is the
standard blockwise/flash online-softmax accumulation: each device keeps its
query block and a running (max, sum, acc) triple; at every ring step it
attends its queries against the visiting K/V block, then passes that block to
the next device.  All control flow is a `lax.scan` over ring steps — one
compiled program, no dynamic shapes; communication is `ppermute` neighbor
exchange, which XLA schedules on ICI concurrently with the block matmuls.

Layout contract: q, k, v are [B, T, H, D] with T sharded over the mesh axis
(`seq`); the output has the same layout.  Mesh resolution, most explicit
first (VERDICT r2 weak #5 — no module-level ambient state):

1. the `mesh=` argument to `ring_attention` (callers that thread it);
2. JAX's own context mesh (`jax.set_mesh(mesh)` around the call/trace) when
   it carries the ring axis — the standard, thread-local, jit-cache-correct
   way for model code (flax modules can't take a Mesh in their config);
3. otherwise a plain masked-softmax fallback, so the same model code works
   single-chip.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils import jax_compat

_NEG = -1e30


def _context_mesh(axis: str):
    """The mesh installed via jax.set_mesh, if it shards the ring axis."""
    m = jax_compat.get_abstract_mesh()
    if m is not None and axis in m.axis_names and m.shape[axis] > 1:
        return m
    return None


@contextlib.contextmanager
def use_ring_mesh(mesh: Optional[Mesh], axis: str = "seq"):
    """Back-compat alias for `jax.set_mesh` (the axis travels with the mesh's
    own name now; `axis` is kept for signature stability and must match a
    mesh axis). Prefer `jax.set_mesh(mesh)` directly in new code."""
    if mesh is None:
        yield
        return
    if axis not in mesh.axis_names:
        raise ValueError(f"ring axis {axis!r} not in mesh axes {mesh.axis_names}")
    if axis != "seq":
        # the context mesh can't carry a custom axis name to ring_attention;
        # only the explicit argument can
        raise NotImplementedError(
            f"use_ring_mesh can only install the default 'seq' axis; pass "
            f"axis={axis!r} to ring_attention (or set GPT2Config.ring_axis) "
            "and use jax.set_mesh directly"
        )
    with jax_compat.set_mesh(mesh):
        yield


def _dense_causal(q, k, v):
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], dtype=q.dtype))
    att = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    T = q.shape[1]
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    att = jnp.where(mask[None, None], att, _NEG)
    att = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", att, v)


def _ring_local(q, k, v, *, axis: str, ring_size: int):
    """Body run under shard_map: local blocks [B, Tl, H, D]."""
    B, Tl, H, D = q.shape
    my = jax.lax.axis_index(axis)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, dtype=jnp.float32))
    qf = q.astype(jnp.float32) * scale
    q_pos = my * Tl + jnp.arange(Tl)

    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def step(carry, s):
        k_blk, v_blk, m, l, acc = carry
        src = (my - s) % ring_size  # whose K/V block we hold this step
        k_pos = src * Tl + jnp.arange(Tl)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]  # causal, global ids
        scores = jnp.where(mask, scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None]) * mask
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        k_nxt = jax.lax.ppermute(k_blk, axis, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new), None

    # the accumulators become device-varying inside the scan (axis_index use),
    # so mark the initial values varying over the ring axis up front
    varying = lambda x: jax_compat.pcast(x, (axis,), to="varying")
    m0 = varying(jnp.full((B, H, Tl), _NEG, dtype=jnp.float32))
    l0 = varying(jnp.zeros((B, H, Tl), dtype=jnp.float32))
    acc0 = varying(jnp.zeros((B, H, Tl, D), dtype=jnp.float32))
    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(ring_size)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, H, Tl, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # -> [B, Tl, H, D]


def ring_attention(q, k, v, causal: bool = True, mesh=None, axis: str = "seq"):
    """Causal attention over a seq-sharded [B, T, H, D]; see module docstring.

    `mesh` (explicit) or the jax.set_mesh context supplies the ring; with
    neither this is a plain (flash-style numerics) causal attention — the
    single-chip path of the same model code.
    """
    if not causal:
        raise NotImplementedError("ring_attention is causal-only (LM path)")
    if mesh is None:
        mesh = _context_mesh(axis)
    if mesh is None:
        return _dense_causal(q, k, v)
    ring_size = mesh.shape[axis]
    body = functools.partial(_ring_local, axis=axis, ring_size=ring_size)
    spec = P(None, axis, None, None)
    return jax_compat.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=jax_compat.CHECK_REP,
    )(q, k, v)
