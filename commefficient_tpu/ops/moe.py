"""Expert parallelism: Switch-Transformer-style top-1 mixture-of-experts FFN
with the expert axis sharded over the mesh.

The reference has no MoE (its models are ResNet-9 and GPT-2-small —
SURVEY.md §2); this op completes the rebuild's parallelism coverage
(dp/tp/sp/pp/ep) the TPU-native way: routing is expressed as dense one-hot
dispatch/combine einsums (the GShard/Switch recipe — no gather/scatter, no
dynamic shapes, capacity overflow dropped), so sharding the expert axis of
the dispatched activations and expert weights over the mesh turns the
einsums into an all-to-all + per-device expert matmuls, all inserted by XLA
from the shardings alone.

Semantics (top-1, capacity factor c):
- router logits [T, E] -> gate = softmax; expert = argmax.
- each expert processes at most C = ceil(c * T / E) tokens (position within
  the expert's queue via a cumsum over arrival order); overflow tokens pass
  through unchanged (standard Switch behavior).
- output = gate * expert_out + (1 - routed) * x  (dropped tokens keep x).
- aux load-balancing loss = E * sum_e f_e * p_e (Switch eq. 4), returned so
  callers can add `aux_coef * aux` to their objective.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp


def top1_dispatch(router_logits: jnp.ndarray, capacity: int):
    """Dispatch/combine tensors for top-1 routing.

    router_logits: [T, E]. Returns (dispatch [T, E, C] bool-ish float,
    combine [T, E, C] float, aux scalar). Token t occupies slot
    (its arrival position among tokens routed to e) in expert e's queue iff
    that position < capacity.
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # [T, E]
    expert = jnp.argmax(probs, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [T, E]
    # position of each token in its expert's queue (arrival order)
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot  # [T, E]; 0-based
    kept = onehot * (pos < capacity)  # [T, E]
    slot = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = kept[:, :, None] * slot[:, None, :]  # [T, E, C]
    gate = (probs * kept).sum(-1)  # [T]
    combine = dispatch * gate[:, None, None]
    # Switch load-balancing aux: E * sum_e (fraction routed to e) * (mean prob e)
    frac = onehot.mean(0)
    mean_p = probs.mean(0)
    aux = E * jnp.sum(frac * mean_p)
    return dispatch, combine, aux


def moe_ffn(
    x: jnp.ndarray,
    router_w: jnp.ndarray,
    expert_params,
    expert_fn: Callable,
    *,
    capacity_factor: float = 1.25,
):
    """Top-1 MoE FFN over tokens x [T, D].

    `expert_params` leaves have leading axis [E] (shard it over the mesh's
    expert axis; with x replicated or batch-sharded, XLA lowers the dispatch
    einsum to an all-to-all). `expert_fn(params_e, h [C, D]) -> [C, D]`
    applies one expert. Returns (y [T, D], aux).

    Capacity overflow and unrouted mass degrade to identity (residual MoE
    blocks add x outside), matching Switch's pass-through behavior.
    """
    T, D = x.shape
    E = jax.tree.leaves(expert_params)[0].shape[0]
    C = max(1, math.ceil(capacity_factor * T / E))
    logits = x.astype(jnp.float32) @ router_w  # [T, E]
    dispatch, combine, aux = top1_dispatch(logits, C)
    # [T, E, C] x [T, D] -> [E, C, D]: expert-major queues
    h = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    y = jax.vmap(expert_fn)(expert_params, h)  # [E, C, D]
    out = jnp.einsum("tec,ecd->td", combine, y.astype(jnp.float32))
    routed = combine.sum((1, 2))  # [T] gate mass that actually landed
    out = out + (1.0 - routed)[:, None] * x.astype(jnp.float32)
    return out.astype(x.dtype), aux


def dense_oracle(x, router_w, expert_params, expert_fn):
    """Every token through its argmax expert with NO capacity limit — the
    correctness oracle moe_ffn must match when capacity is not binding."""
    T, D = x.shape
    probs = jax.nn.softmax(x.astype(jnp.float32) @ router_w, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    # run EVERY expert on ALL tokens, select after (oracle only — O(E*T*D))
    all_y = jax.vmap(lambda p: expert_fn(p, x.astype(jnp.float32)))(expert_params)
    sel = all_y[expert, jnp.arange(T)]  # [T, D]
    # same residual convention as moe_ffn: (1 - gate) of every token's mass
    # stays on x (no token is dropped here, so routed == gate)
    return (gate[:, None] * sel + (1.0 - gate)[:, None] * x.astype(jnp.float32)).astype(x.dtype)
