"""Pipeline parallelism: GPipe-style microbatched stage execution over a
`pipe` mesh axis.

The reference has no pipeline parallelism (its models fit one device —
SURVEY.md §2 "Parallelism strategies present"); this op makes layer-sharded
execution available to the rebuild's larger-model paths the TPU-native way:
one compiled program, `shard_map` over the pipe axis, activations flowing
stage s -> s+1 by `ppermute` each step, a `lax.scan` over the
fill-drain schedule. Backward works by autodiff (the transpose of a
ppermute is the reverse ppermute), so `jax.grad` through `pipeline_apply`
yields the standard GPipe backward with no special handling.

Layout contract:
- `stage_params`: pytree whose leaves have leading axis [S] (one slice per
  stage), sharded `P("pipe")` on the mesh. Each stage applies
  `stage_fn(stage_slice, x)` — typically a scan over that stage's layers.
- `x`: [M, mb, ...] microbatches, replicated. Returns [M, mb, ...].

Schedule: T = M + S - 1 steps. At step t, stage 0 ingests microbatch t (if
t < M); every stage applies its layers to the buffer it holds; buffers
rotate one stage forward; the LAST stage's output at step t is microbatch
t - (S-1), written into the output buffer when valid. Bubble fraction is
(S-1)/T, the usual GPipe fill/drain cost — pick M >= 4*S in practice.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils import jax_compat


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    axis: str = "pipe",
):
    """Run microbatches [M, mb, ...] through S pipeline stages; see module
    docstring. `stage_fn(params_slice, x_mb) -> y_mb` applies ONE stage's
    layers (shapes of x_mb and y_mb must match — residual-block style)."""
    S = mesh.shape[axis]
    M = x.shape[0]
    if M < 1:
        raise ValueError("need at least one microbatch")
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"stage_params leading axis must equal the {S}-stage pipe "
                f"axis, got {leaf.shape[0]} — per-layer stacks go through "
                "stack_stages(params, num_stages) first"
            )

    def per_stage(params, xs):
        # params: stage's slice, leading axis [1]; xs: [M, mb, ...] (full copy)
        stage = jax.lax.axis_index(axis)
        my_params = jax.tree.map(lambda a: a[0], params)
        # carries become device-varying (axis_index use) — mark them varying
        # up front so scan/where types agree (same dance as ring attention)
        varying = lambda a: jax_compat.pcast(a, (axis,), to="varying")  # noqa: E731
        buf = varying(jnp.zeros_like(xs[0]))
        out = varying(jnp.zeros_like(xs))
        fwd = [(i, (i + 1) % S) for i in range(S)]

        def step(carry, t):
            buf, out = carry
            # stage 0 ingests microbatch t (clamped; masked by validity)
            ingest = jnp.where(t < M, t, M - 1)
            buf = jnp.where(stage == 0, xs[ingest], buf)
            y = stage_fn(my_params, buf)
            # last stage completed microbatch t-(S-1) this step; record it
            # (unconditional masked write — a varying predicate can't drive
            # lax.cond)
            done_idx = t - (S - 1)
            valid = (stage == S - 1) & (done_idx >= 0)
            idx = jnp.maximum(done_idx, 0)
            cur = jax.lax.dynamic_index_in_dim(out, idx, axis=0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, cur), idx, axis=0
            )
            # rotate buffers one stage forward (stage 0 receives garbage from
            # the last stage; it is overwritten by the next ingest)
            buf = jax.lax.ppermute(y, axis, fwd)
            return (buf, out), None

        (buf, out), _ = jax.lax.scan(step, (buf, out), jnp.arange(M + S - 1))
        # every stage holds a copy of `out`, but only the last stage's is
        # real — broadcast it so out_specs can be replicated
        out = jax.lax.psum(
            jnp.where(stage == S - 1, out, jnp.zeros_like(out)), axis
        )
        return out

    pspec = jax.tree.map(lambda _: P(axis), stage_params)
    return jax_compat.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_rep=jax_compat.CHECK_REP,
    )(stage_params, x)


def stack_stages(per_layer_params, num_stages: int):
    """[L, ...] per-layer stacked params -> [S, L//S, ...] per-stage slices
    (stage s owns layers s*L//S .. (s+1)*L//S - 1)."""

    def reshape(a):
        L = a.shape[0]
        if L % num_stages:
            raise ValueError(f"{L} layers not divisible by {num_stages} stages")
        return a.reshape(num_stages, L // num_stages, *a.shape[1:])

    return jax.tree.map(reshape, per_layer_params)


def scan_stage(layer_fn: Callable):
    """Lift a per-layer fn into a stage fn: scans the stage's [Lps, ...]
    layer slice over the activation."""

    def stage_fn(stage_params, x):
        def body(h, layer_params):
            return layer_fn(layer_params, h), None

        y, _ = jax.lax.scan(body, x, stage_params)
        return y

    return stage_fn
