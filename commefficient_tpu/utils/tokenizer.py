"""Tokenization for the NLP path.

The reference tokenizes PersonaChat with HuggingFace's GPT-2 BPE (SURVEY.md
§2 "Fed datasets": transfer-learning-conv-ai lineage).  This environment has
no network, so we use the cached HF tokenizer when present and otherwise a
byte-level fallback with the same interface — every pipeline stage
(persona grouping, packing, masking, PPL eval) is exercised identically;
only the subword inventory differs.
"""

from __future__ import annotations

import numpy as np


class ByteTokenizer:
    """Byte-level tokenizer: 256 byte values + bos/eos/pad specials."""

    def __init__(self):
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 259

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8", errors="replace"))

    def decode(self, ids) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    def __init__(self, tok):
        self.tok = tok
        self.bos_id = tok.bos_token_id
        self.eos_id = tok.eos_token_id
        self.pad_id = tok.eos_token_id  # GPT-2 has no pad token
        self.vocab_size = int(tok.vocab_size)

    def encode(self, text: str) -> list[int]:
        return self.tok.encode(text)

    def decode(self, ids) -> str:
        return self.tok.decode(list(ids))


def get_tokenizer():
    try:
        from transformers import GPT2TokenizerFast

        return HFTokenizer(GPT2TokenizerFast.from_pretrained("gpt2", local_files_only=True))
    except Exception:
        return ByteTokenizer()


def pack_sequence(ids: list[int], seq_len: int, pad_id: int) -> tuple[np.ndarray, np.ndarray]:
    """(input_ids[T], labels[T]) — labels are input_ids with pad masked to
    -100 (ignored by the LM loss)."""
    ids = ids[:seq_len]
    x = np.full(seq_len, pad_id, dtype=np.int32)
    y = np.full(seq_len, -100, dtype=np.int32)
    x[: len(ids)] = ids
    y[: len(ids)] = ids
    return x, y
