"""Tokenization for the NLP path.

The reference tokenizes PersonaChat with HuggingFace's GPT-2 BPE (SURVEY.md
§2 "Fed datasets": transfer-learning-conv-ai lineage).  This environment has
no network, so we use the cached HF tokenizer when present and otherwise a
byte-level fallback with the same interface — every pipeline stage
(persona grouping, packing, masking, PPL eval) is exercised identically;
only the subword inventory differs.
"""

from __future__ import annotations

# PersonaChat dialog specials, transfer-learning-conv-ai lineage (SURVEY.md
# §3.2): bos/eos frame the sequence, speaker1/speaker2 tag utterances (and
# serve as the token_type embedding ids), pad fills to seq_len. Appended to
# the base vocab; gpt2_loader.load_hf_gpt2(target_vocab_size=...) grows the
# pretrained wte to match.
SPECIAL_TOKENS = ("<bos>", "<eos>", "<speaker1>", "<speaker2>", "<pad>")


class ByteTokenizer:
    """Byte-level tokenizer: 256 byte values + the 5 dialog specials."""

    def __init__(self) -> None:
        self.bos_id = 256
        self.eos_id = 257
        self.speaker1_id = 258
        self.speaker2_id = 259
        self.pad_id = 260
        self.vocab_size = 261

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8", errors="replace"))

    def decode(self, ids: "list[int]") -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """GPT-2 BPE with the dialog specials appended (ids >= 50257), as the
    reference's `add_special_tokens_` does before fine-tuning."""

    def __init__(self, tok) -> None:
        self.tok = tok
        tok.add_special_tokens({
            "bos_token": SPECIAL_TOKENS[0],
            "eos_token": SPECIAL_TOKENS[1],
            "pad_token": SPECIAL_TOKENS[4],
            "additional_special_tokens": list(SPECIAL_TOKENS[2:4]),
        })
        self.bos_id = tok.bos_token_id
        self.eos_id = tok.eos_token_id
        self.pad_id = tok.pad_token_id
        self.speaker1_id = tok.convert_tokens_to_ids(SPECIAL_TOKENS[2])
        self.speaker2_id = tok.convert_tokens_to_ids(SPECIAL_TOKENS[3])
        self.vocab_size = len(tok)

    def encode(self, text: str) -> list[int]:
        return self.tok.encode(text)

    def decode(self, ids: "list[int]") -> str:
        return self.tok.decode(list(ids))


def get_tokenizer() -> "ByteTokenizer | HFTokenizer":
    try:
        from transformers import GPT2TokenizerFast

        return HFTokenizer(GPT2TokenizerFast.from_pretrained("gpt2", local_files_only=True))
    except Exception:
        return ByteTokenizer()


