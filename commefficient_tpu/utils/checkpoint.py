"""Checkpoint / resume (SURVEY.md §5: the reference has only a minimal model
save; the rebuild checkpoints the full server state — params, net_state,
Vvelocity/Verror, per-client state, round counter, host RNG — via orbax, so a
run can resume mid-schedule at the exact round)."""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

import jax
import orbax.checkpoint as ocp


def _unpadded_client_state(session):
    """Host copy of per-client state with mesh-padding rows stripped, so a
    checkpoint is portable between sharded and unsharded sessions (the mesh
    session pads [num_clients, d] to a multiple of the client-axis size)."""
    n = session.train_set.num_clients
    return jax.tree.map(lambda a: np.asarray(a)[:n], jax.device_get(session.client_state))


def save(ckpt_dir: str, session, keep: int = 3):
    path = os.path.abspath(os.path.join(ckpt_dir, f"round_{session.round:08d}"))
    payload = {
        "state": jax.device_get(session.state),
        "round": session.round,
    }
    if session.client_state is not None:
        payload["client_state"] = _unpadded_client_state(session)
    ckpt = ocp.PyTreeCheckpointer()
    ckpt.save(path, payload, force=True)
    # host-side sampling RNG, so resumed runs replay the same client sequence
    rng_state = session.rng.get_state()
    np.save(os.path.join(path, "host_rng.npy"),
            np.array([rng_state[0], rng_state[1].tolist(), rng_state[2], rng_state[3],
                      rng_state[4]], dtype=object), allow_pickle=True)
    # measured cumulative communication: per-round figures vary with dropout
    # survivors and local_topk's measured down-link, so round * static-estimate
    # would overstate resumed runs. num_workers makes a cohort-size change
    # across the checkpoint boundary loud at restore (it breaks exact replay).
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"comm_mb_total": float(session.comm_mb_total),
                   "num_workers": session.num_workers}, f)
    _prune(ckpt_dir, keep)
    return path


def latest(ckpt_dir: str) -> str | None:
    # absolute: orbax's tensorstore kvstore REJECTS relative paths at
    # restore time (save() already abspaths), so a relative --checkpoint_dir
    # would save fine and then crash every --resume
    if not os.path.isdir(ckpt_dir):
        return None
    rounds = sorted(d for d in os.listdir(ckpt_dir) if d.startswith("round_"))
    return os.path.abspath(os.path.join(ckpt_dir, rounds[-1])) if rounds else None


def restore(path: str, session) -> None:
    ckpt = ocp.PyTreeCheckpointer()
    template: dict[str, Any] = {
        "state": jax.device_get(session.state),
        "round": 0,
    }
    if session.client_state is not None:
        template["client_state"] = _unpadded_client_state(session)
    payload = ckpt.restore(path, item=template)

    def _place(a, like):
        # Mesh-sharded leaves (TP params, client-sharded local state) keep
        # their NamedSharding; everything else stays an UNCOMMITTED plain
        # array — committing to one device would conflict with sharded
        # batches at the next jit call.
        if isinstance(like.sharding, jax.sharding.NamedSharding):
            return jax.device_put(a, like.sharding)
        return jax.numpy.asarray(a)

    session.state = jax.tree.map(_place, payload["state"], session.state)
    session.round = int(payload["round"])
    if session.client_state is not None:

        def _fit(a, like):
            a = np.asarray(a)
            pad = like.shape[0] - a.shape[0]  # re-pad for the mesh, if any
            if pad:
                a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            return _place(a, like)

        session.client_state = jax.tree.map(_fit, payload["client_state"], session.client_state)
    rng_file = os.path.join(path, "host_rng.npy")
    if os.path.exists(rng_file):
        s = np.load(rng_file, allow_pickle=True)
        session.rng.set_state((s[0], np.asarray(s[1], dtype=np.uint32), int(s[2]),
                               int(s[3]), float(s[4])))
    meta_file = os.path.join(path, "meta.json")
    if os.path.exists(meta_file):
        with open(meta_file) as f:
            meta = json.load(f)
        session.comm_mb_total = float(meta["comm_mb_total"])
        saved_w = meta.get("num_workers")
        if saved_w is not None and saved_w != session.num_workers:
            print(
                f"warning: checkpoint {path} was written with num_workers="
                f"{saved_w} but this session runs {session.num_workers} "
                "(mesh rounding or a flag change?); the resumed run will NOT "
                "replay the uninterrupted client sequence exactly",
                flush=True,
            )
    else:
        # pre-meta checkpoint: fall back to the static per-round estimate
        # (exact when every round is uniform; overstates under dropout)
        session.comm_mb_total = session.round * session.comm_per_round["comm_total_mb"]


def _prune(ckpt_dir: str, keep: int):
    rounds = sorted(d for d in os.listdir(ckpt_dir) if d.startswith("round_"))
    for stale in rounds[:-keep]:
        full = os.path.join(ckpt_dir, stale)
        for root, dirs, files in os.walk(full, topdown=False):
            for f in files:
                os.unlink(os.path.join(root, f))
            for d in dirs:
                os.rmdir(os.path.join(root, d))
        os.rmdir(full)
