"""Checkpoint / resume (SURVEY.md §5: the reference has only a minimal model
save; the rebuild checkpoints the full server state — params, net_state,
Vvelocity/Verror, per-client state, round counter, host RNG — via orbax, so a
run can resume mid-schedule at the exact round).

Hardened for long paper-scale runs (resilience/):

- **Atomic commit**: everything (orbax tree, host RNG, meta, manifest) is
  written into a `.tmp_round_*` staging dir, then `os.rename`d to its final
  `round_*` name. A crash mid-write leaves only a staging dir, which
  `latest()`/`restore_latest()` never consider and the next save sweeps.
- **Integrity manifest**: `manifest.json` records a sha256 per file, written
  last. `verify()` checks it; `restore_latest()` walks newest-to-oldest and
  falls back LOUDLY past any checkpoint that fails verification or restore,
  so a corrupted/truncated latest checkpoint costs one checkpoint interval,
  not the run. `save()` additionally READS BACK the committed files against
  the manifest (silent-bitrot-on-write media fails the save, counted in
  `save_verify_failures()`, retried by the wrapper below).
- **Retries + fault injection**: the write path runs under
  `resilience.retry` (site "ckpt_save"), and a `FaultPlan` can inject
  transient write failures or post-commit corruption to prove the above.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import sys
from typing import Any

import numpy as np

import jax
import orbax.checkpoint as ocp

from ..resilience import retry as rtry

MANIFEST = "manifest.json"
_TMP_PREFIX = ".tmp_round_"
# restore_latest renames a checkpoint that failed verification/restore aside
# to <name>.damaged: it stops being a restore candidate (no re-verifying a
# known-bad tree on every resume), stops counting toward save()'s keep-N
# pruning (damaged trees must not crowd out good ones), and is kept for
# post-mortem — bounded by _gc_damaged (newest KEEP_DAMAGED survive).
_DAMAGED_SUFFIX = ".damaged"
KEEP_DAMAGED = 2


def _round_dirs(ckpt_dir: str) -> list[str]:
    """Restorable-candidate names, sorted: round_* (including .displaced
    rename-aside copies — same round, same state) minus damaged ones."""
    return sorted(
        d for d in os.listdir(ckpt_dir)
        if d.startswith("round_") and not d.endswith(_DAMAGED_SUFFIX)
    )

# process-wide count of committed checkpoints that FAILED the post-commit
# read-back (save-time manifest verification): silent-bitrot-on-write media
# caught in the act. Each failure also raises inside the retry wrapper, so a
# transient flake gets re-written; bench.py surfaces the count in its JSON.
_VERIFY_FAILURES = 0


def save_verify_failures() -> int:
    return _VERIFY_FAILURES


class CheckpointVerifyError(RuntimeError):
    """A just-committed checkpoint failed its read-back against the sha256
    manifest — the write path (or the media under it) silently corrupted
    data. Raised from inside the retry wrapper so bounded retries re-write;
    exhaustion propagates it to the caller LOUDLY."""


def _unpadded_client_state(client_state: Any, num_clients: int) -> Any:
    """Host copy of per-client state with mesh-padding rows stripped, so a
    checkpoint is portable between sharded and unsharded sessions (the mesh
    session pads [num_clients, d] to a multiple of the client-axis size)."""
    return jax.tree.map(lambda a: np.asarray(a)[:num_clients],
                        jax.device_get(client_state))


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_manifest(path: str) -> None:
    sums = {}
    for root, _, files in os.walk(path):
        for f in sorted(files):
            if f == MANIFEST:
                continue
            full = os.path.join(root, f)
            sums[os.path.relpath(full, path)] = _sha256(full)
    with open(os.path.join(path, MANIFEST), "w") as f:
        json.dump({"files": sums}, f)


def verify(path: str) -> bool | None:
    """True: manifest present and every file matches. False: mismatch,
    missing file, or unreadable manifest. None: no manifest (pre-hardening
    checkpoint — can't verify; restore_latest still tries it)."""
    mf = os.path.join(path, MANIFEST)
    if not os.path.exists(mf):
        return None
    try:
        with open(mf) as f:
            sums = json.load(f)["files"]
    except Exception:
        return False
    for rel, digest in sums.items():
        full = os.path.join(path, rel)
        if not os.path.exists(full) or _sha256(full) != digest:
            return False
    return True


def save(ckpt_dir: str, session, keep: int = 3, fault_plan=None,
         retry_policy: rtry.RetryPolicy | None = None,
         verify_on_save: bool = True):
    # capture every session field under the session's mutation lock (when it
    # has one): an emergency save on the watchdog's timer thread must never
    # mix round N's params with round N-1's counter/RNG because the stalled
    # round un-stuck mid-save. jax arrays are immutable, so holding
    # references is a consistent frozen view — the expensive device_get
    # happens after the lock is released. (The references stay READABLE
    # mid-round only because sessions that arm emergency saves disable
    # state donation — FederatedSession donate_state=False; a donated
    # state would be deleted buffers for the whole in-flight round.)
    lock = getattr(session, "mutate_lock", None) or contextlib.nullcontext()
    with lock:
        rnd = session.round
        state_ref = session.state
        client_state_ref = session.client_state
        # RNG as of the last COMPLETED round (FederatedSession.rng_snapshot),
        # not the live streams: mid-round the live streams are already
        # advanced for the in-flight round, and a resumed run would train a
        # different cohort. The device key covers participation masks / DP
        # noise — without it a resumed run replays the client sequence but
        # draws FRESH dropout masks.
        rng_state, device_key = getattr(session, "rng_snapshot", None) or (
            session.rng.get_state(), session._rng_key
        )
        comm_mb_total = float(session.comm_mb_total)
        num_workers = session.num_workers
        # committed re-queue of dropped clients (cohort fault tolerance):
        # like the RNG, the COMMITTED snapshot, not the live queue a
        # prefetcher may already have served for uncommitted rounds. The
        # rounds-waiting ages ride along so a restored --requeue_policy aged
        # queue resumes each entry's REAL age instead of restarting at 1.
        requeued = [int(i) for i in
                    getattr(session, "_requeue_committed", ())]
        requeue_ages = [[int(c), int(r)] for c, r in
                        getattr(session, "_requeue_ages_committed", ())]
        # serving-layer state (serve/): the service registers a callable
        # returning a JSON-safe dict snapshotted at the committed round
        # boundary — the pending arrival queue and, in buffered-async mode,
        # the FULL stale band (parked late tables base64-exact, retained
        # screen state, straggler stash, in-flight stale-poison tables), so
        # an async preempt -> resume replays its stale folds bit-identically
        # (meta.json "serve"); None when the session is driven by the batch
        # simulator
        serve_provider = getattr(session, "serve_meta", None)
        serve_meta = serve_provider() if callable(serve_provider) else None
    final = os.path.abspath(os.path.join(ckpt_dir, f"round_{rnd:08d}"))
    staging = os.path.abspath(os.path.join(ckpt_dir, f"{_TMP_PREFIX}{rnd:08d}"))

    # snapshot the full payload ONCE, outside the retry closure: the state is
    # identical across attempts, and re-pulling hundreds of MB over a
    # tunnelled TPU link on every filesystem flake would make retries
    # expensive exactly when the run is already struggling
    payload = {
        "state": jax.device_get(state_ref),
        "round": rnd,
    }
    if client_state_ref is not None:
        payload["client_state"] = _unpadded_client_state(
            client_state_ref, session.train_set.num_clients
        )
    device_key = np.asarray(jax.device_get(device_key))

    def attempt():
        if fault_plan is not None:
            fault_plan.fire_transient("ckpt_fail", rnd)
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        ocp.PyTreeCheckpointer().save(staging, payload, force=True)
        # host-side sampling RNG, so resumed runs replay the same client
        # sequence
        np.save(os.path.join(staging, "host_rng.npy"),
                np.array([rng_state[0], rng_state[1].tolist(), rng_state[2],
                          rng_state[3], rng_state[4]], dtype=object),
                allow_pickle=True)
        np.save(os.path.join(staging, "device_rng.npy"), device_key)
        # measured cumulative communication: per-round figures vary with
        # dropout survivors and local_topk's measured down-link, so
        # round * static-estimate would overstate resumed runs. num_workers
        # makes a cohort-size change across the checkpoint boundary loud at
        # restore (it breaks exact replay).
        with open(os.path.join(staging, "meta.json"), "w") as f:
            json.dump({"comm_mb_total": comm_mb_total,
                       "num_workers": num_workers,
                       "requeued": requeued,
                       "requeue_ages": requeue_ages,
                       **({"serve": serve_meta}
                          if serve_meta is not None else {})}, f)
        _write_manifest(staging)
        # overwrite (emergency save of a round already checkpointed): rename
        # the committed copy ASIDE first — a delete-then-rename would leave a
        # window (the whole rmtree) where round_N's only copy is gone, and
        # the watchdog's abort stage is designed to fire during this save.
        # The displaced name still starts with "round_", so if the process
        # dies between the two renames, restore_latest() finds the displaced
        # copy (same round, same state — both saves capture the same
        # round-boundary snapshot) instead of silently losing the round.
        old = None
        if os.path.isdir(final):
            old = final + ".displaced"
            if os.path.isdir(old):
                shutil.rmtree(old)
            os.rename(final, old)
        os.rename(staging, final)  # the atomic commit point
        if verify_on_save and verify(final) is not True:
            # read-back of the COMMITTED files against the manifest: media
            # that acknowledges writes and returns different bytes (silent
            # bitrot-on-write) must fail the SAVE loudly, not the restore
            # hours later when this checkpoint is the only copy. Counted,
            # then raised inside the retry wrapper so the write is retried.
            # Runs BEFORE the displaced copy is deleted: a corrupt re-save
            # of an already-checkpointed round must never destroy the
            # verified-good copy it displaced — put it back instead.
            global _VERIFY_FAILURES
            _VERIFY_FAILURES += 1
            if old is not None:
                shutil.rmtree(final, ignore_errors=True)
                os.rename(old, final)
            raise CheckpointVerifyError(
                f"checkpoint {final} failed post-commit read-back "
                "verification (write-path corruption); "
                f"save-verify failures this process: {_VERIFY_FAILURES}"
            )
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        return final

    path = rtry.with_retries(
        attempt, site="ckpt_save", policy=retry_policy, seed=rnd
    )
    if fault_plan is not None:
        # post-commit damage (ckpt_corrupt/ckpt_partial) — lands AFTER the
        # manifest so verification, not luck, has to catch it
        fault_plan.corrupt_checkpoint(rnd, path)
    _prune(ckpt_dir, keep)
    return path


def latest(ckpt_dir: str) -> str | None:
    # absolute: orbax's tensorstore kvstore REJECTS relative paths at
    # restore time (save() already abspaths), so a relative --checkpoint_dir
    # would save fine and then crash every --resume
    if not os.path.isdir(ckpt_dir):
        return None
    rounds = _round_dirs(ckpt_dir)
    return os.path.abspath(os.path.join(ckpt_dir, rounds[-1])) if rounds else None


def restore(path: str, session) -> None:
    ckpt = ocp.PyTreeCheckpointer()
    template: dict[str, Any] = {
        "state": jax.device_get(session.state),
        "round": 0,
    }
    if session.client_state is not None:
        template["client_state"] = _unpadded_client_state(
            session.client_state, session.train_set.num_clients
        )
    payload = ckpt.restore(path, item=template)

    def _place(a, like):
        # Mesh-sharded leaves (TP params, client-sharded local state) keep
        # their NamedSharding; everything else stays an UNCOMMITTED plain
        # array — committing to one device would conflict with sharded
        # batches at the next jit call.
        if isinstance(like.sharding, jax.sharding.NamedSharding):
            return jax.device_put(a, like.sharding)
        return jax.numpy.asarray(a)

    session.state = jax.tree.map(_place, payload["state"], session.state)
    session.round = int(payload["round"])
    if session.client_state is not None:

        def _fit(a, like):
            a = np.asarray(a)
            pad = like.shape[0] - a.shape[0]  # re-pad for the mesh, if any
            if pad:
                a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
            return _place(a, like)

        session.client_state = jax.tree.map(_fit, payload["client_state"], session.client_state)
    rng_file = os.path.join(path, "host_rng.npy")
    if os.path.exists(rng_file):
        s = np.load(rng_file, allow_pickle=True)
        session.rng.set_state((s[0], np.asarray(s[1], dtype=np.uint32), int(s[2]),
                               int(s[3]), float(s[4])))
    key_file = os.path.join(path, "device_rng.npy")
    if os.path.exists(key_file):  # pre-hardening checkpoints lack it
        session._rng_key = jax.numpy.asarray(np.load(key_file))
    if hasattr(session, "_snapshot_rng"):
        session._snapshot_rng()  # restored streams ARE a round boundary
    meta_file = os.path.join(path, "meta.json")
    if os.path.exists(meta_file):
        with open(meta_file) as f:
            meta = json.load(f)
        session.comm_mb_total = float(meta["comm_mb_total"])
        if hasattr(session, "_requeue"):
            import collections

            requeued = [int(i) for i in meta.get("requeued", [])]
            session._requeue = collections.deque(requeued)
            session._requeue_committed = tuple(requeued)
            if hasattr(session, "_requeue_enqueued"):
                # rounds-waiting ages resume exactly (requeue_ages pairs);
                # entries a pre-age checkpoint doesn't cover restart at the
                # restored round (rounds-waiting 1 — the old behavior)
                ages = {int(c): int(r)
                        for c, r in meta.get("requeue_ages", [])}
                session._requeue_enqueued = {
                    cid: ages.get(cid, session.round) for cid in requeued}
                session._requeue_ages_committed = tuple(
                    session._requeue_enqueued.items())
        # serving-layer state for serve/ to pick up when it attaches to the
        # restored session (pending arrival queue etc.); absent = empty
        session.restored_serve_meta = meta.get("serve")
        saved_w = meta.get("num_workers")
        if saved_w is not None and saved_w != session.num_workers:
            print(
                f"warning: checkpoint {path} was written with num_workers="
                f"{saved_w} but this session runs {session.num_workers} "
                "(mesh rounding or a flag change?); the resumed run will NOT "
                "replay the uninterrupted client sequence exactly",
                flush=True,
            )
    else:
        # pre-meta checkpoint: fall back to the static per-round estimate
        # (exact when every round is uniform; overstates under dropout)
        session.comm_mb_total = session.round * session.comm_per_round["comm_total_mb"]


def _set_aside_damaged(ckpt_dir: str, name: str) -> None:
    """Rename a failed candidate to <name>.damaged: no longer a restore/
    prune candidate (see _DAMAGED_SUFFIX), kept for post-mortem until
    _gc_damaged reaps it."""
    src = os.path.join(ckpt_dir, name)
    dst = src + _DAMAGED_SUFFIX
    try:
        if os.path.isdir(dst):
            shutil.rmtree(dst, ignore_errors=True)
        os.rename(src, dst)
    except OSError as e:
        # best effort (read-only media, races): the restore fallback worked
        # either way, the rename only dedupes future verification work
        print(f"warning: could not set damaged checkpoint aside "
              f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)


def _gc_damaged(ckpt_dir: str, keep: int = KEEP_DAMAGED) -> int:
    """Bound the .damaged graveyard: keep the newest `keep`, delete the
    rest, return the deletion count (loud). Without this, chaos runs with
    ckpt_corrupt plans grow one immortal damaged tree per injection."""
    names = sorted(d for d in os.listdir(ckpt_dir)
                   if d.endswith(_DAMAGED_SUFFIX))
    stale = names[:-keep] if keep > 0 else names
    for name in stale:
        shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)
    if stale:
        print(
            f"checkpoint GC: deleted {len(stale)} damaged checkpoint(s) "
            f"beyond the newest {keep} ({', '.join(stale)})",
            file=sys.stderr, flush=True,
        )
    return len(stale)


def restore_latest(ckpt_dir: str, session) -> str | None:
    """Restore the newest checkpoint that verifies AND restores, falling
    back loudly past damaged ones — each failed candidate is renamed aside
    to <name>.damaged (kept for post-mortem, garbage-collected beyond the
    newest KEEP_DAMAGED) so later resumes never re-verify known-bad trees
    and save()'s keep-N pruning never counts them. Returns the restored
    path, or None when the directory holds no checkpoints (a fresh run).
    Raises when checkpoints exist(ed) but ALL are unrecoverable — silently
    restarting a long run from round 0 would be the worst outcome."""
    if not os.path.isdir(ckpt_dir):
        return None
    rounds = sorted(_round_dirs(ckpt_dir), reverse=True)
    if not rounds:
        if any(d.endswith(_DAMAGED_SUFFIX) for d in os.listdir(ckpt_dir)):
            # every checkpoint was already set aside as damaged by an
            # earlier resume: this is NOT a fresh run, refuse round 0
            raise RuntimeError(
                f"no restorable checkpoint in {ckpt_dir}: only damaged "
                "checkpoints remain (set aside by a previous restore)"
            )
        return None
    restored_path = None
    skipped = 0
    for name in rounds:
        path = os.path.abspath(os.path.join(ckpt_dir, name))
        if verify(path) is False:
            print(
                f"ERROR: checkpoint {path} FAILED integrity verification "
                "(corrupt or partial write); falling back to the previous "
                "verified-good checkpoint",
                file=sys.stderr, flush=True,
            )
            _set_aside_damaged(ckpt_dir, name)
            skipped += 1
            continue
        try:
            restore(path, session)
        except Exception as e:  # noqa: BLE001 — fall back past broken trees
            print(
                f"ERROR: checkpoint {path} failed to restore "
                f"({type(e).__name__}: {e}); falling back to the previous "
                "verified-good checkpoint",
                file=sys.stderr, flush=True,
            )
            _set_aside_damaged(ckpt_dir, name)
            skipped += 1
            continue
        restored_path = path
        break
    _gc_damaged(ckpt_dir)
    if restored_path is None:
        raise RuntimeError(
            f"no restorable checkpoint in {ckpt_dir}: all {len(rounds)} "
            "candidates failed verification or restore"
        )
    if skipped:
        print(
            f"recovered: restored {restored_path} after skipping {skipped} "
            "damaged checkpoint(s)",
            file=sys.stderr, flush=True,
        )
    return restored_path


def _prune(ckpt_dir: str, keep: int) -> None:
    names = _round_dirs(ckpt_dir)  # damaged trees never count toward keep
    stale = names[:-keep] if keep > 0 else []
    # abandoned staging dirs (crash mid-write) are dead weight: sweep them
    stale += [d for d in os.listdir(ckpt_dir) if d.startswith(_TMP_PREFIX)]
    for name in stale:
        shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)
    _gc_damaged(ckpt_dir)
