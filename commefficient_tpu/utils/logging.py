"""Run logging (SURVEY.md L0c / §5: `TableLogger` stdout tables + `Timer`).

The reference prints fixed-width epoch tables; we keep that surface and add a
JSONL sink so runs are machine-readable (the rebuild's observability upgrade,
SURVEY.md §5 "Metrics / logging").

The JSONL sink is crash-safe by construction: the file is opened ONCE in
append mode with line buffering, every row lands as a single whole-line
write followed by a flush, and each row carries a `schema` version field —
so a process killed mid-run leaves only complete, parseable JSON lines
(tests/test_obs.py pins this with a SIGKILLed child), and a consumer can
tell which row shape it is reading. The obs tracer's event sink
(obs/trace.py) follows the same discipline.
"""

from __future__ import annotations

import json
import time

# bump when a row's FIELD SEMANTICS change (not when callers add columns —
# the row dict is caller-shaped; schema versions the envelope discipline)
JSONL_SCHEMA_VERSION = 1


class Timer:
    """Wall-clock phase timer: t = timer(); ... ; dt = timer()."""

    def __init__(self) -> None:
        self._last = time.perf_counter()
        self.total = 0.0

    def __call__(self) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.total += dt
        return dt


class TableLogger:
    """Fixed-width column table printed incrementally, one row per epoch.
    The optional JSONL sink appends `{"schema": N, **row}` per row (the
    stdout table prints the caller's columns unchanged)."""

    def __init__(self, jsonl_path: str | None = None) -> None:
        self.columns: list[str] | None = None
        self.jsonl_path = jsonl_path
        self._jsonl = None

    def append(self, row: dict) -> None:
        if self.columns is None:
            self.columns = list(row.keys())
            print("  ".join(f"{c:>12s}" for c in self.columns), flush=True)
        cells = []
        for c in self.columns:
            v = row.get(c, "")
            if isinstance(v, float):
                cells.append(f"{v:>12.4f}")
            else:
                cells.append(f"{str(v):>12s}")
        print("  ".join(cells), flush=True)
        if self.jsonl_path:
            if self._jsonl is None:
                # opened once, line-buffered: every append below is one
                # whole-line write + flush, so a kill between rows can
                # never leave a torn line
                self._jsonl = open(self.jsonl_path, "a", buffering=1)
            self._jsonl.write(
                json.dumps({"schema": JSONL_SCHEMA_VERSION, **row}) + "\n")
            self._jsonl.flush()

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
