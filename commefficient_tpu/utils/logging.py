"""Run logging (SURVEY.md L0c / §5: `TableLogger` stdout tables + `Timer`).

The reference prints fixed-width epoch tables; we keep that surface and add a
JSONL sink so runs are machine-readable (the rebuild's observability upgrade,
SURVEY.md §5 "Metrics / logging").
"""

from __future__ import annotations

import json
import time


class Timer:
    """Wall-clock phase timer: t = timer(); ... ; dt = timer()."""

    def __init__(self) -> None:
        self._last = time.perf_counter()
        self.total = 0.0

    def __call__(self) -> float:
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.total += dt
        return dt


class TableLogger:
    """Fixed-width column table printed incrementally, one row per epoch."""

    def __init__(self, jsonl_path: str | None = None) -> None:
        self.columns: list[str] | None = None
        self.jsonl_path = jsonl_path

    def append(self, row: dict) -> None:
        if self.columns is None:
            self.columns = list(row.keys())
            print("  ".join(f"{c:>12s}" for c in self.columns), flush=True)
        cells = []
        for c in self.columns:
            v = row.get(c, "")
            if isinstance(v, float):
                cells.append(f"{v:>12.4f}")
            else:
                cells.append(f"{str(v):>12s}")
        print("  ".join(cells), flush=True)
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
