"""Communication accounting — bytes up/down per round per mode.

The reference's headline claim is the accuracy-vs-communication trade-off
(SURVEY.md §6 row 4: FetchSGD dominates local_topk/FedAvg at high client
counts).  In the simulator nothing actually crosses a WAN, so the cost model
is analytic, using the wire formats a real deployment of each mode would
send (matching the paper's accounting):

- sketch:        up = r*c floats per client; down = k (index, value) pairs
- true_topk:     up = d floats (dense);      down = k pairs
- local_topk:    up = k pairs;               down = up to min(W*k, d) pairs
                 (union of client supports after server aggregation; the
                 static figure is the no-server-momentum worst case — per
                 round the engine reports the broadcast delta's measured
                 support via the `down_support` metric and
                 FederatedSession.run_round substitutes it, capped at the
                 dense-float cost since virtual momentum / DP noise can
                 densify the delta past the sparse-encoding crossover)
- fedavg/localSGD: up = d floats (weight delta); down = d floats
- uncompressed:  up = d floats;              down = d floats
"""

from __future__ import annotations

from ..modes.config import ModeConfig

BYTES_F32 = 4
BYTES_PAIR = 8  # int32 index + float32 value


def bytes_up_per_client(cfg: ModeConfig) -> int:
    if cfg.mode == "sketch":
        return cfg.num_rows * cfg.num_cols * BYTES_F32
    if cfg.mode == "local_topk":
        return cfg.k * BYTES_PAIR
    return cfg.d * BYTES_F32  # true_topk / fedavg / localSGD / uncompressed


def bytes_down_per_client(cfg: ModeConfig, num_workers: int) -> int:
    if cfg.mode in ("sketch", "true_topk"):
        return cfg.k * BYTES_PAIR
    if cfg.mode == "local_topk":
        return min(num_workers * cfg.k, cfg.d) * BYTES_PAIR
    return cfg.d * BYTES_F32


def round_comm_mb(cfg: ModeConfig, num_workers: int) -> dict:
    up = bytes_up_per_client(cfg) * num_workers
    down = bytes_down_per_client(cfg, num_workers) * num_workers
    return {
        "comm_up_mb": up / 1e6,
        "comm_down_mb": down / 1e6,
        "comm_total_mb": (up + down) / 1e6,
    }


def compression_ratio(cfg: ModeConfig, num_workers: int) -> float:
    """Dense (uncompressed) bytes / this mode's bytes, per round."""
    dense = 2 * cfg.d * BYTES_F32 * num_workers
    this = (bytes_up_per_client(cfg) + bytes_down_per_client(cfg, num_workers)) * num_workers
    return dense / max(this, 1)
