"""Hung-round detection + escalation (SURVEY.md §5 "Failure detection: none —
a dead worker hangs the run"; motivated concretely by this repo's
tunnelled-TPU outages where a wedged device claim stalls a training loop
silently for hours, and by the round-5 FEMNIST run whose ~10-min stall the
old single-warning watchdog could only mention).

A `RoundWatchdog` wraps the per-round host loop. It learns the typical round
wall-time online (median of completed rounds) and, from a daemon timer
thread, walks an ESCALATION LADDER while the in-flight round stays stuck
(stages at growing multiples of the stall threshold `factor x median`, with
an absolute floor so compile-length first rounds don't trip it):

    1x  warn       — one attributable alert: round number, stall duration
    2x  stacks     — dump every Python thread's stack (where is the host
                     loop actually stuck: data loader? device_get? orbax?)
    3x  checkpoint — call `on_emergency` (CLIs wire `ckpt.save`) so a later
                     kill loses nothing; best-effort — it can only succeed
                     when the HOST side is stuck (IO, loader), not when the
                     device op itself is wedged
    4x  abort      — call `on_abort` (opt-in; CLIs wire `os._exit(75)` so a
                     supervisor relaunches with --resume). Off by default:
                     nothing can interrupt a hung XLA call from Python, but
                     a resumable exit beats a silent multi-hour hang.

    wd = RoundWatchdog(on_emergency=lambda: ckpt.save(dir, session))
    for rnd in range(rounds):
        with wd.round(rnd):
            metrics = model(lr)

Thread-safety: stage timers re-arm under a lock that `round()`'s exit takes
to disarm, so a round finishing mid-escalation cannot leak a timer."""

from __future__ import annotations

import contextlib
import sys
import threading
import time
import traceback


def dump_all_stacks() -> str:
    """Every Python thread's current stack, formatted — the "where is it
    stuck" payload of escalation stage 2. Pure-Python (sys._current_frames),
    so it works from the timer thread while the main thread is blocked."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out)


class RoundWatchdog:
    # stage multipliers on the stall threshold, in firing order
    LADDER = (1.0, 2.0, 3.0, 4.0)
    STAGES = ("warn", "stacks", "checkpoint", "abort")

    def __init__(
        self,
        factor: float = 10.0,
        min_history: int = 3,
        floor_s: float = 120.0,
        alert=None,
        on_emergency=None,
        on_abort=None,
    ) -> None:
        """factor: stall threshold as a multiple of the median round time.
        min_history: completed rounds before the watchdog arms (first rounds
        include compiles). floor_s: never alert before this many seconds,
        whatever the median says. alert: callable(str) (default: stderr).
        on_emergency: zero-arg emergency-checkpoint callback (stage 3;
        skipped with a note when None). on_abort: zero-arg abort callback
        (stage 4; opt-in — None means the ladder ends with a final
        diagnosis instead of killing the job)."""
        self.factor = factor
        self.min_history = min_history
        self.floor_s = floor_s
        self.alert = alert or (
            lambda msg: print(msg, file=sys.stderr, flush=True)
        )
        self.on_emergency = on_emergency
        self.on_abort = on_abort
        self._times: list[float] = []
        self._timer: threading.Timer | None = None
        self._lock = threading.Lock()
        self._armed = False
        # generation counter: Timer.cancel() cannot stop a callback that has
        # already started and is blocked on self._lock, so a stale stage from
        # round N could otherwise see round N+1's _armed=True and replay the
        # ladder (stale start -> zero delays) against a healthy round
        self._gen = 0
        self.stalls_detected = 0
        self.stages_fired: list[str] = []

    def _median(self) -> float:
        s = sorted(self._times)
        return s[len(s) // 2]

    def threshold_s(self) -> float | None:
        """Current stall threshold (ladder stage 1), or None while unarmed."""
        if len(self._times) < self.min_history:
            return None
        return max(self.factor * self._median(), self.floor_s)

    def _arm_stage(self, round_index: int, thr: float, start: float,
                   stage: int, gen: int) -> None:
        """Caller holds self._lock."""
        delay = max(thr * self.LADDER[stage] - (time.monotonic() - start), 0.0)
        self._timer = threading.Timer(
            delay, self._fire, args=(round_index, thr, start, stage, gen)
        )
        self._timer.daemon = True
        self._timer.start()

    def _fire(self, round_index: int, thr: float, start: float, stage: int,
              gen: int) -> None:
        with self._lock:
            # the round can complete in the instant between this timer
            # expiring and round()'s cancel() — and cancel() cannot stop a
            # callback already blocked on this lock, so the generation check
            # is load-bearing: without it a stale stage from round N would
            # see round N+1's _armed=True, replay the ladder with round N's
            # start (delays clamp to 0), and could abort a healthy run
            if not self._armed or gen != self._gen:
                return
            # arm the NEXT stage BEFORE running this one's action: stage 3's
            # emergency checkpoint blocks forever when the device op is the
            # thing that's hung (device_get never returns), and the abort
            # stage must still fire in exactly that scenario
            if stage + 1 < len(self.LADDER):
                self._arm_stage(round_index, thr, start, stage + 1, gen)
        elapsed = time.monotonic() - start
        name = self.STAGES[stage]
        self.stages_fired.append(name)
        if stage == 0:
            self.stalls_detected += 1
            self.alert(
                f"WATCHDOG: round {round_index} has run {elapsed:.0f}s, > "
                f"{thr:.0f}s (median round {self._median():.1f}s x "
                f"{self.factor}). The device op may be hung (dead "
                "interconnect / wedged device claim / stalled loader); "
                "escalation ladder armed (stacks -> emergency checkpoint -> "
                "abort)."
            )
        elif stage == 1:
            self.alert(
                f"WATCHDOG: stacks at {elapsed:.0f}s stall (round "
                f"{round_index}):\n{dump_all_stacks()}"
            )
        elif stage == 2:
            if self.on_emergency is None:
                self.alert(
                    "WATCHDOG: no emergency-checkpoint callback configured; "
                    "skipping the checkpoint stage"
                )
            else:
                self.alert(
                    f"WATCHDOG: taking emergency checkpoint at {elapsed:.0f}s "
                    f"stall (round {round_index}); best-effort — succeeds "
                    "only if the host side is stuck, not the device op"
                )
                try:
                    self.on_emergency()
                except Exception as e:  # noqa: BLE001 — never kill the timer
                    self.alert(
                        f"WATCHDOG: emergency checkpoint failed "
                        f"({type(e).__name__}: {e})"
                    )
        elif stage == 3:
            if self.on_abort is None:
                self.alert(
                    f"WATCHDOG: round {round_index} still stuck after "
                    f"{elapsed:.0f}s; abort disabled (no on_abort) — the "
                    "loop cannot be interrupted from Python; investigate or "
                    "kill the job"
                )
            else:
                self.alert(
                    f"WATCHDOG: aborting the stalled run (round "
                    f"{round_index}, {elapsed:.0f}s) for a resumable restart"
                )
                self.on_abort()

    @contextlib.contextmanager
    def round(self, round_index: int, rounds: int = 1, record: bool = True):
        """Time one guarded segment. `rounds` > 1 marks a segment that
        legitimately spans that many rounds (the async runner's boundary
        drain waits out every queued dispatch): the stall threshold scales
        by `rounds` and the completion time is recorded PER ROUND, so the
        learned median stays a true round time. `record=False` guards a
        segment without feeding the median at all — the async runner's
        dispatch segments return in ~ms (no host sync) and would otherwise
        drag the median to ~0, collapsing every threshold to the floor and
        false-firing the ladder on healthy boundary drains."""
        rounds = max(rounds, 1)
        thr = self.threshold_s()
        start = time.monotonic()
        if thr is not None:
            with self._lock:
                self._armed = True
                self._gen += 1
                self._arm_stage(round_index, thr * rounds, start, 0, self._gen)
        try:
            yield
        finally:
            with self._lock:
                self._armed = False
                if self._timer is not None:
                    self._timer.cancel()
                    self._timer = None
            if record:
                self._times.append((time.monotonic() - start) / rounds)
