"""Hung-round detection (SURVEY.md §5 "Failure detection: none — a dead
worker hangs the run"; the rebuild's runtime equivalent of that missing
subsystem, motivated concretely by this repo's tunnelled-TPU outages where a
wedged device claim stalls a training loop silently for hours).

A `RoundWatchdog` wraps the per-round host loop. It learns the typical round
wall-time online (median of completed rounds) and, from a daemon timer
thread, emits ONE alert per stall once the in-flight round exceeds
`factor x median` (with an absolute floor so compile-length first rounds
don't trip it). It cannot interrupt a hung XLA call — nothing can from
Python — but it turns "the job has printed nothing for 3 hours" into an
immediate, attributable diagnosis with the stall duration and round number,
which is exactly what the bench.py stage markers do for benchmarks.

    wd = RoundWatchdog()
    for rnd in range(rounds):
        with wd.round(rnd):
            metrics = model(lr)

Thread-safety: the timer thread only reads monotonic timestamps written
before it is armed; arming/disarming happens on the training thread.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time


class RoundWatchdog:
    def __init__(
        self,
        factor: float = 10.0,
        min_history: int = 3,
        floor_s: float = 120.0,
        alert=None,
    ):
        """factor: stall threshold as a multiple of the median round time.
        min_history: completed rounds before the watchdog arms (first rounds
        include compiles). floor_s: never alert before this many seconds,
        whatever the median says. alert: callable(str) (default: stderr)."""
        self.factor = factor
        self.min_history = min_history
        self.floor_s = floor_s
        self.alert = alert or (
            lambda msg: print(msg, file=sys.stderr, flush=True)
        )
        self._times: list[float] = []
        self._timer: threading.Timer | None = None
        self.stalls_detected = 0

    def _median(self) -> float:
        s = sorted(self._times)
        return s[len(s) // 2]

    def threshold_s(self) -> float | None:
        """Current stall threshold, or None while unarmed."""
        if len(self._times) < self.min_history:
            return None
        return max(self.factor * self._median(), self.floor_s)

    @contextlib.contextmanager
    def round(self, round_index: int):
        thr = self.threshold_s()
        start = time.monotonic()
        if thr is not None:
            def fire():
                self.stalls_detected += 1
                self.alert(
                    f"WATCHDOG: round {round_index} has run "
                    f"{time.monotonic() - start:.0f}s, > {thr:.0f}s "
                    f"(median round {self._median():.1f}s x {self.factor}). "
                    "The device op is likely hung (dead interconnect / wedged "
                    "device claim); the loop cannot be interrupted from "
                    "Python — investigate or kill the job."
                )

            self._timer = threading.Timer(thr, fire)
            self._timer.daemon = True
            self._timer.start()
        try:
            yield
        finally:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._times.append(time.monotonic() - start)
