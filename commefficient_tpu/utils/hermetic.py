"""Hermetic CPU-backend setup, shared by tests, bench, and driver entry points.

This machine's sitecustomize registers a TPU-tunnel PJRT plugin ("axon") in
every interpreter; its backend init can hang when the tunnel is down — even
under JAX_PLATFORMS=cpu. Anything that must run hermetically on the host CPU
(the forced-multi-device test mesh, the bench CPU fallback, dryrun_multichip)
therefore strips that factory and pins the platform before any backend
initialises. One helper so the plugin name / private-API touchpoint lives in
exactly one place.
"""

from __future__ import annotations

import os


def backends_initialized() -> bool:
    """Whether any JAX backend has initialized (too late to join a
    cluster). The jax._src.xla_bridge private-API touchpoint stays in this
    module only; unknown JAX internals degrade to "assume initialized" —
    the safe answer for every caller."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:  # noqa: BLE001 — private API; fail safe
        return True


def force_hermetic_cpu(n_devices: int | None = None) -> None:
    """Pin this process's JAX to the CPU backend; optionally force an
    n_devices virtual-device mesh (xla_force_host_platform_device_count).

    Must run before the first JAX computation. Safe to call after `import
    jax` as long as no backend has initialised yet (it sets the config
    explicitly, not just the env, because jax may have latched JAX_PLATFORMS
    from the ambient env at import time).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        # append, don't setdefault: a pre-existing XLA_FLAGS must not
        # silently drop the forced device count
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
