"""Version-bridging aliases for jax APIs that were renamed across releases.

The repo is written against the current jax surface (`jax.set_mesh`,
`jax.shard_map`, `jax.sharding.get_abstract_mesh`, `jax.lax.pcast`,
`pltpu.CompilerParams`); older jaxlibs (0.4.x) spell every one of these
differently. Each alias resolves the NEW name first and falls back to the
old one, so the rest of the codebase uses a single spelling and a toolchain
bump deletes this module instead of touching call sites. Pure lookups — no
behavior shims beyond name resolution (the one exception is `pcast`, which
degrades to identity where rep-tracking doesn't exist, paired with
`CHECK_REP` so shard_map callers relax the check only on toolchains that
can't track varying values).
"""

from __future__ import annotations

import jax

# -- shard_map ---------------------------------------------------------------

if hasattr(jax, "shard_map"):
    _shard_map_impl = jax.shard_map
else:  # jax < 0.5: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_impl

try:
    import inspect

    _SHARD_MAP_REP_KW = (
        "check_vma"
        if "check_vma" in inspect.signature(_shard_map_impl).parameters
        else "check_rep"
    )
except (TypeError, ValueError):  # signature unavailable: assume the old name
    _SHARD_MAP_REP_KW = "check_rep"


def shard_map(f, **kwargs):
    """`shard_map` under one spelling of the replication-check kwarg: callers
    pass `check_rep=`; the public `jax.shard_map` renamed it `check_vma`."""
    if "check_rep" in kwargs and _SHARD_MAP_REP_KW != "check_rep":
        kwargs[_SHARD_MAP_REP_KW] = kwargs.pop("check_rep")
    return _shard_map_impl(f, **kwargs)

# -- pcast / rep-checking ----------------------------------------------------

if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
    CHECK_REP = True
else:
    # old shard_map has no varying-value tracking: marking is meaningless
    # and the caller must pass check_rep=False for bodies that use
    # axis_index (CHECK_REP advertises which world we are in)
    CHECK_REP = False

    def pcast(x, axes, to="varying"):  # noqa: ARG001 — signature parity
        return x


# -- ambient mesh ------------------------------------------------------------


def set_mesh(mesh):
    """Context manager installing `mesh` as the ambient mesh
    (`jax.set_mesh` / `jax.sharding.use_mesh` / the legacy `with mesh:`
    resource-env context, newest first)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    if hasattr(jax.sharding, "use_mesh"):
        return jax.sharding.use_mesh(mesh)
    # jax 0.4.x: Mesh is itself the context manager for the resource env
    return mesh


def get_abstract_mesh():
    """The ambient mesh, or None. New jax returns the AbstractMesh from
    jax.sharding.get_abstract_mesh(); old jax exposes the physical mesh of
    the active resource env (empty mesh -> None, matching the new API's
    'nothing installed' contract closely enough for axis lookups)."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        m = jax.sharding.get_abstract_mesh()
        if m is not None and not getattr(m, "axis_names", ()):  # empty mesh
            return None
        return m
    try:
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
    except Exception:  # noqa: BLE001 — internals moved: behave as "no mesh"
        return None
    if m is None or not m.axis_names:
        return None
    return m


# -- Pallas TPU compiler params ---------------------------------------------


def tpu_compiler_params(**kwargs):
    """`pltpu.CompilerParams(**kwargs)` under whichever name this jax ships
    it (old: TPUCompilerParams). Imported lazily: the tpu pallas module is
    not importable on every backend."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
