"""LR schedules (SURVEY.md L0c: `PiecewiseLinear` — 0 -> peak at pivot_epoch
-> 0 over num_epochs, the cifar10-fast triangular schedule)."""

from __future__ import annotations


class PiecewiseLinear:
    """Linear interpolation through (knot, value) pairs; flat beyond the ends.

    The reference's triangular schedule is
    `PiecewiseLinear([0, pivot_epoch, num_epochs], [0, lr_scale, 0])`,
    evaluated at fractional epochs.
    """

    def __init__(self, knots: list[float], values: list[float]) -> None:
        if len(knots) != len(values) or len(knots) < 2:
            raise ValueError("need >= 2 matching knots/values")
        if any(b <= a for a, b in zip(knots, knots[1:])):
            raise ValueError("knots must be strictly increasing")
        self.knots = list(map(float, knots))
        self.values = list(map(float, values))

    def __call__(self, t: float) -> float:
        ks, vs = self.knots, self.values
        if t <= ks[0]:
            return vs[0]
        if t >= ks[-1]:
            return vs[-1]
        for i in range(len(ks) - 1):
            if t <= ks[i + 1]:
                frac = (t - ks[i]) / (ks[i + 1] - ks[i])
                return vs[i] + frac * (vs[i + 1] - vs[i])
        return vs[-1]


def triangular(lr_scale: float, pivot_epoch: float, num_epochs: float) -> PiecewiseLinear:
    return PiecewiseLinear([0.0, pivot_epoch, max(num_epochs, pivot_epoch + 1e-6)],
                           [0.0, lr_scale, 0.0])
