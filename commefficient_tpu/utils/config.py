"""The flag surface — reference CLI compatibility (SURVEY.md §5.6).

One argparse namespace drives everything, as in the reference's
`utils.parse_args`. Flag names follow the reference ([K]-provenance; SURVEY.md
notes they may differ from the mounted fork — re-ground via SURVEY.md §0.3
when the mount is populated).
"""

from __future__ import annotations

import argparse

from ..modes.config import MODES, ModeConfig


def make_parser(task: str = "cv") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=f"commefficient-tpu {task} training")
    # compression / update mode
    p.add_argument("--mode", default="uncompressed", choices=list(MODES))
    p.add_argument("--error_type", default=None, choices=["none", "local", "virtual"],
                   help="default: virtual for sketch/true_topk, local for local_topk, else none")
    p.add_argument("--momentum_type", default=None, choices=["none", "virtual", "local"],
                   help="default: virtual when --momentum > 0 (local for local_topk), else none")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--k", type=int, default=50000)
    p.add_argument("--num_rows", type=int, default=5)
    p.add_argument("--num_cols", type=int, default=500000)
    p.add_argument("--num_blocks", type=int, default=1)
    p.add_argument("--hash_family", default="rotation", choices=["rotation", "random"],
                   help="sketch bucket-hash family: rotation = TPU-fast roll-based "
                        "(default), random = reference-like per-coordinate hashing")
    p.add_argument("--topk_impl", default="exact",
                   choices=["exact", "approx", "oversample"],
                   help="top-k selection: exact (lax.top_k), approx "
                        "(lax.approx_max_k, TPU-fast at --topk_recall; "
                        "paper-scale accuracy impact within seed variance "
                        "at recall 0.99 — results/README.md), or oversample "
                        "(approx 4k-candidate preselect + exact refine: "
                        "near-exact at approx speed by construction)")
    p.add_argument("--topk_recall", type=float, default=0.95,
                   help="approx_max_k recall_target for --topk_impl approx "
                        "and for oversample's preselect pass")
    p.add_argument("--sketch_path", default="ravel",
                   choices=["ravel", "layerwise"],
                   help="mode=sketch only: how the round's Count-Sketch "
                        "table is built. ravel (default) concatenates every "
                        "layer into one flat [d] gradient before "
                        "compressing (the reference flat path); layerwise "
                        "folds "
                        "each layer's gradient block straight into the "
                        "running r x c table as it comes off the backward "
                        "pass — the dense [d] gradient (and the flat "
                        "params copy for the delta apply) never "
                        "materializes, so peak sketch-side memory is "
                        "O(r*c) + one layer instead of O(d). Pinned "
                        "bit-identical to ravel (fused, split, sharded)")
    p.add_argument("--server_state", default="dense",
                   choices=["dense", "sketch"],
                   help="server optimizer state representation: dense "
                        "(default; [d] Vvelocity/Verror, the seed "
                        "behavior) or sketch (momentum + virtual error "
                        "feedback kept as r x c Count-Sketch tables — "
                        "server memory stops scaling with d; true_topk "
                        "and local_topk-with-virtual-error only; "
                        "mode=sketch is already sketch-state and accepts "
                        "both). With --num_cols >= d the sketch is a "
                        "lossless signed permutation and matches dense "
                        "bit-for-bit; below that it is the FetchSGD-style "
                        "approximation")
    p.add_argument("--agg_op", default="mean", choices=["mean", "sum"],
                   help="client-wire aggregation: mean (cohort-size-independent "
                        "default) or sum (FetchSGD Alg. 1 semantics — use with "
                        "reference lr_scale values; sum@lr == mean@lr*W exactly)")
    # federation shape
    p.add_argument("--num_clients", type=int, default=100)
    p.add_argument("--num_workers", type=int, default=8,
                   help="clients sampled (simulated) per round")
    p.add_argument("--local_batch_size", type=int, default=8)
    p.add_argument("--num_local_iters", type=int, default=1)
    p.add_argument("--server_lr", type=float, default=1.0,
                   help="fedavg/localSGD: server rate on the averaged weight "
                        "delta (with --momentum_type virtual this is slowmo)")
    p.add_argument("--iid", action="store_true")
    # optimisation
    p.add_argument("--num_epochs", type=float, default=24)
    p.add_argument("--lr_scale", type=float, default=0.4)
    p.add_argument("--pivot_epoch", type=float, default=5)
    p.add_argument("--weight_decay", type=float, default=5e-4)
    # differential privacy (upstream fork deltas — SURVEY.md §0.5)
    p.add_argument("--dp_clip", type=float, default=0.0,
                   help="L2 clip per client update (0 = off)")
    p.add_argument("--dp_noise", type=float, default=0.0,
                   help="central-DP noise multiplier on the aggregate (needs --dp_clip)")
    # run plumbing
    p.add_argument("--client_dropout", type=float, default=0.0,
                   help="per-round probability each sampled client drops "
                        "before aggregation (straggler simulation; the "
                        "reference has none — a dead worker hangs it)")
    p.add_argument("--client_update_clip", type=float, default=0.0,
                   help="sketch-space quarantine: reject any client whose "
                        "update L2 exceeds this multiple of the running "
                        "median of live client norms (non-finite updates "
                        "always rejected) — the client is zeroed out of the "
                        "merge and removed from the renormalization, so one "
                        "poisoned update costs one client, not the round. "
                        "Counted per round as clients_quarantined. 0 = off")
    p.add_argument("--merge_policy", default="sum",
                   choices=["sum", "trimmed", "median"],
                   help="how per-client Count-Sketch tables combine into "
                        "the round aggregate. sum (pinned default): the "
                        "linear ordered sum — FetchSGD's merge, maximally "
                        "accurate and exactly what a Byzantine minority "
                        "exploits. trimmed: per table coordinate, drop the "
                        "--merge_trim highest and lowest live "
                        "contributions before the ordered sum (trimmed "
                        "mean; deterministic tie-break by client index, "
                        "mesh-shape-invariant; trim=0 is BIT-identical to "
                        "sum by construction). median: coordinate-wise "
                        "median. Robust policies need per-client tables, "
                        "so they forfeit the compress-once linearity "
                        "shortcut (the round runs the wire-payload shape "
                        "even unserved) and require --mode sketch with "
                        "--sketch_path ravel; they also weaken error-"
                        "feedback exactness (see README threat model)")
    p.add_argument("--merge_trim", type=int, default=0,
                   help="--merge_policy trimmed: contributions dropped per "
                        "coordinate from EACH end (defends up to this many "
                        "colluders; needs 2*trim < --num_workers). 0 = "
                        "trim nothing = the sum program, bit-identically")
    p.add_argument("--robust_residual", default="off",
                   choices=["off", "on"],
                   help="error-feedback-aware robust merges (--merge_policy "
                        "trimmed|median): accumulate the robust-vs-mean "
                        "merge residual into the Verror table, with the "
                        "mean WINSORIZED into the policy's kept window — "
                        "the honest mass the trim clips re-enters through "
                        "error feedback (telescoping survives the robust "
                        "merge) while an adversary's residual contribution "
                        "stays bounded by the clean value range. off "
                        "(default) keeps the PR 10 robust program "
                        "bit-for-bit; MIGRATION.md notes the intent to "
                        "flip after a soak")
    p.add_argument("--quarantine_scope", default="cohort",
                   choices=["cohort", "layer"],
                   help="--client_update_clip screen granularity. cohort "
                        "(default): one L2 norm per client vs the running "
                        "cohort median (the original screen, unchanged). "
                        "layer: ADDITIONALLY screen each client's update "
                        "per LAYER — per-leaf L2 vs that leaf's own "
                        "running median ring (--quarantine_window applies "
                        "per leaf), a client over ANY leaf's screen is "
                        "dropped — so an attack hiding inside the flat "
                        "norm (all its mass in one layer) still trips. "
                        "Single-leaf models are bit-identical to cohort "
                        "scope on the update-norm (announce) rounds; "
                        "table rounds (--serve_payload sketch / robust "
                        "--merge_policy) add the update-space per-leaf "
                        "screen beside the table-space one even "
                        "single-leaf. Fused round paths only (widens the "
                        "quarantine state tree — see MIGRATION.md)")
    p.add_argument("--quarantine_window", type=int, default=1,
                   help="--client_update_clip threshold baseline: 1 "
                        "(default) screens against the LAST non-empty "
                        "round's live-cohort median (the pre-window "
                        "behavior, bit-identical); K > 1 screens against "
                        "the median over a ring of the last K rounds' "
                        "medians, so models whose update norms drift fast "
                        "don't quarantine healthy clients (one outlier "
                        "round perturbs one window slot, not the whole "
                        "threshold). Works on the fused, sharded, and "
                        "payload rounds; --split_compile rejects it loudly "
                        "(the split boundary threads one scalar median)")
    p.add_argument("--requeue_policy", default="fifo",
                   choices=["fifo", "aged"],
                   help="serving order for the dropped-client re-queue: "
                        "fifo (default; substitution order = drop order) or "
                        "aged (weighted choice by rounds-waiting from a "
                        "pinned dedicated seed — at high drop rates FIFO "
                        "can starve recently-dropped clients behind a long "
                        "head; aged keeps expected wait bounded). Both "
                        "consume zero host-sampling RNG, so the sampled "
                        "cohort stream is policy-invariant")
    # streaming aggregation service (serve/): clients PUSH submissions at a
    # continuously-running aggregator instead of the loop pulling them
    p.add_argument("--serve", default="off",
                   choices=["off", "inproc", "socket"],
                   help="run as a streaming aggregation service: cohorts "
                        "assemble from a PUSH arrival stream (trace-driven "
                        "traffic generator) with W-of-N round close, "
                        "admission control, and backpressure, instead of "
                        "the loop sampling clients itself. inproc = "
                        "in-process submissions (deterministic; the parity "
                        "path), socket = loopback-TCP JSON-lines wire. "
                        "off (default) = the batch simulator")
    p.add_argument("--serve_quorum", type=int, default=0,
                   help="W of the W-of-N round close: the round closes as "
                        "soon as this many of the --num_workers invited "
                        "clients have submitted; stragglers and no-shows "
                        "are masked + re-queued (bit-identical to the "
                        "round over the survivors). 0 = full cohort")
    p.add_argument("--serve_deadline", type=float, default=4.0,
                   help="round-close deadline in (virtual) seconds: a "
                        "round short of quorum closes degraded here")
    p.add_argument("--serve_trace", default="",
                   help="traffic-generator trace spec, 'k=v,...' over "
                        "population/base_rate/diurnal_amplitude/"
                        "diurnal_period_s/burst_rate/burst_size/seed "
                        "(serve.TraceConfig); unset = defaults with "
                        "population=num_clients and seed=--seed")
    p.add_argument("--serve_payload", default="announce",
                   choices=["announce", "sketch"],
                   help="what a submission carries. announce (default): an "
                        "arrival announcement — the engine computes every "
                        "update server-side from the client's shard. "
                        "sketch: the client's REAL r x c Count-Sketch table "
                        "crosses the wire (length-prefixed, checksummed, "
                        "schema-versioned frames on the socket transport), "
                        "runs the server's validation gauntlet "
                        "(MALFORMED/STALE_SCHEMA/QUARANTINED rejections), "
                        "and the server merely SUMS accepted tables — the "
                        "linearity FetchSGD is servable on. Requires "
                        "--mode sketch; announce stays the default until "
                        "the payload path soaks (see MIGRATION.md)")
    p.add_argument("--serve_shed_watermark", type=float, default=0.0,
                   help="load shedding: reject submissions with SHEDDING "
                        "(+ a retry-after hint on the socket wire) once "
                        "queue depth passes this fraction of total "
                        "capacity, BEFORE any per-submission work — "
                        "overload degrades gracefully instead of queuing "
                        "unboundedly. 0 = off (hard QUEUE_FULL only)")
    p.add_argument("--serve_pipeline", action="store_true",
                   help="always-on aggregation: run the serve cycle "
                        "(invite -> collect -> close -> prep) on a "
                        "double-buffered worker AHEAD of the merge, so "
                        "round r+1's ingest overlaps round r's merge and "
                        "the commit-to-dispatch gap collapses "
                        "(server_idle_ms ~ 0). Bit-identical to the serial "
                        "served loop by construction (same producer order, "
                        "dispatch-gated payload compute)")
    p.add_argument("--serve_async", action="store_true",
                   help="buffered ASYNCHRONOUS aggregation (FedBuff-"
                        "shaped): rounds close at a buffer-size trigger "
                        "(--serve_buffer) instead of the W-of-N quorum, "
                        "and late tables — stragglers past the trigger, "
                        "pushes for a recently-closed round — fold into a "
                        "later merge weighted (1+lag)^-alpha instead of "
                        "being discarded. Requires --serve_payload sketch. "
                        "Composes with --merge_policy trimmed|median: the "
                        "per-BUFFER robust merge runs the order statistics "
                        "over {current buffer + staleness-weighted stale "
                        "folds}, so a stale adversarial table is trimmed "
                        "like an on-time one. Sync stays the parity "
                        "reference: an async run where everyone answers on "
                        "time is pinned bitwise == the sync run (zero-"
                        "stale robust rounds == the sync robust program)")
    p.add_argument("--serve_buffer", type=int, default=0,
                   help="--serve_async: merged-table count that triggers a "
                        "round's merge (replaces the quorum; 0 = the "
                        "--serve_quorum value)")
    p.add_argument("--serve_staleness", type=float, default=0.5,
                   help="--serve_async: staleness exponent alpha — a table "
                        "lag rounds late folds with weight (1+lag)^-alpha "
                        "(0 = unweighted, FedBuff default 0.5)")
    p.add_argument("--serve_stale_rounds", type=int, default=1,
                   help="--serve_async: how many rounds behind the newest "
                        "window a late table is still admitted and folded; "
                        "older submissions bounce OUT_OF_ROUND and the "
                        "parked entry is dropped (counted)")
    p.add_argument("--serve_transport", default="eventloop",
                   choices=["threaded", "eventloop"],
                   help="--serve socket: the connection engine. eventloop "
                        "(default since PR 18): the serve/scale selectors "
                        "reactor — ONE thread multiplexing thousands of "
                        "connections (non-blocking accept, incremental "
                        "frame reassembly, read deadlines). The C1M path. "
                        "threaded (the reference, and the default before "
                        "PR 18): one OS thread per connection, capped — "
                        "fine for chaos tests, dead at heavy traffic; "
                        "pinning it prints a startup NOTE. Identical "
                        "admission decisions either way (shared protocol, "
                        "same G011 gauntlet).")
    p.add_argument("--serve_shards", type=int, default=0,
                   help=">= 2 shards the socket ingest that many ways, "
                        "clients routed by client-id hash — spreads "
                        "connection handling and payload-gauntlet CPU "
                        "across workers (reactor threads or real worker "
                        "processes; --serve_shard_mode). Per-shard "
                        "admission/shed counters and load-scaled retry-"
                        "after hints land in /metrics and /metrics.prom, "
                        "so an overloaded shard is distinguishable from "
                        "an overloaded server. Requires --serve socket "
                        "--serve_transport eventloop. 0 = one listener")
    p.add_argument("--serve_shard_mode", default="thread",
                   choices=["thread", "process"],
                   help="--serve_shards >= 2: what a shard IS. thread "
                        "(default): N reactor threads over the ONE "
                        "admission queue — connection scale-out, but "
                        "decode/gauntlet/admission still serialize on "
                        "this process's GIL. process: N SO_REUSEPORT "
                        "worker PROCESSES (serve/scale/procshard.py), "
                        "shared-nothing — each owns its clients' "
                        "admission state outright (dedup, pending, "
                        "quarantine screen against the round's broadcast "
                        "median) and lands validated tables in a shared-"
                        "memory ring block the root's close reads "
                        "directly; misroutes forward to the owner "
                        "(counted). A killed worker == its clients "
                        "dropped + re-queued bitwise (shard_kill fault "
                        "kind); dead workers respawn at the next round. "
                        "Served params stay BITWISE identical to thread "
                        "mode and to the unsharded path, fastpath on or "
                        "off. Does not compose with --serve_pipeline/"
                        "--serve_async/--serve_edges yet")
    p.add_argument("--serve_edges", type=int, default=0,
                   help=">= 2 arms TWO-TIER edge aggregation "
                        "(serve/scale/edge.py): the cohort partitions "
                        "over this many edge aggregators by client-id "
                        "hash; each edge validates + ordered-sums its "
                        "shard's tables and forwards ONE r x c partial "
                        "to the root (sketch linearity makes the tree "
                        "merge exact), which folds partials in fixed "
                        "edge order — pinned BITWISE equal to the flat "
                        "merge of the same edge-armed session over the "
                        "same surviving cohort. An edge dying == its "
                        "shard dropped + re-queued, bitwise (edge_kill "
                        "fault kind). Robust --merge_policy forces per-"
                        "client FORWARDING through the tree (loud note; "
                        "order statistics need individual tables). "
                        "Requires --serve_payload sketch; does not "
                        "compose with --serve_async/--serve_pipeline "
                        "yet. 0 = flat merge (the exact prior program)")
    p.add_argument("--serve_fastpath", action="store_true",
                   help="zero-copy ingest-to-merge fast path: accepted "
                        "r x c tables decode ONCE straight into a pinned "
                        "host ring block sized by the cohort (serve/"
                        "ring.py) and upload to device in chunks WHILE "
                        "the round window is still open; socket "
                        "transports also batch the validation gauntlet "
                        "over blocks of arrivals (vectorized finite/L2 "
                        "screening, --serve_gauntlet_workers). Per-"
                        "submission admission verdicts, their counters, "
                        "and the served round's bytes are pinned "
                        "BITWISE identical to the slow path — the ring "
                        "changes layout and copy count, never order. "
                        "Requires --serve_payload sketch; does not "
                        "compose with --serve_edges yet")
    p.add_argument("--serve_gauntlet_workers", type=int, default=2,
                   help="--serve_fastpath + --serve socket: worker "
                        "threads draining the batched validation "
                        "gauntlet (each drains up to 32 queued frames "
                        "per wake and screens them as one numpy block). "
                        "Inproc serving validates inline and ignores "
                        "this")
    p.add_argument("--serve_max_conns", type=int, default=0,
                   help="--serve socket: concurrent-connection cap of the "
                        "connection engine (per reactor when sharded) — "
                        "past it connections are refused and counted "
                        "(serve_conn_refused_total), never queued. 0 = "
                        "the engine default: threaded 128 (every "
                        "connection is an OS thread), eventloop 8192 "
                        "(fd-bounded)")
    p.add_argument("--serve_port", type=int, default=0,
                   help="--serve socket: loopback bind port (0 = ephemeral; "
                        "sharded ingest binds port+k per shard when set)")
    p.add_argument("--serve_metrics_port", type=int, default=-1,
                   help=">= 0 serves GET /metrics (JSON: round, queue "
                        "depth, arrival rate, quarantine/requeue counters) "
                        "on this loopback port (0 = ephemeral, printed at "
                        "startup); -1 = no endpoint")
    p.add_argument("--rounds_per_dispatch", type=int, default=1,
                   help="> 1 compiles this many rounds into one program "
                        "(lax.scan) with a single host sync per block — "
                        "amortizes the host round-trip; stateless modes only "
                        "(others silently run per-round)")
    p.add_argument("--sync_loop", action="store_true",
                   help="run the fully synchronous round loop: inline batch "
                        "assembly, a blocking metrics sync per dispatch, and "
                        "blocking checkpoint writes. The default ASYNC "
                        "harness (runner/) overlaps all three with device "
                        "compute and is pinned bit-identical to this loop; "
                        "--sync_loop is the escape hatch / A-B baseline")
    p.add_argument("--client_chunk", type=int, default=0,
                   help="> 0 scans the per-client grads in chunks of this "
                        "many clients (must divide --num_workers), so at "
                        "most client_chunk full gradients coexist in HBM — "
                        "lets GPT-2-scale rounds sample big cohorts per chip")
    p.add_argument("--split_compile", action="store_true",
                   help="compile the round as TWO XLA programs (client grads "
                        "| sketch server step) so Pallas custom-calls stay in "
                        "a small dedicated module; linear grad modes only")
    p.add_argument("--multihost", action="store_true",
                   help="force jax.distributed.initialize() at startup "
                        "(auto-detected multi-host environments initialize "
                        "without this flag; see parallel/distributed.py)")
    p.add_argument("--coordinator_address", default=None,
                   help="host:port of process 0 for --multihost on clusters "
                        "without auto-detection (non-TPU)")
    p.add_argument("--num_processes", type=int, default=None,
                   help="total hosts for --multihost (with "
                        "--coordinator_address)")
    p.add_argument("--process_id", type=int, default=None,
                   help="this host's rank for --multihost (with "
                        "--coordinator_address)")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--num_devices", type=int, default=0, help="0 = all visible")
    p.add_argument("--mesh", default="",
                   help="device mesh for the data-parallel federated round: "
                        "clients=N[,slices=M]. The sampled cohort shards "
                        "N-ways (xM across pod slices over DCN); each device "
                        "accumulates its shard's partial Count Sketch and "
                        "the cross-device merge ships one r x c table per "
                        "round instead of the dense [d] gradient. Errors if "
                        "the host exposes fewer devices than the spec needs. "
                        "Unset = shard over all visible devices (the sharded "
                        "round is the default whenever > 1 device is "
                        "visible); combine with --model_parallel/"
                        "--seq_parallel on the gpt2 CLI")
    p.add_argument("--max_inflight", type=int, default=0,
                   help="async loop: drain when this many rounds are "
                        "dispatched-uncommitted. 0 = auto-tune from the "
                        "measured host<->device round-trip so the per-drain "
                        "sync stays ~10%% of the amortized work (tunnelled "
                        "TPUs get a deep chain, local runs stay shallow)")
    p.add_argument("--prefetch_depth", type=int, default=0,
                   help="async round-preparation lookahead; 0 = auto "
                        "(double buffering, deepened on high-RTT links)")
    # resilience (resilience/: fault injection + failure recovery)
    p.add_argument("--fault_plan", default="",
                   help="deterministic fault-injection plan: ';'-separated "
                        "kind[@round,...][:key=val,...] entries — kinds: "
                        "preempt (SIGTERM mid-round), stall:secs=S / "
                        "data_fail:times=N (data-loader), eval_stall:secs=S "
                        "(eval loader), nonfinite[:value="
                        "inf] (NaN/Inf gradient burst), ckpt_fail:times=N / "
                        "ckpt_corrupt / ckpt_partial (checkpoint IO), "
                        "dist_init:times=N (distributed bootstrap), "
                        "client_drop:clients=I+J / client_straggle:clients="
                        "I,secs=S / client_poison:clients=I,value=nan|inf|"
                        "big (cohort-level: mask/stall/poison individual "
                        "clients inside the round), host_preempt:host=K "
                        "(SIGTERM one simulated host; the cross-host "
                        "barrier carries it to all), client_signflip:"
                        "clients=I / client_scale:clients=I,factor=F / "
                        "client_collude:frac=P (Byzantine wire attacks on "
                        "the per-client sketch table — mode=sketch table "
                        "rounds; answered by --merge_policy and the "
                        "quarantine), seed=N. "
                        "Unset = zero injection, zero behavior change")
    p.add_argument("--on_nonfinite", default="skip",
                   choices=["off", "skip", "halt"],
                   help="NaN/Inf aggregate guard: skip treats the poisoned "
                        "round as fully-dropped (momentum/error state stay "
                        "clean; counted in metrics), halt additionally "
                        "checkpoints and exits, off restores the unguarded "
                        "seed behavior (poison propagates into the params)")
    p.add_argument("--max_retries", type=int, default=3,
                   help="bounded retries (exponential backoff + jitter) for "
                        "checkpoint IO, distributed init, and data loading")
    p.add_argument("--no_emergency_checkpoint", action="store_true",
                   help="disable the watchdog's MID-ROUND emergency "
                        "checkpoint and keep server-state buffer donation "
                        "(saves one full state copy in HBM — for runs that "
                        "barely fit). Scheduled --checkpoint_every saves and "
                        "the preemption checkpoint still work: both run at "
                        "round boundaries where donation is safe")
    p.add_argument("--watchdog_abort", action="store_true",
                   help="arm the RoundWatchdog's final escalation stage: "
                        "after warn -> stack dump -> emergency checkpoint, "
                        "abort the wedged process with the resumable exit "
                        "status so a supervisor relaunches with --resume "
                        "(needs --checkpoint_dir)")
    # reference-CLI compatibility no-ops (SURVEY.md §5.6): the reference's
    # process/queue machinery needs them; the TPU engine has no worker
    # processes to pin or ports to bind. Accepted so reference launch
    # commands run unmodified; a note is printed if set.
    p.add_argument("--share_ps_gpu", action="store_true",
                   help="accepted for reference-CLI compatibility; no-op "
                        "(no parameter-server process exists here)")
    p.add_argument("--port", type=int, default=0,
                   help="accepted for reference-CLI compatibility; no-op "
                        "(no torch.multiprocessing rendezvous here)")
    p.add_argument("--eval_batch_size", type=int, default=512)
    p.add_argument("--eval_every", type=int, default=0, help="rounds; 0 = once per epoch")
    p.add_argument("--num_rounds", type=int, default=0,
                   help="hard round cap (0 = derive from epochs); handy for smoke tests")
    p.add_argument("--data_root", default="./data")
    p.add_argument("--checkpoint_dir", default="")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--checkpoint_every", type=int, default=0, help="rounds; 0 = never")
    p.add_argument("--log_jsonl", default="")
    # observability (obs/): round tracing + metrics registry + profiler
    p.add_argument("--trace", default="",
                   help="write a Chrome-trace/Perfetto JSON of the run "
                        "here: host-side spans on named tracks (runner, "
                        "device, writer, serve-ingest, assembler, "
                        "federated, resilience) with deferred device-phase "
                        "durations resolved at drain boundaries — zero "
                        "host syncs added, traced run bit-identical to "
                        "untraced. Open in chrome://tracing or "
                        "ui.perfetto.dev")
    p.add_argument("--trace_events", default="",
                   help="append obs events as JSONL here (one schema-"
                        "versioned object per span/instant, line-buffered "
                        "whole-line writes — crash-safe); independent of "
                        "--trace, both may be set")
    p.add_argument("--profile_rounds", default="",
                   help="START:END — programmatic jax.profiler capture "
                        "window: start_trace before round START "
                        "dispatches, stop_trace after round END commits "
                        "(whole rounds, async pipeline included). Needs "
                        "--profile_dir; degrades to a loud no-op where "
                        "the profiler is unavailable. Without this flag "
                        "--profile_dir still captures the whole run")
    p.add_argument("--profile_dir", default="", help="write a jax.profiler trace here")
    p.add_argument("--health_every", type=int, default=0,
                   help="N > 0 computes sketch-health estimators ON DEVICE "
                        "inside the round program every N rounds (mode="
                        "sketch, fused/sharded/served paths): heavy-hitter "
                        "mass + top-k recall proxy, table saturation/"
                        "collision proxy, error-feedback Verror telescoping "
                        "health, per-leaf gradient-norm distribution, "
                        "uplink-vs-dense bytes — resolved at the existing "
                        "drain boundary (zero added host syncs) into "
                        "health_* registry gauges, /metrics, the trace, and "
                        "the round ledger. Estimators only READ round "
                        "state: a health-armed run is pinned bit-identical "
                        "to an unarmed one. 0 = off (the seed program, "
                        "bit-for-bit)")
    p.add_argument("--ledger", default="",
                   help="append one schema-versioned JSONL record per "
                        "COMMITTED round here (cohort + masks, admission/"
                        "quarantine/attack/stale-fold counter deltas, "
                        "health block, params/optimizer fingerprints) — "
                        "written with the whole-line crash-safe discipline, "
                        "riding the committed-snapshot rewind (uncommitted "
                        "rounds never appear; --resume continues the same "
                        "file gap-free). Also arms the crash postmortem "
                        "bundle at PATH.postmortem/ (trace + ledger tail + "
                        "registry snapshot + resolved config on watchdog "
                        "abort / unhandled exception / exit 75). Inspect "
                        "with `python -m commefficient_tpu.obs.ledger "
                        "diff|replay-check`")
    p.add_argument("--slo", default="off", choices=["off", "warn", "halt"],
                   help="arm the SLO/anomaly engine: windowed rules over "
                        "the committed round series (default set: "
                        "quarantine-rate spike, recall-proxy floor, stale-"
                        "fold runaway, server_idle_ms regression, non-"
                        "finite streak), evaluated at each commit. warn = "
                        "stderr + slo_* counters + trace instant; halt = "
                        "additionally checkpoint and exit cleanly at the "
                        "next drain boundary (the --on_nonfinite halt "
                        "discipline)")
    p.add_argument("--slo_rules", default="",
                   help="';'-separated rule specs overriding the default "
                        "set: name:series(>|<|^)threshold[@window] — e.g. "
                        "'q_spike:quarantine_rate>0.2@8;recall:"
                        "topk_mass_proxy<0.1@4'. > / < compare the "
                        "windowed mean; ^ fires when the current window "
                        "exceeds threshold x the older baseline "
                        "(regression). Series: any per-round metric, "
                        "quarantine_rate, stale_fraction, server_idle_ms, "
                        "or any health_* estimator name (needs "
                        "--health_every). Requires --slo")
    p.add_argument("--dtype", default="float32", choices=["float32", "bfloat16"],
                   help="model compute dtype (params/BN/logits stay float32); "
                        "bfloat16 runs convs/matmuls on the TPU MXU at full rate")
    if task == "cv":
        p.add_argument("--dataset", default="cifar10",
                       choices=["cifar10", "cifar100", "femnist"])
        p.add_argument("--synthetic_separation", type=float, default=1.0,
                       help="class-prototype scale for the synthetic CIFAR "
                            "fallback: 1.0 = trivially separable (smoke "
                            "tests); ~0.025 puts Bayes accuracy near 0.86 "
                            "so accuracy-vs-comm trade-offs are meaningful")
        p.add_argument("--synthetic_train", type=int, default=10000,
                       help="synthetic-CIFAR fallback train-set size; 50000 "
                            "matches real CIFAR so paper-scale cohorts "
                            "(10,000 sort-by-label clients) get the same 5 "
                            "images/client as BASELINE config #2")
    else:  # gpt2
        p.add_argument("--dataset", default="personachat", choices=["personachat"])
        p.add_argument("--seq_len", type=int, default=256)
        p.add_argument("--model_size", default="small", choices=["tiny", "small"])
        p.add_argument("--init_from", default="",
                       help="HF GPT-2 checkpoint dir (config.json + "
                            "pytorch_model.bin) to fine-tune from; the wte is "
                            "grown for the dialog special tokens")
        p.add_argument("--model_parallel", type=int, default=1,
                       help="tensor-parallel ways for the GPT-2 path")
        p.add_argument("--attn_impl", default="dense", choices=["dense", "ring"],
                       help="ring = sequence-parallel ring attention (needs "
                            "--seq_parallel > 1; K/V blocks rotate over ICI)")
        p.add_argument("--seq_parallel", type=int, default=1,
                       help="sequence-parallel ways (mesh 'seq' axis) for "
                            "--attn_impl ring")
        p.add_argument("--mc_coef", type=float, default=0.0,
                       help="> 0 enables the next-utterance-classification "
                            "head: joint loss lm + mc_coef * mc over "
                            "--num_candidates candidate replies "
                            "(transfer-learning-conv-ai double head)")
        p.add_argument("--num_candidates", type=int, default=2,
                       help="candidates per example (gold + distractors) "
                            "when --mc_coef > 0")
        p.add_argument("--mc_hard_negatives", action="store_true",
                       help="synthetic corpus only: draw MC distractors "
                            "from other personas' replies (same word pool) "
                            "instead of a reserved vocabulary half — "
                            "mc_acc then measures persona-reply matching, "
                            "not token identity (real-json distractors are "
                            "always hard)")
        p.add_argument("--moe_experts", type=int, default=0,
                       help="> 0 swaps every 2nd block's MLP for a "
                            "Switch-style top-1 MoE with this many experts "
                            "(shard over an 'expert' mesh axis for EP)")
        p.add_argument("--moe_aux_coef", type=float, default=0.01,
                       help="weight of the MoE load-balancing aux loss")
        p.add_argument("--eval_f1", type=int, default=0,
                       help="> 0 decodes this many validation dialogs at "
                            "every eval and logs val_f1 (ConvAI2 word-level "
                            "F1 of the generated reply vs gold)")
        p.add_argument("--decode_max_new", type=int, default=32,
                       help="max generated tokens per reply for --eval_f1")
        p.add_argument("--decode_temperature", type=float, default=0.0,
                       help="0 = greedy; > 0 samples with nucleus top-p")
        p.add_argument("--decode_top_p", type=float, default=0.9)
    return p


def resolve_defaults(args: argparse.Namespace) -> argparse.Namespace:
    """Fill mode-dependent defaults so every reference flag combo maps onto a
    ModeConfig the mode library implements (see ModeConfig validation)."""
    if args.momentum_type is None:
        if args.momentum and args.momentum > 0:
            args.momentum_type = "local" if args.mode == "local_topk" else "virtual"
        else:
            args.momentum_type = "none"
    if args.error_type is None:
        args.error_type = {
            "sketch": "virtual",
            "true_topk": "virtual",
            "local_topk": "local",
        }.get(args.mode, "none")
    if args.mode in ("fedavg", "localSGD") and args.num_local_iters < 1:
        args.num_local_iters = 1
    if getattr(args, "share_ps_gpu", False) or getattr(args, "port", 0):
        print("note: --share_ps_gpu/--port are reference-CLI compatibility "
              "no-ops (the TPU engine has no worker processes)", flush=True)
    if getattr(args, "watchdog_abort", False) and not getattr(args, "checkpoint_dir", None):
        # silently dropping the flag would leave a wedged run hanging for
        # hours — the exact outcome the operator opted out of
        raise SystemExit(
            "--watchdog_abort needs --checkpoint_dir: aborting without an "
            "emergency checkpoint would lose the run instead of resuming it"
        )
    if getattr(args, "robust_residual", "off") == "on":
        # the residual is the robust merge's error-feedback repair; with
        # no effective robust policy there is nothing to repair and the
        # flag would be a silent no-op discovered at the postmortem
        if (args.merge_policy == "sum"
                or (args.merge_policy == "trimmed"
                    and args.merge_trim == 0)):
            raise SystemExit(
                "--robust_residual on names the robust merge's error-"
                "feedback residual; with --merge_policy sum (or trimmed@0, "
                "which IS the sum program) there is no robust merge — arm "
                "--merge_policy trimmed (trim > 0) or median")
    if getattr(args, "serve_async", False):
        # the async fold is a compiled merge variant over wire tables —
        # both prerequisites must fail AT LAUNCH, not as an attribute
        # error rounds in
        if getattr(args, "serve", "off") == "off":
            raise SystemExit(
                "--serve_async is a serving mode; arm --serve inproc|socket")
        if getattr(args, "serve_payload", "announce") != "sketch":
            raise SystemExit(
                "--serve_async merges client tables as they arrive; the "
                "announce path has none — arm --serve_payload sketch")
    elif getattr(args, "serve_buffer", 0):
        raise SystemExit(
            "--serve_buffer is the --serve_async trigger size; without "
            "--serve_async the close discipline is --serve_quorum")
    if (getattr(args, "serve_pipeline", False)
            and getattr(args, "serve", "off") == "off"):
        raise SystemExit(
            "--serve_pipeline pipelines the serving rounds; arm --serve "
            "inproc|socket")
    # (the eventloop default means an unpinned non-socket run carries
    # serve_transport="eventloop" harmlessly — only a PINNED threaded
    # engine off-socket is detectably pointless now)
    if (getattr(args, "serve_transport", "eventloop") == "threaded"
            and getattr(args, "serve", "off") not in ("off", "socket")):
        raise SystemExit(
            "--serve_transport picks the SOCKET connection engine; arm "
            "--serve socket (inproc has no connections to multiplex)")
    if getattr(args, "serve_shards", 0):
        if getattr(args, "serve_shards", 0) < 2:
            raise SystemExit(
                f"--serve_shards must be >= 2 (or 0 = one listener), got "
                f"{args.serve_shards}")
        if getattr(args, "serve", "off") != "socket":
            raise SystemExit(
                "--serve_shards shards the socket ingest; arm --serve "
                "socket")
        if getattr(args, "serve_transport", "eventloop") != "eventloop":
            raise SystemExit(
                "--serve_shards runs N event-loop reactors; arm "
                "--serve_transport eventloop (thread-per-connection has "
                "no reactor to shard)")
    elif getattr(args, "serve_shard_mode", "thread") == "process":
        raise SystemExit(
            "--serve_shard_mode process needs --serve_shards >= 2 (one "
            "shard IS the plain event-loop transport)")
    if getattr(args, "serve_shard_mode", "thread") == "process":
        if (getattr(args, "serve_pipeline", False)
                or getattr(args, "serve_async", False)
                or getattr(args, "serve_edges", 0) >= 2):
            raise SystemExit(
                "--serve_shard_mode process does not compose with "
                "--serve_pipeline/--serve_async/--serve_edges yet "
                "(admission state lives in the worker processes; the "
                "cross-process band/boundary/edge disciplines are named "
                "follow-ups) — drop one of the flags")
    if getattr(args, "serve_max_conns", 0) < 0:
        raise SystemExit(
            f"--serve_max_conns must be >= 0 (0 = engine default), got "
            f"{args.serve_max_conns}")
    if getattr(args, "serve_edges", 0):
        if getattr(args, "serve_edges", 0) < 2:
            raise SystemExit(
                f"--serve_edges must be >= 2 (or 0 = flat merge), got "
                f"{args.serve_edges} (one edge IS the flat merge)")
        if getattr(args, "serve", "off") == "off":
            raise SystemExit(
                "--serve_edges is a serving topology; arm --serve "
                "inproc|socket")
        if getattr(args, "serve_payload", "announce") != "sketch":
            raise SystemExit(
                "--serve_edges aggregates client TABLES at the edge tier; "
                "the announce path has none — arm --serve_payload sketch")
        if (getattr(args, "serve_async", False)
                or getattr(args, "serve_pipeline", False)):
            raise SystemExit(
                "--serve_edges does not compose with --serve_async/"
                "--serve_pipeline yet (stale-fold edge assignment and the "
                "pipelined worker's edge timing are open follow-ups) — "
                "drop one of the flags")
    if getattr(args, "serve_fastpath", False):
        if getattr(args, "serve", "off") == "off":
            raise SystemExit(
                "--serve_fastpath is a serving-path optimization; arm "
                "--serve inproc|socket")
        if getattr(args, "serve_payload", "announce") != "sketch":
            raise SystemExit(
                "--serve_fastpath pins client TABLES into a host ring; "
                "the announce path has none — arm --serve_payload sketch")
        if getattr(args, "serve_edges", 0) >= 2:
            raise SystemExit(
                "--serve_fastpath does not compose with --serve_edges yet "
                "(the edge tier consumes the host table stack the ring "
                "replaces) — drop one of the flags")
    if getattr(args, "serve_gauntlet_workers", 2) < 1:
        raise SystemExit(
            f"--serve_gauntlet_workers must be >= 1, got "
            f"{args.serve_gauntlet_workers}")
    if getattr(args, "health_every", 0):
        if args.health_every < 0:
            raise SystemExit(
                f"--health_every must be >= 0, got {args.health_every}")
        if args.mode != "sketch":
            raise SystemExit(
                "--health_every computes SKETCH-wire quality estimators; "
                f"--mode {args.mode} has no table to estimate from")
        if getattr(args, "split_compile", False):
            raise SystemExit(
                "--health_every is fused-paths-only (the split program "
                "boundary does not thread the estimator metrics); drop "
                "--split_compile")
    if getattr(args, "slo_rules", "") and getattr(args, "slo", "off") == "off":
        raise SystemExit(
            "--slo_rules names rules for the SLO engine; arm it with "
            "--slo warn|halt")
    if getattr(args, "slo", "off") != "off":
        # validate the rule grammar at launch — a typo'd rule must not be
        # a silently-absent guard discovered at the postmortem
        from ..obs.slo import parse_rules

        try:
            parse_rules(getattr(args, "slo_rules", ""))
        except ValueError as e:
            raise SystemExit(str(e)) from None
    if getattr(args, "profile_rounds", ""):
        # validate the window at launch: a typo'd spec (or a missing
        # output dir) must not surface hours later as a silently-absent
        # capture
        from ..obs.profiler import parse_rounds_spec

        try:
            parse_rounds_spec(args.profile_rounds)
        except ValueError as e:
            raise SystemExit(str(e)) from None
        if not getattr(args, "profile_dir", ""):
            raise SystemExit(
                "--profile_rounds needs --profile_dir (the capture has to "
                "be written somewhere)"
            )
    return args


def mode_config_from_args(args: argparse.Namespace, d: int) -> ModeConfig:
    return ModeConfig(
        mode=args.mode,
        d=d,
        k=min(args.k, d) if args.k else 0,
        num_rows=args.num_rows,
        num_cols=args.num_cols,
        num_blocks=args.num_blocks,
        seed=args.seed,
        momentum=args.momentum if args.momentum_type != "none" else 0.0,
        momentum_type=args.momentum_type,
        error_type=args.error_type,
        num_local_iters=args.num_local_iters if args.mode in ("fedavg", "localSGD") else 1,
        server_lr=args.server_lr if args.mode in ("fedavg", "localSGD") else 1.0,
        num_clients=args.num_clients,
        hash_family=args.hash_family,
        agg_op=args.agg_op,
        topk_impl=args.topk_impl,
        topk_recall=args.topk_recall,
        server_state=args.server_state,
    )
