"""Round preparation sources: inline (sync) and background-thread (async).

Both serve `FederatedSession.prepare_round(rnd)` results strictly in round
order from ONE producer, which is what keeps the host RNG streams — client
sampling, batch-assembly draws, the device key chain — identical to the
synchronous loop: prepare_round is the only mutator of those streams, and
here it is only ever called sequentially from a single thread.

The async variant (`RoundPrefetcher`) is the tentpole's data-prefetch half:
a daemon thread assembles round N+1's (and N+2's, bounded by `depth` —
double buffering by default) client batch while the device computes round
N. Determinism properties preserved:

- **Retry replay**: `_load_client_batch` restores the host RNG snapshot on
  a failed attempt before retrying, so an injected `data_fail` recovered on
  the prefetch thread yields the bit-identical batch the clean run sees.
- **Resume replay**: prepared-but-uncommitted rounds advance only the LIVE
  streams; the session's checkpointable `rng_snapshot` moves at COMMIT time
  (to the snapshot captured inside the PreparedRound), so a checkpoint
  taken while the prefetcher is ahead resumes bit-identically — the
  discarded prepared rounds are simply re-prepared from the same stream
  state after restore.
- **Fault scheduling**: data-load faults fire inside prepare_round with the
  EXPLICIT round index being prepared (not the session's lagging counter),
  so `stall@7`/`data_fail@7` land on round 7 no matter how far ahead the
  prefetcher runs. `preempt` stays a dispatch-time site on the main thread.

Errors that survive the retry budget propagate: the thread parks the
exception and `next()` re-raises it at the consuming point, so the run dies
as loudly as the synchronous loop would.
"""

from __future__ import annotations

from ..data.fed_dataset import ThreadedPrefetcher


class PreparedSource:
    """Inline producer (the --sync_loop path): prepare_round at the call
    point, no thread, no lookahead — byte-for-byte the old loop's timing."""

    def __init__(self, session, start_round: int):
        self.session = session
        self._next = start_round

    def next(self):
        prep = self.session.prepare_round(self._next)
        self._next += 1
        return prep

    def stop(self):
        pass


class RoundPrefetcher(PreparedSource):
    """Background producer with a bounded queue (depth=2: double buffering),
    built on the same ThreadedPrefetcher machinery the eval loader uses.

    `next()` blocks until the next round's preparation is done (or its
    parked error re-raises); `stop()` halts the producer and joins it —
    called by the runner before any exit path so a preemption can't leak a
    thread mid-assembly."""

    def __init__(self, session, start_round: int, depth: int = 2):
        super().__init__(session, start_round)

        def rounds():
            rnd = start_round
            while True:  # unbounded: the consumer (run_loop) decides the end
                yield session.prepare_round(rnd)
                rnd += 1

        self._pf = ThreadedPrefetcher(rounds(), depth=depth,
                                      name="round-prefetch")

    def next(self):
        return self._pf.next()

    def stop(self):
        self._pf.stop()
