"""The shared run loop: block planning, overlap, and the operational wiring
(watchdog, preemption, non-finite halt, eval/checkpoint cadence) both CLIs
previously hand-rolled and copy-pasted.

Overlap model (async, the default):

    prefetch thread:  prepare N+1, N+2   (client sampling + batch assembly)
    main thread:      dispatch N, N+1, ...      (no per-dispatch host sync)
    device:           compute N, N+1, ...       (queued back-to-back)
    writer thread:    periodic checkpoint save  (staging + rename commit)
    main thread @ boundary: ONE batched device_get of every pending round's
        metrics -> commit in dispatch order -> eval / log / checkpoint

What stays synchronous, deliberately:

- **Commit order**: rounds publish (state, round counter, comm totals, RNG
  snapshot) in dispatch order under the session's mutate_lock — an
  emergency checkpoint from the watchdog's timer thread always captures a
  consistent committed view.
- **Eval**: runs only at a drained boundary (the pipeline is empty, so
  `session.state` is the exact committed params — and, with buffer
  donation on, the only state guaranteed live).
- **Emergency + preemption + final saves**: the moments where "the save
  completed" must hold before the next action (abort, exit 75, process
  end). The async writer is DRAINED before the preemption save and before
  exit.
- **Non-finite halt**: evaluated from committed metrics at drain
  boundaries — the same block granularity the old loop had with
  `--rounds_per_dispatch > 1` (the compiled `skip` guard keeps state clean
  for any rounds dispatched past the poisoned one).

`--sync_loop` collapses all of it: inline preparation, one watchdog-wrapped
prepare->dispatch->sync per round (or per fused block), blocking saves —
the old loop, kept as the A/B baseline and escape hatch. Both paths drive
the identical compiled programs in the identical order with the identical
host RNG stream, which is why tests/test_runner.py can pin them
bit-identical.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import sys
import threading
import time

import jax

from ..federated.api import FederatedSession, FedOptimizer, plan_block
from ..obs import registry as obreg
from ..obs import trace as obtrace
from ..obs.profiler import ProfileWindow
from ..resilience import EXIT_RESUMABLE, PreemptionHandler, preemption
from ..utils import checkpoint as ckpt
from ..utils.logging import Timer
from ..utils.watchdog import RoundWatchdog
from .prefetch import PreparedSource, RoundPrefetcher
from .writer import AsyncCheckpointWriter


DEFAULT_MAX_INFLIGHT = 4  # auto-tune's starting point until a round is timed
AUTO_INFLIGHT_LO, AUTO_INFLIGHT_HI = 2, 16


def _process_count() -> int:
    """Host count of the job — indirection point so tests can simulate a
    multi-host loop without lying to the rest of jax (orbax checkpointing
    also reads jax.process_count and would break under a global patch)."""
    return jax.process_count()


# graftlint: drain-point — deliberate one-shot sync probe at loop start;
# the measured RTT is what the in-flight chain amortizes
def measure_rtt_ms(samples: int = 5) -> float:
    """Median host<->device round-trip of a trivial jitted op + device_get —
    the per-drain sync cost the in-flight chain exists to amortize (tens of
    ms on the tunnelled TPU, ~0.1 ms locally). Same discipline as bench.py's
    tunnel measurement; cheap enough to run once at loop start."""
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    jax.device_get(f(x))  # compile + warm
    ts = []
    for _ in range(samples):
        t0 = time.perf_counter()
        jax.device_get(f(x))
        ts.append((time.perf_counter() - t0) * 1e3)
    return sorted(ts)[len(ts) // 2]


def auto_inflight(rtt_ms: float, round_ms: float,
                  target_overhead: float = 0.1) -> int:
    """In-flight depth that keeps the per-drain host sync under
    ~target_overhead of the work it amortizes: each drain costs one RTT (the
    batched device_get), spread over the rounds committed in it, so depth
    >= rtt / (target * round) bounds the sync tax at ~target. Clamped to
    [2, 16]: 2 keeps dispatch/commit overlapped even on zero-RTT local
    backends; 16 bounds how much work a preemption's grace window must wait
    out (the same concern the fixed default had)."""
    if round_ms <= 0:
        return DEFAULT_MAX_INFLIGHT
    import math

    want = math.ceil(rtt_ms / (target_overhead * round_ms))
    return max(AUTO_INFLIGHT_LO, min(AUTO_INFLIGHT_HI, want))


@dataclasses.dataclass
class RunnerConfig:
    """Loop shape + operational policy (mirrors the CLI flag surface; build
    one with from_args in the CLIs, or directly in tests/bench)."""

    total_rounds: int
    eval_every: int
    checkpoint_every: int = 0
    checkpoint_dir: str = ""
    rounds_per_dispatch: int = 1
    sync_loop: bool = False
    # async only: drain when this many rounds are dispatched-uncommitted,
    # even between boundaries — bounds how much work a preemption's grace
    # window has to wait out, and how stale the halt check can run.
    # 0 (default) = auto-tune: measure the host<->device RTT once at loop
    # start, then re-derive the depth from the observed per-round time at
    # every drain (auto_inflight) — a tunnelled TPU gets a deep chain, a
    # local CPU stays shallow. > 0 is the manual override (--max_inflight).
    max_inflight: int = 0
    # round-prep lookahead; 0 = auto (double buffering, deepened to 4 when
    # the measured RTT says the host link is slow enough that batch assembly
    # may lag a drained burst of dispatches)
    prefetch_depth: int = 0
    on_nonfinite: str = "skip"  # the CLI-level halt policy ("halt" stops)
    watchdog_abort: bool = False
    no_emergency_checkpoint: bool = False
    # observability: a jax.profiler capture window around whole rounds
    # ("START:END"; empty = off) written into profile_dir — see
    # obs/profiler.py for the start/stop-at-round-boundary semantics
    profile_rounds: str = ""
    profile_dir: str = ""

    @classmethod
    def from_args(cls, args, total_rounds: int, eval_every: int):
        return cls(
            total_rounds=total_rounds,
            eval_every=eval_every,
            checkpoint_every=args.checkpoint_every,
            checkpoint_dir=args.checkpoint_dir,
            rounds_per_dispatch=args.rounds_per_dispatch,
            sync_loop=args.sync_loop,
            max_inflight=getattr(args, "max_inflight", 0),
            prefetch_depth=getattr(args, "prefetch_depth", 0),
            on_nonfinite=args.on_nonfinite,
            watchdog_abort=args.watchdog_abort,
            no_emergency_checkpoint=args.no_emergency_checkpoint,
            profile_rounds=getattr(args, "profile_rounds", ""),
            profile_dir=getattr(args, "profile_dir", ""),
        )


@dataclasses.dataclass
class RunStats:
    """What the loop did — bench.py's run_loop section reads these.

    Since the obs/ layer landed these are a per-run VIEW over the
    process-wide metrics registry: run_loop increments named registry
    counters (runner_rounds_total, cohort_clients_dropped_total, ...) at
    the same points it always counted, takes a RegistryMark at loop start,
    and fills this dataclass from the deltas at loop end — so RunStats,
    serve's /metrics snapshot, and bench's resilience block all read the
    SAME numbers. (Concurrent run_loops in one process would cross-count;
    the loops in this repo — bench arms, the CLIs — run sequentially.)"""

    rounds: int = 0
    wall_s: float = 0.0
    nonfinite_rounds: int = 0
    drains: int = 0
    evals: int = 0
    sync_checkpoints: int = 0
    async_checkpoints: int = 0
    # async loop introspection: the measured host<->device RTT and the
    # in-flight depth the loop ended on (auto-tuned unless --max_inflight)
    rtt_ms: float = 0.0
    max_inflight_used: int = 0
    # cohort degradation (bench.py resilience block): clients masked out of
    # rounds (failed loads / injected drops), clients rejected by the
    # sketch-space quarantine, rounds that ran degraded at all, and how deep
    # the dropped-client re-queue got
    clients_dropped: int = 0
    clients_quarantined: int = 0
    degraded_rounds: int = 0
    requeue_depth_max: int = 0
    # Byzantine attacks the fault plan injected while this loop ran
    # (client_signflip / client_scale / client_collude firings — the
    # resilience_attack_*_total counters' per-run deltas summed): a chaos
    # run's stats say how much adversarial pressure the merge absorbed
    attacks_injected: int = 0
    # always-on serving acceptance: the mean/max wall gap between a drain's
    # commit and the NEXT dispatch — the server idle the pipelined serving
    # mode exists to close (a pipelined source has the next round prepared
    # when the drain ends, so the gap collapses to the dispatch call
    # itself). Also published as the `server_idle_ms` registry gauge (last
    # observed gap) + `runner_idle_ms` histogram.
    server_idle_ms: float = 0.0
    server_idle_ms_max: float = 0.0
    # SLO engine firings while this loop ran (--slo warn|halt; the
    # slo_violations_total registry counter's per-run delta) — a run that
    # finished "green" with violations > 0 finished on a warn posture, not
    # a healthy one
    slo_violations: int = 0


def make_save_ckpt(session: FederatedSession, checkpoint_dir: str):
    """The one shared save closure: serialized by its own lock (the
    watchdog's emergency save runs on a timer thread and must not race a
    scheduled/periodic save of the same round — both would target the same
    staging/final dirs), sharing the session's fault plan + retry policy so
    per-site injection counters stay coherent across the whole run.

    One writer per JOB, not per host: on a pod the checkpoint dir is shared
    storage and every host holds the same replicated state, so only process
    0 writes — two hosts saving the same round would build the identical
    staging dir name and clobber each other's half-written trees. Non-zero
    processes return None (callers treat it as 'nothing written here')."""
    lock = threading.Lock()

    # graftlint: drain-point — checkpoint writes ARE sanctioned blocking
    # work: sync-mode saves run on the dispatch thread at round boundaries
    # by design (the async writer moves the periodic ones off it)
    def save_ckpt():
        if jax.process_index() != 0:
            return None
        with lock:
            return ckpt.save(
                checkpoint_dir, session,
                fault_plan=session.fault_plan,
                retry_policy=session.retry_policy,
            )

    return save_ckpt


def run_loop(
    session: FederatedSession,
    opt: FedOptimizer,
    cfg: RunnerConfig,
    *,
    eval_fn=None,
    build_row=None,
    logger=None,
    save_ckpt=None,
    source=None,
    slo=None,
    postmortem=None,
) -> RunStats:
    """Run the training loop from session.round to cfg.total_rounds.

    eval_fn() -> metrics dict, called at every eval boundary (drained).
    build_row(rnd, m, totals, ev, time_s, nonfinite_total) -> row dict for
    the logger; `m` is the last round's metrics, `totals` the sum of every
    numeric metric key since the previous eval row. Either may be None (no
    eval / no logging — bench runs). save_ckpt defaults to make_save_ckpt
    when cfg.checkpoint_dir is set.

    source: an external round source (next() -> PreparedRound in round
    order, stop()) — the serving layer (serve/ServedSource) passes one so
    the SERVICE drives the loop from its arrival stream instead of the loop
    pulling clients through the sampling prefetcher. When given, the loop
    neither wraps nor replaces it (the source owns its own overlap policy);
    default None builds the usual PreparedSource/RoundPrefetcher pair.

    slo: an obs.SloEngine the SESSION feeds at each commit (the CLIs wire
    both ends); the loop only checks its halt latch at drain boundaries
    and exits through the same clean shutdown/save path --on_nonfinite
    halt uses. postmortem: callable(reason) writing the crash bundle
    (obs.ledger.write_postmortem_bundle) — invoked on the watchdog-abort
    and preemption exit-75 paths, where the CLIs' exception handling
    never runs (os._exit) or runs too late to matter.

    Exits the process (not returns) on preemption (EXIT_RESUMABLE) and on
    --on_nonfinite halt, after the same drain/save sequence the CLIs used
    to inline.
    """
    stats = RunStats()
    t0 = time.perf_counter()
    eval_every = max(cfg.eval_every, 1)
    start_round = session.round
    # observability: every operational count goes through the process-wide
    # registry (obs/registry.py) and RunStats is carved out of it via this
    # mark's deltas at loop end; the tracer (obs/trace.py) is a no-op
    # unless the CLI armed it (--trace / --trace_events)
    reg = obreg.default()
    mark = reg.mark()
    tracer = obtrace.get()
    # device-phase span attribute: which sketch accumulation program the
    # session compiled (EngineConfig.sketch_path; "ravel" unless layerwise)
    sketch_path = getattr(session.cfg, "sketch_path", "ravel")
    phase_hist = {ph: reg.histogram(f"runner_phase_{ph}_ms")
                  for ph in obreg.RUNNER_PHASES}
    profile = ProfileWindow.parse(cfg.profile_rounds, cfg.profile_dir)
    if profile is not None and profile.start >= cfg.total_rounds:
        # same contract as FaultPlan.validate_rounds: a window the run can
        # never reach must be loud at launch, not a silently-missing
        # capture discovered hours later
        profile.declare_unreachable(cfg.total_rounds)
        profile = None
    # (client_* fault schedules are validated against the FULL run length by
    # the CLIs — run_loop may legitimately cover a segment, e.g. bench arms)
    # multi-host coordinated preemption: with > 1 process the LOCAL SIGTERM
    # flag must not short-circuit the SPMD schedule (the un-signalled hosts
    # would block in the next round's collectives) — every preemption
    # decision goes through the cross-host max-reduce at block boundaries,
    # where every host's collective call counts line up.
    process_count = _process_count()

    if save_ckpt is None and cfg.checkpoint_dir:
        save_ckpt = make_save_ckpt(session, cfg.checkpoint_dir)

    def _postmortem(reason: str):
        """Best-effort crash-bundle write: the exit it precedes is the
        point — a failing bundle must never mask it."""
        if postmortem is None:
            return
        try:
            postmortem(reason)
        except Exception as e:  # noqa: BLE001 — crash path
            print(f"runner: postmortem bundle failed ({type(e).__name__}: "
                  f"{e})", file=sys.stderr, flush=True)

    def _abort():
        # stage 4 of the watchdog ladder: flush the black box, THEN die
        # with the resumable status (os._exit skips every finally — this
        # is the one chance the bundle gets)
        _postmortem("watchdog_abort")
        os._exit(EXIT_RESUMABLE)

    # escalation ladder: warn -> stacks -> emergency ckpt -> (opt-in) abort
    # with the resumable status so a supervisor relaunches with --resume
    watchdog = RoundWatchdog(
        on_emergency=save_ckpt
        if save_ckpt and not cfg.no_emergency_checkpoint else None,
        on_abort=_abort if cfg.watchdog_abort and save_ckpt else None,
    )

    async_mode = not cfg.sync_loop
    # auto-tuned overlap depth (ROADMAP follow-up): measure the per-drain
    # host sync cost once, then keep re-deriving the in-flight depth from
    # the observed per-round time so the RTT tax stays ~10% of the round —
    # a tunnelled TPU converges to a deep chain, a local CPU to a shallow
    # one. --max_inflight / --prefetch_depth stay as manual overrides.
    rtt_ms = (
        measure_rtt_ms()
        if async_mode and (cfg.max_inflight <= 0 or cfg.prefetch_depth <= 0)
        else 0.0
    )
    eff_inflight = (cfg.max_inflight if cfg.max_inflight > 0
                    else DEFAULT_MAX_INFLIGHT)
    prefetch_depth = (
        cfg.prefetch_depth if cfg.prefetch_depth > 0
        else (4 if rtt_ms > 10.0 else 2)
    )
    ema_round_ms = 0.0
    stats.rtt_ms = rtt_ms
    writer = None
    if async_mode and save_ckpt and cfg.checkpoint_every:
        if session._donate_state:
            # an overlapped save reads session.state while later rounds
            # dispatch — with donation the committed buffers are already
            # dead. Keep the periodic saves, just blocking (the HBM-tight
            # --no_emergency_checkpoint trade-off extends to overlap).
            print(
                "runner: state-buffer donation is on "
                "(--no_emergency_checkpoint); periodic checkpoint writes "
                "stay synchronous — an overlapped save would read donated "
                "buffers",
                flush=True,
            )
        else:
            writer = AsyncCheckpointWriter(save_ckpt)
    src = source if source is not None else (
        RoundPrefetcher(session, start_round, depth=prefetch_depth)
        if async_mode else PreparedSource(session, start_round)
    )

    pending: collections.deque = collections.deque()  # in-flight dispatches
    pending_rounds = 0
    # serving-layer hook: a pipelined ServedSource gates the NEXT round's
    # payload client compute on the previous merge being dispatched (the
    # head-state chaining the bit-parity rests on) — resolved once so the
    # batch-simulator sources pay one getattr, not one per dispatch
    on_dispatched = getattr(src, "on_dispatched", None)
    # server-idle accounting (always-on serving acceptance): the gap from a
    # drain's commit to the next dispatch — ≈0 when the source has the next
    # round ready (pipelined), the whole invite/collect/close cycle when it
    # doesn't (serial served source)
    idle_hist = reg.histogram("runner_idle_ms")
    idle_gauge = reg.gauge("server_idle_ms")
    idle_mark: list = [None]  # [perf_counter at drain end] | [None]
    idle_acc = [0.0, 0, 0.0]  # sum_ms, n, max_ms

    def note_idle():
        """Called at each dispatch site BEFORE the dispatch: resolves the
        commit-to-dispatch gap the last drain opened (first dispatch after
        a drain only)."""
        if idle_mark[0] is None:
            return
        ms = (time.perf_counter() - idle_mark[0]) * 1e3
        idle_mark[0] = None
        idle_hist.observe(ms)
        idle_gauge.set(ms)
        idle_acc[0] += ms
        idle_acc[1] += 1
        idle_acc[2] = max(idle_acc[2], ms)
    # per-dispatch (trace timestamp, first round, round count): the
    # deferred device-phase spans — resolved at the drain that commits
    # them, never by a mid-round sync (the deferred-metrics discipline)
    dispatch_marks: collections.deque = collections.deque()
    totals: collections.defaultdict = collections.defaultdict(float)
    last_m: dict | None = None
    nonfinite_total = 0
    timer = Timer()

    last_drain_t = time.perf_counter()
    first_drain = True

    # graftlint: drain-point — THE drain point: one batched device_get for
    # every pending round's metrics
    def drain(watch: bool = True):
        """Commit every pending dispatch: ONE batched device_get for all
        their metrics, then in-order publication + metric folding. In auto
        mode the wall time between drains (boundary work included — an
        overestimate only ever tunes the depth DOWN toward the safe floor)
        feeds the next in-flight depth; the FIRST interval is discarded —
        it carries the round step's jit compile (tens of seconds on the
        tunnelled target), which would seed the EMA ~1000x high and pin
        the depth at the floor for many drains."""
        nonlocal pending_rounds, last_m, nonfinite_total
        nonlocal eff_inflight, ema_round_ms, last_drain_t, first_drain
        if not pending:
            return
        committed = pending_rounds
        first = session.round  # oldest uncommitted round index
        # the drain legitimately waits out every queued dispatch, so the
        # watchdog threshold scales by the round count and the recorded
        # time is normalized back to a per-round figure (true median)
        t_drain0 = time.perf_counter()
        with (watchdog.round(first, rounds=pending_rounds)
              if watch else contextlib.nullcontext()):
            with tracer.span("runner", "drain", round_first=first,
                             rounds=committed):
                hosts = jax.device_get([fl.metrics for fl in pending])
        phase_hist["drain"].observe((time.perf_counter() - t_drain0) * 1e3)
        # deferred device-phase spans: each dispatch recorded only a host
        # timestamp; the span closes HERE, where its rounds are known done.
        # sketch_path names the compiled round variant (ravel | layerwise)
        # so a trace shows which accumulation program the device time
        # belongs to when A/B-ing the two arms.
        end_us = tracer.now_us()
        while dispatch_marks:
            ts_us, d_first, d_n = dispatch_marks.popleft()
            tracer.complete(
                "device", f"rounds {d_first}..{d_first + d_n - 1}",
                ts_us, end_us - ts_us, round_first=d_first, rounds=d_n,
                sketch_path=sketch_path)
        t_commit0 = time.perf_counter()
        with tracer.span("runner", "commit", round_first=first,
                         rounds=committed):
            for i, m in enumerate(session.commit_rounds(list(pending),
                                                        hosts)):
                rnd_i = first + i
                last_m = m
                nf = int(m.get("nonfinite_rounds", 0))
                nonfinite_total += nf
                dropped = int(m.get("clients_dropped", 0))
                quarantined = int(m.get("clients_quarantined", 0))
                depth = int(m.get("requeue_depth", 0))
                reg.counter("runner_nonfinite_rounds_total").inc(nf)
                reg.counter("cohort_clients_dropped_total").inc(dropped)
                reg.counter("cohort_clients_quarantined_total").inc(
                    quarantined)
                if dropped or quarantined:
                    reg.counter("cohort_degraded_rounds_total").inc()
                reg.gauge("cohort_requeue_depth").set(depth)
                stats.requeue_depth_max = max(stats.requeue_depth_max, depth)
                tracer.instant("runner", "commit_round", round=rnd_i)
                if quarantined:
                    tracer.instant("resilience", "quarantine", round=rnd_i,
                                   clients=quarantined)
                for k, v in m.items():
                    if isinstance(v, (int, float)):
                        totals[k] += v
        phase_hist["commit"].observe((time.perf_counter() - t_commit0) * 1e3)
        pending.clear()
        pending_rounds = 0
        reg.counter("runner_rounds_total").inc(committed)
        reg.counter("runner_drains_total").inc()
        if profile is not None:
            profile.on_committed(session.round)
        on_committed = getattr(src, "on_committed", None)
        if on_committed is not None:
            # serving layer hook: submission-to-merge latencies resolve at
            # the commit that published their round's merged update
            on_committed(session.round)
        now = time.perf_counter()
        idle_mark[0] = now  # the idle window the next dispatch closes
        per_round = (now - last_drain_t) * 1e3 / max(committed, 1)
        last_drain_t = now
        if first_drain:
            first_drain = False  # compile-tainted interval: discard
        else:
            ema_round_ms = (per_round if ema_round_ms <= 0
                            else 0.5 * ema_round_ms + 0.5 * per_round)
            if async_mode and cfg.max_inflight <= 0:
                eff_inflight = auto_inflight(rtt_ms, ema_round_ms)

    def shutdown():
        """Exit-path teardown (preemption/halt): stop the prefetcher and
        drain the writer. A failed async save is reported but must NOT
        block the synchronous exit save that follows — that save is the
        corrective action (and carries its own retries)."""
        src.stop()
        if writer is not None:
            try:
                writer.drain()
            except Exception as e:  # noqa: BLE001 — exit save still runs
                print(
                    f"runner: async checkpoint failure at shutdown "
                    f"({type(e).__name__}: {e}); continuing to the "
                    "synchronous exit save", file=sys.stderr, flush=True,
                )
            writer.close()

    rnd = start_round
    try:
        with PreemptionHandler() as pre:
            while rnd < cfg.total_rounds:
                lrs = plan_block(opt, rnd, cfg.total_rounds, eval_every,
                                 cfg.checkpoint_every, cfg.rounds_per_dispatch)
                if len(lrs) > 1 and session.supports_block_dispatch:
                    # a fused block cannot split, so the capture window
                    # arms on OVERLAP (round-aligned superset); the
                    # per-round fallback below keeps per-round precision
                    if profile is not None:
                        profile.on_dispatch(rnd, rounds=len(lrs))
                    # one dispatch for the block; the watchdog times the
                    # block (prefetch pull included — a stalled loader is a
                    # stall the ladder should see). In async mode a dispatch
                    # returns without a host sync in ~ms, so it must not
                    # feed the learned round-time median (record=False) —
                    # the boundary drain records the true per-round time.
                    with watchdog.round(rnd, record=cfg.sync_loop):
                        t_p0 = time.perf_counter()
                        with tracer.span("runner", "prepare", round=rnd,
                                         rounds=len(lrs)):
                            preps = [src.next() for _ in lrs]
                        phase_hist["prepare"].observe(
                            (time.perf_counter() - t_p0) * 1e3)
                        note_idle()
                        t_d0 = time.perf_counter()
                        t_mark = tracer.now_us()
                        with tracer.span("runner", "dispatch", round=rnd,
                                         rounds=len(lrs)):
                            pending.append(session.dispatch_block(preps, lrs))
                        # marked only AFTER the dispatch succeeded: a
                        # raising dispatch must not leave a stale mark the
                        # next drain would resolve into a phantom span
                        dispatch_marks.append((t_mark, rnd, len(lrs)))
                        if on_dispatched is not None:
                            on_dispatched(rnd + len(lrs) - 1)
                        phase_hist["dispatch"].observe(
                            (time.perf_counter() - t_d0) * 1e3)
                        if len(pending) > 1:
                            pending[-2].release_state()  # superseded head
                        pending_rounds += len(lrs)
                        if cfg.sync_loop:
                            drain(watch=False)
                    rnd += len(lrs)
                else:
                    # per-round dispatch (stateful/split/fault-plan
                    # fallback): keep the watchdog per-round so a hang is
                    # detected at round, not block, granularity
                    for j, lr in enumerate(lrs):
                        if profile is not None:
                            profile.on_dispatch(rnd + j)
                        with watchdog.round(rnd + j, record=cfg.sync_loop):
                            t_p0 = time.perf_counter()
                            with tracer.span("runner", "prepare",
                                             round=rnd + j):
                                prep = src.next()
                            phase_hist["prepare"].observe(
                                (time.perf_counter() - t_p0) * 1e3)
                            note_idle()
                            t_d0 = time.perf_counter()
                            t_mark = tracer.now_us()
                            with tracer.span("runner", "dispatch",
                                             round=rnd + j):
                                pending.append(
                                    session.dispatch_round(prep, lr)
                                )
                            dispatch_marks.append((t_mark, rnd + j, 1))
                            if on_dispatched is not None:
                                on_dispatched(rnd + j)
                            phase_hist["dispatch"].observe(
                                (time.perf_counter() - t_d0) * 1e3)
                            if len(pending) > 1:
                                pending[-2].release_state()  # superseded
                            pending_rounds += 1
                            if cfg.sync_loop:
                                drain(watch=False)
                        rnd += 1
                        if pre.triggered and process_count == 1:
                            break  # stop inside the block: the grace window
                            # is short. Multi-host: an early break would
                            # desync this host's dispatch count from its
                            # peers' (their collectives would hang), so the
                            # flag waits for the coordinated boundary check.
                # cross-host agreement on the preemption flag at the block
                # boundary: every host sees "any host was signalled" and
                # they all finish THIS round, checkpoint it, and exit 75
                # together (single process: just the local flag)
                preempt_now = (pre.triggered if process_count == 1
                               else preemption.coordinated(pre.triggered))
                if (pending_rounds
                        and (preempt_now
                             or pending_rounds >= eff_inflight
                             or rnd >= cfg.total_rounds
                             or rnd % eval_every == 0
                             or (cfg.checkpoint_every
                                 and rnd % cfg.checkpoint_every == 0))):
                    drain()
                if preempt_now:
                    tracer.instant("resilience", "preempt_boundary",
                                   round=session.round)
                    shutdown()
                    if save_ckpt:
                        # make_save_ckpt already gates writes to process 0
                        # (one writer per job; None = not this host's write)
                        path = save_ckpt()
                        if path:
                            print(
                                f"preemption: emergency checkpoint at round "
                                f"{session.round}: {path}", flush=True,
                            )
                    _postmortem("preemption")
                    sys.exit(EXIT_RESUMABLE)
                if nonfinite_total and cfg.on_nonfinite == "halt":
                    shutdown()
                    if save_ckpt:
                        save_ckpt()
                    sys.exit(
                        f"halting at round {rnd}: non-finite update skipped "
                        "(--on_nonfinite halt; "
                        + ("state checkpointed clean)" if save_ckpt
                           else "no --checkpoint_dir, nothing saved)")
                    )
                if slo is not None and slo.halted:
                    # the session's commit hook fed the SLO engine at the
                    # drain above; a latched halt exits through the SAME
                    # clean sequence the non-finite halt uses — committed
                    # state saved, writer drained, loud one-line verdict
                    shutdown()
                    if save_ckpt:
                        save_ckpt()
                    sys.exit(
                        f"halting at round {rnd}: SLO violation "
                        f"({slo.halted_reason}) (--slo halt; "
                        + ("state checkpointed clean)" if save_ckpt
                           else "no --checkpoint_dir, nothing saved)")
                    )
                if (cfg.checkpoint_every and save_ckpt
                        and rnd % cfg.checkpoint_every == 0):
                    if writer is not None:
                        writer.request()  # off the round path
                        reg.counter("runner_ckpt_async_total").inc()
                    else:
                        with tracer.span("runner", "checkpoint_sync",
                                         round=session.round):
                            save_ckpt()
                        reg.counter("runner_ckpt_sync_total").inc()
                if rnd % eval_every == 0 or rnd >= cfg.total_rounds:
                    with tracer.span("runner", "eval", round=session.round):
                        ev = eval_fn() if eval_fn is not None else {}
                    reg.counter("runner_evals_total").inc()
                    if build_row is not None and logger is not None:
                        logger.append(build_row(
                            rnd=rnd, m=last_m, totals=dict(totals), ev=ev,
                            time_s=timer(), nonfinite_total=nonfinite_total,
                        ))
                    totals.clear()
    finally:
        if profile is not None:
            profile.close()
        src.stop()
        # the prefetcher may have prepared (drawn host RNG / split the
        # device key for) rounds that were never dispatched; rewind the
        # LIVE streams to the committed round boundary so a caller reusing
        # the session (a second run_loop, run_round in a notebook) stays on
        # the bit-identical sequence the sync loop would produce. No-op
        # when the streams already sit at the boundary (sync mode, clean
        # exit).
        with session.mutate_lock:
            rng_state, rng_key = session.rng_snapshot
            session.rng.set_state(rng_state)
            session._rng_key = rng_key
            # same discipline for the dropped-client re-queue: uncommitted
            # prepares may have served (or grown) the live queue — restore
            # the ages WITH it, or the aged policy's weights would diverge
            # from the committed sequence on session reuse
            session._requeue = collections.deque(session._requeue_committed)
            session._requeue_enqueued = dict(session._requeue_ages_committed)
    # shutdown() tolerates a stored async-save failure: the final
    # synchronous save below is the corrective action (it carries its own
    # retries), and an hours-old transient write error must not block it
    shutdown()
    if save_ckpt:
        save_ckpt()  # final checkpoint, synchronous (durable before return)
        reg.counter("runner_ckpt_sync_total").inc()
    # RunStats = this run's registry deltas (see the dataclass docstring):
    # the registry is the single source of truth, RunStats its per-run view
    stats.rounds = session.round - start_round
    stats.nonfinite_rounds = int(mark.delta("runner_nonfinite_rounds_total"))
    stats.drains = int(mark.delta("runner_drains_total"))
    stats.evals = int(mark.delta("runner_evals_total"))
    stats.sync_checkpoints = int(mark.delta("runner_ckpt_sync_total"))
    stats.async_checkpoints = int(mark.delta("runner_ckpt_async_total"))
    stats.clients_dropped = int(mark.delta("cohort_clients_dropped_total"))
    stats.clients_quarantined = int(
        mark.delta("cohort_clients_quarantined_total"))
    stats.degraded_rounds = int(mark.delta("cohort_degraded_rounds_total"))
    from ..resilience.faults import ADVERSARIAL_KINDS

    stats.attacks_injected = sum(
        int(mark.delta(
            f"resilience_attack_{kind[len('client_'):]}_total"))
        for kind in ADVERSARIAL_KINDS)
    stats.slo_violations = int(mark.delta("slo_violations_total"))
    stats.max_inflight_used = eff_inflight if async_mode else 0
    stats.server_idle_ms = idle_acc[0] / max(idle_acc[1], 1)
    stats.server_idle_ms_max = idle_acc[2]
    reg.gauge("runner_rtt_ms").set(rtt_ms)
    reg.gauge("runner_max_inflight").set(stats.max_inflight_used)
    stats.wall_s = time.perf_counter() - t0
    return stats
