"""The shared run loop: block planning, overlap, and the operational wiring
(watchdog, preemption, non-finite halt, eval/checkpoint cadence) both CLIs
previously hand-rolled and copy-pasted.

Overlap model (async, the default):

    prefetch thread:  prepare N+1, N+2   (client sampling + batch assembly)
    main thread:      dispatch N, N+1, ...      (no per-dispatch host sync)
    device:           compute N, N+1, ...       (queued back-to-back)
    writer thread:    periodic checkpoint save  (staging + rename commit)
    main thread @ boundary: ONE batched device_get of every pending round's
        metrics -> commit in dispatch order -> eval / log / checkpoint

What stays synchronous, deliberately:

- **Commit order**: rounds publish (state, round counter, comm totals, RNG
  snapshot) in dispatch order under the session's mutate_lock — an
  emergency checkpoint from the watchdog's timer thread always captures a
  consistent committed view.
- **Eval**: runs only at a drained boundary (the pipeline is empty, so
  `session.state` is the exact committed params — and, with buffer
  donation on, the only state guaranteed live).
- **Emergency + preemption + final saves**: the moments where "the save
  completed" must hold before the next action (abort, exit 75, process
  end). The async writer is DRAINED before the preemption save and before
  exit.
- **Non-finite halt**: evaluated from committed metrics at drain
  boundaries — the same block granularity the old loop had with
  `--rounds_per_dispatch > 1` (the compiled `skip` guard keeps state clean
  for any rounds dispatched past the poisoned one).

`--sync_loop` collapses all of it: inline preparation, one watchdog-wrapped
prepare->dispatch->sync per round (or per fused block), blocking saves —
the old loop, kept as the A/B baseline and escape hatch. Both paths drive
the identical compiled programs in the identical order with the identical
host RNG stream, which is why tests/test_runner.py can pin them
bit-identical.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import sys
import threading
import time

import jax

from ..federated.api import FederatedSession, FedOptimizer, plan_block
from ..resilience import EXIT_RESUMABLE, PreemptionHandler
from ..utils import checkpoint as ckpt
from ..utils.logging import Timer
from ..utils.watchdog import RoundWatchdog
from .prefetch import PreparedSource, RoundPrefetcher
from .writer import AsyncCheckpointWriter


@dataclasses.dataclass
class RunnerConfig:
    """Loop shape + operational policy (mirrors the CLI flag surface; build
    one with from_args in the CLIs, or directly in tests/bench)."""

    total_rounds: int
    eval_every: int
    checkpoint_every: int = 0
    checkpoint_dir: str = ""
    rounds_per_dispatch: int = 1
    sync_loop: bool = False
    # async only: drain when this many rounds are dispatched-uncommitted,
    # even between boundaries — bounds how much work a preemption's grace
    # window has to wait out, and how stale the halt check can run
    max_inflight: int = 4
    prefetch_depth: int = 2  # 2 = double buffering
    on_nonfinite: str = "skip"  # the CLI-level halt policy ("halt" stops)
    watchdog_abort: bool = False
    no_emergency_checkpoint: bool = False

    @classmethod
    def from_args(cls, args, total_rounds: int, eval_every: int):
        return cls(
            total_rounds=total_rounds,
            eval_every=eval_every,
            checkpoint_every=args.checkpoint_every,
            checkpoint_dir=args.checkpoint_dir,
            rounds_per_dispatch=args.rounds_per_dispatch,
            sync_loop=args.sync_loop,
            on_nonfinite=args.on_nonfinite,
            watchdog_abort=args.watchdog_abort,
            no_emergency_checkpoint=args.no_emergency_checkpoint,
        )


@dataclasses.dataclass
class RunStats:
    """What the loop did — bench.py's run_loop section reads these."""

    rounds: int = 0
    wall_s: float = 0.0
    nonfinite_rounds: int = 0
    drains: int = 0
    evals: int = 0
    sync_checkpoints: int = 0
    async_checkpoints: int = 0


def make_save_ckpt(session: FederatedSession, checkpoint_dir: str):
    """The one shared save closure: serialized by its own lock (the
    watchdog's emergency save runs on a timer thread and must not race a
    scheduled/periodic save of the same round — both would target the same
    staging/final dirs), sharing the session's fault plan + retry policy so
    per-site injection counters stay coherent across the whole run."""
    lock = threading.Lock()

    def save_ckpt():
        with lock:
            return ckpt.save(
                checkpoint_dir, session,
                fault_plan=session.fault_plan,
                retry_policy=session.retry_policy,
            )

    return save_ckpt


def run_loop(
    session: FederatedSession,
    opt: FedOptimizer,
    cfg: RunnerConfig,
    *,
    eval_fn=None,
    build_row=None,
    logger=None,
    save_ckpt=None,
) -> RunStats:
    """Run the training loop from session.round to cfg.total_rounds.

    eval_fn() -> metrics dict, called at every eval boundary (drained).
    build_row(rnd, m, totals, ev, time_s, nonfinite_total) -> row dict for
    the logger; `m` is the last round's metrics, `totals` the sum of every
    numeric metric key since the previous eval row. Either may be None (no
    eval / no logging — bench runs). save_ckpt defaults to make_save_ckpt
    when cfg.checkpoint_dir is set.

    Exits the process (not returns) on preemption (EXIT_RESUMABLE) and on
    --on_nonfinite halt, after the same drain/save sequence the CLIs used
    to inline.
    """
    stats = RunStats()
    t0 = time.perf_counter()
    eval_every = max(cfg.eval_every, 1)
    start_round = session.round

    if save_ckpt is None and cfg.checkpoint_dir:
        save_ckpt = make_save_ckpt(session, cfg.checkpoint_dir)

    # escalation ladder: warn -> stacks -> emergency ckpt -> (opt-in) abort
    # with the resumable status so a supervisor relaunches with --resume
    watchdog = RoundWatchdog(
        on_emergency=save_ckpt
        if save_ckpt and not cfg.no_emergency_checkpoint else None,
        on_abort=(lambda: os._exit(EXIT_RESUMABLE))
        if cfg.watchdog_abort and save_ckpt else None,
    )

    async_mode = not cfg.sync_loop
    writer = None
    if async_mode and save_ckpt and cfg.checkpoint_every:
        if session._donate_state:
            # an overlapped save reads session.state while later rounds
            # dispatch — with donation the committed buffers are already
            # dead. Keep the periodic saves, just blocking (the HBM-tight
            # --no_emergency_checkpoint trade-off extends to overlap).
            print(
                "runner: state-buffer donation is on "
                "(--no_emergency_checkpoint); periodic checkpoint writes "
                "stay synchronous — an overlapped save would read donated "
                "buffers",
                flush=True,
            )
        else:
            writer = AsyncCheckpointWriter(save_ckpt)
    src = (
        RoundPrefetcher(session, start_round, depth=cfg.prefetch_depth)
        if async_mode else PreparedSource(session, start_round)
    )

    pending: collections.deque = collections.deque()  # in-flight dispatches
    pending_rounds = 0
    totals: collections.defaultdict = collections.defaultdict(float)
    last_m: dict | None = None
    nonfinite_total = 0
    timer = Timer()

    def drain(watch: bool = True):
        """Commit every pending dispatch: ONE batched device_get for all
        their metrics, then in-order publication + metric folding."""
        nonlocal pending_rounds, last_m, nonfinite_total
        if not pending:
            return
        first = session.round  # oldest uncommitted round index
        # the drain legitimately waits out every queued dispatch, so the
        # watchdog threshold scales by the round count and the recorded
        # time is normalized back to a per-round figure (true median)
        with (watchdog.round(first, rounds=pending_rounds)
              if watch else contextlib.nullcontext()):
            hosts = jax.device_get([fl.metrics for fl in pending])
        for m in session.commit_rounds(list(pending), hosts):
            last_m = m
            nonfinite_total += int(m.get("nonfinite_rounds", 0))
            for k, v in m.items():
                if isinstance(v, (int, float)):
                    totals[k] += v
        pending.clear()
        pending_rounds = 0
        stats.drains += 1

    def shutdown():
        """Exit-path teardown (preemption/halt): stop the prefetcher and
        drain the writer. A failed async save is reported but must NOT
        block the synchronous exit save that follows — that save is the
        corrective action (and carries its own retries)."""
        src.stop()
        if writer is not None:
            try:
                writer.drain()
            except Exception as e:  # noqa: BLE001 — exit save still runs
                print(
                    f"runner: async checkpoint failure at shutdown "
                    f"({type(e).__name__}: {e}); continuing to the "
                    "synchronous exit save", file=sys.stderr, flush=True,
                )
            writer.close()

    rnd = start_round
    try:
        with PreemptionHandler() as pre:
            while rnd < cfg.total_rounds:
                lrs = plan_block(opt, rnd, cfg.total_rounds, eval_every,
                                 cfg.checkpoint_every, cfg.rounds_per_dispatch)
                if len(lrs) > 1 and session.supports_block_dispatch:
                    # one dispatch for the block; the watchdog times the
                    # block (prefetch pull included — a stalled loader is a
                    # stall the ladder should see). In async mode a dispatch
                    # returns without a host sync in ~ms, so it must not
                    # feed the learned round-time median (record=False) —
                    # the boundary drain records the true per-round time.
                    with watchdog.round(rnd, record=cfg.sync_loop):
                        preps = [src.next() for _ in lrs]
                        pending.append(session.dispatch_block(preps, lrs))
                        if len(pending) > 1:
                            pending[-2].release_state()  # superseded head
                        pending_rounds += len(lrs)
                        if cfg.sync_loop:
                            drain(watch=False)
                    rnd += len(lrs)
                else:
                    # per-round dispatch (stateful/split/fault-plan
                    # fallback): keep the watchdog per-round so a hang is
                    # detected at round, not block, granularity
                    for j, lr in enumerate(lrs):
                        with watchdog.round(rnd + j, record=cfg.sync_loop):
                            pending.append(
                                session.dispatch_round(src.next(), lr)
                            )
                            if len(pending) > 1:
                                pending[-2].release_state()  # superseded
                            pending_rounds += 1
                            if cfg.sync_loop:
                                drain(watch=False)
                        rnd += 1
                        if pre.triggered:
                            break  # stop inside the block: the grace window
                            # is short
                if (pending_rounds
                        and (pre.triggered
                             or pending_rounds >= cfg.max_inflight
                             or rnd >= cfg.total_rounds
                             or rnd % eval_every == 0
                             or (cfg.checkpoint_every
                                 and rnd % cfg.checkpoint_every == 0))):
                    drain()
                if pre.triggered:
                    shutdown()
                    if save_ckpt:
                        path = save_ckpt()
                        print(
                            f"preemption: emergency checkpoint at round "
                            f"{session.round}: {path}", flush=True,
                        )
                    sys.exit(EXIT_RESUMABLE)
                if nonfinite_total and cfg.on_nonfinite == "halt":
                    shutdown()
                    if save_ckpt:
                        save_ckpt()
                    sys.exit(
                        f"halting at round {rnd}: non-finite update skipped "
                        "(--on_nonfinite halt; "
                        + ("state checkpointed clean)" if save_ckpt
                           else "no --checkpoint_dir, nothing saved)")
                    )
                if (cfg.checkpoint_every and save_ckpt
                        and rnd % cfg.checkpoint_every == 0):
                    if writer is not None:
                        writer.request()  # off the round path
                        stats.async_checkpoints += 1
                    else:
                        save_ckpt()
                        stats.sync_checkpoints += 1
                if rnd % eval_every == 0 or rnd >= cfg.total_rounds:
                    ev = eval_fn() if eval_fn is not None else {}
                    stats.evals += 1
                    if build_row is not None and logger is not None:
                        logger.append(build_row(
                            rnd=rnd, m=last_m, totals=dict(totals), ev=ev,
                            time_s=timer(), nonfinite_total=nonfinite_total,
                        ))
                    totals.clear()
    finally:
        src.stop()
        # the prefetcher may have prepared (drawn host RNG / split the
        # device key for) rounds that were never dispatched; rewind the
        # LIVE streams to the committed round boundary so a caller reusing
        # the session (a second run_loop, run_round in a notebook) stays on
        # the bit-identical sequence the sync loop would produce. No-op
        # when the streams already sit at the boundary (sync mode, clean
        # exit).
        with session.mutate_lock:
            rng_state, rng_key = session.rng_snapshot
            session.rng.set_state(rng_state)
            session._rng_key = rng_key
    # shutdown() tolerates a stored async-save failure: the final
    # synchronous save below is the corrective action (it carries its own
    # retries), and an hours-old transient write error must not block it
    shutdown()
    if save_ckpt:
        save_ckpt()  # final checkpoint, synchronous (durable before return)
        stats.sync_checkpoints += 1
    stats.rounds = session.round - start_round
    stats.nonfinite_rounds = nonfinite_total
    stats.wall_s = time.perf_counter() - t0
    return stats
