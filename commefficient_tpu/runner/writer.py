"""Asynchronous checkpoint writer: periodic saves off the round path.

A scheduled `--checkpoint_every` save costs a full `device_get` of the
server state plus orbax serialization plus fsync-ish filesystem traffic —
all host work the old loop paid INSIDE the round loop, stalling dispatch.
The writer moves it to a dedicated thread. This is safe to overlap because
of utils.checkpoint's commit protocol: writes stage into `.tmp_round_*` and
`os.rename` to their final name, so training can keep dispatching while a
save is in flight and a torn write can never be mistaken for a checkpoint;
`ckpt.save` itself captures a consistent (state, round, RNG-snapshot) view
under the session's mutate_lock, exactly like the watchdog's emergency save
has always done from ITS timer thread.

Contract:

- `request()` coalesces: a request arriving while a save runs marks ONE
  follow-up save (which captures the then-newest committed state) — a slow
  filesystem degrades checkpoint cadence, never queues unbounded work.
- `drain()` blocks until idle and re-raises the first stored error, so a
  failing writer surfaces at the next boundary instead of silently eating
  checkpoints; the runner drains before exit 75 (a preemption must not race
  its own emergency save against an in-flight periodic one — ckpt.save's
  caller-side lock serializes the writes themselves).
- Emergency (watchdog) and preemption saves do NOT go through the writer:
  they stay synchronous on their triggering thread, because both run at
  moments where "the save completed" must hold before the next action
  (abort / exit 75).

NOT safe with server-state buffer donation: an overlapped save reads
`session.state` while later rounds dispatch, which requires the live
buffers to survive the in-flight round (`donate_state=False` — the same
condition the watchdog's mid-round emergency save already imposes). The
runner checks and falls back to synchronous saves when donation is on.
"""

from __future__ import annotations

import sys
import threading

from ..obs import trace as obtrace


class AsyncCheckpointWriter:
    def __init__(self, save_fn, alert=None):
        """save_fn: zero-arg callable performing one checkpoint save (the
        CLI/runner closure over ckpt.save, including its serializing lock).
        alert: callable(str) for failure messages (default: stderr)."""
        self._save_fn = save_fn
        self._alert = alert or (
            lambda msg: print(msg, file=sys.stderr, flush=True)
        )
        self._cv = threading.Condition()
        self._pending = False
        self._busy = False
        self._closed = False
        self._error: BaseException | None = None
        self.saves_completed = 0
        self.saves_coalesced = 0
        self.last_path = None
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    def request(self) -> None:
        """Ask for one save of the (future) newest committed state."""
        with self._cv:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            if self._pending or self._busy:
                self.saves_coalesced += 1
            self._pending = True
            self._cv.notify_all()

    def _run(self):
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending:  # closed, nothing queued
                    return
                self._pending = False
                self._busy = True
            try:
                # the save span rides the writer's OWN track: overlap with
                # the runner track's dispatch spans is exactly what the
                # trace exists to show
                with obtrace.span("writer", "checkpoint_save"):
                    path = self._save_fn()
                with self._cv:
                    self.saves_completed += 1
                    self.last_path = path
            except BaseException as e:  # noqa: BLE001 — surfaced at drain()
                with self._cv:
                    if self._error is None:
                        self._error = e
                self._alert(
                    f"async-checkpoint: save FAILED ({type(e).__name__}: "
                    f"{e}); the failure re-raises at the next drain"
                )
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def drain(self) -> None:
        """Block until no save is queued or running; re-raise a stored
        failure (once)."""
        with self._cv:
            while self._pending or self._busy:
                self._cv.wait()
            if self._error is not None:
                e, self._error = self._error, None
                raise e

    def close(self) -> None:
        """Finish outstanding work and stop the thread (drain first if the
        caller wants errors re-raised; close itself never raises)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=60.0)
