"""Shared asynchronous run-loop harness for both training CLIs.

BENCH_flagship_r05.json measured the host<->device tunnel round-trip
(~63 ms) ABOVE the compiled round (~53 ms): on this rig the round loop is
host-overhead-bound, not compute-bound. The harness overlaps the three
host-side costs the old hand-rolled CLI loops paid serially every round —
client-batch assembly, metrics readback, checkpoint writes — with device
compute, and hoists the watchdog/preemption/non-finite-halt/eval-cadence
wiring that was copy-pasted between `cv_train.py` and `gpt2_train.py` into
one place so fixes land once.

- `prefetch.RoundPrefetcher` — double-buffered background preparation of
  client batches via `FederatedSession.prepare_round`, preserving the
  RNG-snapshot/retry semantics (a retried or replayed load is bit-identical).
- `writer.AsyncCheckpointWriter` — periodic checkpoint writes on a writer
  thread (safe to overlap: the staging-dir + rename-commit protocol means a
  torn write can never be mistaken for a checkpoint); emergency/preemption
  saves stay synchronous, and the writer is drained before exit 75.
- `loop.run_loop` — the loop itself: per-block device dispatch with metrics
  kept as DEVICE arrays until an eval/log/checkpoint boundary (JAX async
  dispatch queues rounds back-to-back; one batched `device_get` per
  boundary instead of one blocking sync per dispatch).

`--sync_loop` is the escape hatch: it reproduces the old serial loop
exactly (inline preparation, per-dispatch sync, blocking saves). The async
loop is pinned bit-identical to it — same host RNG order, same compiled
programs, same commit order — by tests/test_runner.py, including across a
checkpoint resume.
"""

from .loop import (
    RunnerConfig,
    RunStats,
    auto_inflight,
    measure_rtt_ms,
    run_loop,
)
from .prefetch import PreparedSource, RoundPrefetcher
from .writer import AsyncCheckpointWriter

__all__ = [
    "AsyncCheckpointWriter",
    "PreparedSource",
    "RoundPrefetcher",
    "RunStats",
    "RunnerConfig",
    "auto_inflight",
    "measure_rtt_ms",
    "run_loop",
]
