"""Cohort-level fault tolerance (ISSUE 4 tentpole): the per-client validity
mask and the sketch-space quarantine, at engine level.

The acceptance contract under test: a round with k masked clients is
bit-identical (params + metrics) to a reference round over just the W-k
surviving clients — on the fused path and on the sharded (mesh ==
single-device) path — and a poisoned client is rejected by the quarantine
exactly as if it had been externally masked, while an identical clean run is
untouched. conftest forces the 8-device CPU mesh, so this file is part of the
forced-8-device tier-1 slice (scripts/tier1_8dev.sh).

Bit-identity mechanics: with client_chunk=1 the weighted reduce is a scan
accumulating one client at a time, so a masked client contributes an exact
`acc + 0.0` — the partial-sum sequence over the survivors is literally the
same float operations the surviving-cohort round performs (the losses here
consume no per-client rng, so survivor gradients are identical too).
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from commefficient_tpu.federated import engine
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.parallel import mesh as meshlib
from commefficient_tpu.resilience import FaultPlan

SKETCH_KW = dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
                 hash_family="rotation", momentum_type="virtual",
                 error_type="virtual")


def quad_params(key, din=10, dout=4):
    k1, k2 = jax.random.split(key)
    return {"w": jax.random.normal(k1, (din, dout)) * 0.1,
            "b": jnp.zeros(dout)}


def quad_loss(params, net_state, batch, rng):
    """Least-squares head: the gradient scales LINEARLY with the input, so a
    client whose rows are scaled 1e3 produces an update ~1e6 x the cohort
    median — exactly what the quarantine's magnitude screen must catch (a
    tanh MLP would saturate the poison away)."""
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    per_ex = (err ** 2).sum(-1)
    loss = (per_ex * mask).sum() / count
    return loss, {"net_state": net_state,
                  "metrics": {"loss_sum": (per_ex * mask).sum(),
                              "count": mask.sum()}}


def _data(key, n, din=10, dout=4):
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (n, din))
    w_true = jax.random.normal(kw, (din, dout))
    return {"x": x, "y": (x @ w_true).argmax(-1), "mask": jnp.ones(n)}


def _batch(key, W, B=4):
    data = _data(key, W * B)
    return jax.tree.map(lambda a: a.reshape((W, B) + a.shape[1:]), data)


def _cfg(shards=1, **eng_kw):
    params = quad_params(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(**{**SKETCH_KW, "d": d})
    return params, engine.EngineConfig(mode=mcfg, weight_decay=5e-4,
                                       client_shards=shards, **eng_kw)


def _flat(state):
    return np.asarray(ravel_pytree(state["params"])[0])


def _with_valid(batch, valid):
    out = dict(batch)
    out[engine.VALID_KEY] = jnp.asarray(valid, jnp.float32)
    return out


# ------------------------------------------------- masked == surviving cohort


def test_masked_round_bit_identical_to_surviving_cohort_fused():
    """THE acceptance pin, fused path: kill clients {2, 5} of an 8-cohort via
    the validity mask -> params AND every metric bit-equal to the round
    sampled with just the 6 survivors."""
    W, dead = 8, [2, 5]
    params, cfg = _cfg(client_chunk=1)
    batch = _batch(jax.random.PRNGKey(1), W)
    valid = np.ones(W, np.float32)
    valid[dead] = 0.0
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(7)

    step = jax.jit(engine.make_round_step(quad_loss, cfg))
    s_m = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_m, _, m_m = step(s_m, _with_valid(batch, valid), {}, lr, rng)

    surv = np.flatnonzero(valid).tolist()
    ref_batch = jax.tree.map(lambda a: a[np.asarray(surv)], batch)
    ref_step = jax.jit(engine.make_round_step(quad_loss, cfg))
    s_r = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_r, _, m_r = ref_step(s_r, ref_batch, {}, lr, rng)

    np.testing.assert_array_equal(_flat(s_m), _flat(s_r))
    for a, b in zip(jax.tree.leaves(s_m["mode_state"]),
                    jax.tree.leaves(s_r["mode_state"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert set(m_m) == set(m_r)
    for k in m_r:
        np.testing.assert_array_equal(np.asarray(m_m[k]), np.asarray(m_r[k]),
                                      err_msg=k)
    assert float(m_m["participants"]) == float(len(surv))


def test_masked_round_bit_identical_to_surviving_cohort_sharded():
    """Same pin on the sharded round (single-device reference program): one
    client masked in EVERY shard (W=8 over S=4 -> survivors W-k=4 over the
    same 4 shards), so the per-shard partial sums and the ordered table
    merge are the identical float sequence in both runs."""
    W, S = 8, 4
    dead = [1, 3, 5, 7]  # position 1 of each wl=2 shard
    params, cfg = _cfg(shards=S, client_chunk=1)
    batch = _batch(jax.random.PRNGKey(2), W)
    valid = np.ones(W, np.float32)
    valid[dead] = 0.0
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(9)

    step = jax.jit(engine.make_sharded_round_step(quad_loss, cfg))
    s_m = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_m, _, m_m = step(s_m, _with_valid(batch, valid), {}, lr, rng)

    surv = np.flatnonzero(valid)
    ref_batch = jax.tree.map(lambda a: a[surv], batch)
    s_r = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_r, _, m_r = step(s_r, ref_batch, {}, lr, rng)

    np.testing.assert_array_equal(_flat(s_m), _flat(s_r))
    for k in m_r:
        np.testing.assert_array_equal(np.asarray(m_m[k]), np.asarray(m_r[k]),
                                      err_msg=k)
    assert float(m_m["participants"]) == 4.0


def test_masked_round_mesh_bit_identical_to_single_device():
    """The mask rides the batch pytree, so the 8-device shard_map round with
    a degraded cohort stays bit-identical to the single-device reference —
    params and every metric (the ISSUE's mesh-path acceptance)."""
    mesh = meshlib.make_mesh(8)
    W = 16
    params, cfg = _cfg(shards=8, client_update_clip=4.0)
    batch = _batch(jax.random.PRNGKey(3), W)
    valid = np.ones(W, np.float32)
    valid[[1, 9, 14]] = 0.0
    bm = _with_valid(batch, valid)
    lr = jnp.float32(0.1)

    ref = jax.jit(engine.make_sharded_round_step(quad_loss, cfg))
    msh = jax.jit(engine.make_sharded_round_step(quad_loss, cfg, mesh))
    s_r = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_m = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    bm_sharded = meshlib.shard_client_batch(mesh, bm)
    for i in range(3):
        rng = jax.random.PRNGKey(100 + i)
        s_r, _, m_r = ref(s_r, bm, {}, lr, rng)
        s_m, _, m_m = msh(s_m, bm_sharded, {}, lr, rng)
        assert set(m_r) == set(m_m)
        for k in m_r:
            np.testing.assert_array_equal(np.asarray(m_r[k]),
                                          np.asarray(m_m[k]), err_msg=k)
    np.testing.assert_array_equal(_flat(s_r), _flat(s_m))
    for a, b in zip(jax.tree.leaves(s_r["mode_state"]),
                    jax.tree.leaves(s_m["mode_state"])):
        # same last-bit tolerance as test_sharded_round (XLA:CPU value-
        # dependent vectorization between lax.map and shard_map bodies)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-7, atol=1e-8)
    np.testing.assert_allclose(
        float(s_r["quarantine"]["median"]), float(s_m["quarantine"]["median"]),
        rtol=2e-7)


def test_masked_client_garbage_is_inert():
    """A dead client's batch content must not matter — NaN rows behind a zero
    validity mask produce the identical round a zeroed batch does (the
    degrade path's contract: failed loads hand the engine zeros, but nothing
    may depend on that)."""
    W = 8
    params, cfg = _cfg(client_update_clip=4.0)  # quarantine armed = NaN-safe
    batch = _batch(jax.random.PRNGKey(4), W)
    valid = np.ones(W, np.float32)
    valid[3] = 0.0
    poisoned = {k: np.array(v, copy=True) for k, v in
                jax.tree.map(np.asarray, batch).items()}
    poisoned["x"][3] = np.nan
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(11)

    step = jax.jit(engine.make_round_step(quad_loss, cfg))
    s_a = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_b = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_a, _, m_a = step(s_a, _with_valid(batch, valid), {}, lr, rng)
    s_b, _, m_b = step(
        s_b, _with_valid({k: jnp.asarray(v) for k, v in poisoned.items()},
                         valid), {}, lr, rng)
    np.testing.assert_array_equal(_flat(s_a), _flat(s_b))
    for k in m_a:
        np.testing.assert_array_equal(np.asarray(m_a[k]), np.asarray(m_b[k]),
                                      err_msg=k)


# ----------------------------------------------------------------- quarantine


def _poison_rows(batch, pos, scale):
    out = {k: np.array(np.asarray(v), copy=True) for k, v in batch.items()}
    out["x"][pos] = out["x"][pos] * scale
    return {k: jnp.asarray(v) for k, v in out.items()}


@pytest.mark.parametrize("poison", ["big", "nan"])
def test_quarantine_rejects_poisoned_client_like_a_mask(poison):
    """An adversarially large (or non-finite) update is rejected by the
    quarantine EXACTLY as if the client had been externally masked: params
    bit-equal to the run whose validity mask kills that client, and the
    rejection is counted. Round 0 runs clean to seed the running median."""
    W, bad = 8, 5
    params, cfg = _cfg(client_update_clip=10.0)
    b0 = _batch(jax.random.PRNGKey(5), W)
    b1 = _batch(jax.random.PRNGKey(6), W)
    b1_poisoned = (_poison_rows(b1, bad, 1e3) if poison == "big"
                   else _poison_rows(b1, bad, np.nan))
    lr = jnp.float32(0.1)

    step = jax.jit(engine.make_round_step(quad_loss, cfg))
    s_q = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_q, _, m0 = step(s_q, b0, {}, lr, jax.random.PRNGKey(20))
    assert float(m0["clients_quarantined"]) == 0.0
    assert float(s_q["quarantine"]["median"]) > 0.0
    s_q, _, m1 = step(s_q, b1_poisoned, {}, lr, jax.random.PRNGKey(21))
    assert float(m1["clients_quarantined"]) == 1.0
    assert float(m1["participants"]) == W - 1
    assert np.isfinite(_flat(s_q)).all()

    # reference: same rounds, clean data, client `bad` externally masked
    valid = np.ones(W, np.float32)
    valid[bad] = 0.0
    s_m = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_m, _, _ = step(s_m, b0, {}, lr, jax.random.PRNGKey(20))
    s_m, _, mm = step(s_m, _with_valid(b1, valid), {}, lr,
                      jax.random.PRNGKey(21))
    np.testing.assert_array_equal(_flat(s_q), _flat(s_m))
    np.testing.assert_array_equal(np.asarray(m1["loss_sum"]),
                                  np.asarray(mm["loss_sum"]))


def test_quarantine_clean_run_untouched():
    """With no poison, the armed quarantine rejects NOTHING and the run
    matches the clip=0 run to last-bit tolerance over chained rounds (the
    two compile as different XLA programs — the NaN-safe select weighting
    refuses some reduce fusions — so this is a cross-program comparison:
    tight allclose, with the counts exact)."""
    W = 8
    params, cfg_off = _cfg()
    _, cfg_on = _cfg(client_update_clip=3.0)
    lr = jnp.float32(0.1)
    step_off = jax.jit(engine.make_round_step(quad_loss, cfg_off))
    step_on = jax.jit(engine.make_round_step(quad_loss, cfg_on))
    s_off = engine.init_server_state(cfg_off, jax.tree.map(jnp.copy, params), {})
    s_on = engine.init_server_state(cfg_on, jax.tree.map(jnp.copy, params), {})
    for i in range(3):
        b = _batch(jax.random.PRNGKey(30 + i), W)
        rng = jax.random.PRNGKey(60 + i)
        s_off, _, m_off = step_off(s_off, b, {}, lr, rng)
        s_on, _, m_on = step_on(s_on, b, {}, lr, rng)
        assert float(m_on["clients_quarantined"]) == 0.0
        assert float(m_off["participants"]) == float(m_on["participants"])
        for k in m_off:
            np.testing.assert_allclose(np.asarray(m_off[k]),
                                       np.asarray(m_on[k]), rtol=1e-6,
                                       err_msg=k)
    np.testing.assert_allclose(_flat(s_off), _flat(s_on), rtol=1e-6,
                               atol=1e-7)


def test_quarantine_split_matches_fused():
    """The two-program split round threads the quarantine verdict + running
    median across the program boundary (metrics['quarantine_median'] ->
    server qmed): params stay bit-equal to the fused step with a poisoned
    client in the cohort."""
    W, bad = 8, 2
    params, cfg = _cfg(client_update_clip=10.0)
    b0 = _batch(jax.random.PRNGKey(8), W)
    b1 = _poison_rows(_batch(jax.random.PRNGKey(9), W), bad, 1e3)
    lr = jnp.float32(0.1)

    fused = jax.jit(engine.make_round_step(quad_loss, cfg))
    client_p, server_p = engine.make_split_round_step(quad_loss, cfg)
    split = engine.compose_split(jax.jit(client_p), jax.jit(server_p))
    s_f = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_s = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    for b, seed in ((b0, 40), (b1, 41)):
        rng = jax.random.PRNGKey(seed)
        s_f, _, m_f = fused(s_f, b, {}, lr, rng)
        s_s, _, m_s = split(s_s, b, {}, lr, rng)
        assert float(m_f["clients_quarantined"]) == float(
            m_s["clients_quarantined"])
    assert float(m_f["clients_quarantined"]) == 1.0
    np.testing.assert_array_equal(_flat(s_f), _flat(s_s))
    np.testing.assert_array_equal(
        np.asarray(s_f["quarantine"]["median"]),
        np.asarray(s_s["quarantine"]["median"]))


def test_quarantine_sharded_mesh_matches_reference():
    """Per-client quarantine inside the per-shard local reduce: the poisoned
    client is rejected before the table merge (no densified cross-device
    traffic), and mesh == single-device holds with the screen armed."""
    mesh = meshlib.make_mesh(8)
    W, bad = 16, 6
    params, cfg = _cfg(shards=8, client_update_clip=10.0)
    b0 = _batch(jax.random.PRNGKey(12), W)
    b1 = _poison_rows(_batch(jax.random.PRNGKey(13), W), bad, 1e3)
    lr = jnp.float32(0.1)

    ref = jax.jit(engine.make_sharded_round_step(quad_loss, cfg))
    msh = jax.jit(engine.make_sharded_round_step(quad_loss, cfg, mesh))
    s_r = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_m = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    for b, seed in ((b0, 50), (b1, 51)):
        rng = jax.random.PRNGKey(seed)
        s_r, _, m_r = ref(s_r, b, {}, lr, rng)
        s_m, _, m_m = msh(s_m, meshlib.shard_client_batch(mesh, b), {}, lr,
                          rng)
        for k in m_r:
            np.testing.assert_array_equal(np.asarray(m_r[k]),
                                          np.asarray(m_m[k]), err_msg=k)
    assert float(m_r["clients_quarantined"]) == 1.0
    assert float(m_r["participants"]) == W - 1
    np.testing.assert_array_equal(_flat(s_r), _flat(s_m))


def test_quarantine_local_state_mode_keeps_rows_clean():
    """Per-client-wire path (local_topk with local error): a quarantined
    client's error row keeps its pre-round value — the poison never enters
    its persistent state."""
    params = quad_params(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="local_topk", d=d, k=8, momentum_type="none",
                      error_type="local", num_clients=8)
    cfg = engine.EngineConfig(mode=mcfg, client_update_clip=10.0)
    from commefficient_tpu.modes import modes as modelib

    rows = jax.vmap(lambda _: modelib.empty_client_row(mcfg))(jnp.arange(8))
    step = jax.jit(engine.make_round_step(quad_loss, cfg))
    st = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    b0 = _batch(jax.random.PRNGKey(14), 8)
    st, rows, _ = step(st, b0, rows, jnp.float32(0.1), jax.random.PRNGKey(0))
    before = np.asarray(rows["error"][4])
    b1 = _poison_rows(_batch(jax.random.PRNGKey(15), 8), 4, np.nan)
    st, rows, m = step(st, b1, rows, jnp.float32(0.1), jax.random.PRNGKey(1))
    assert float(m["clients_quarantined"]) == 1.0
    np.testing.assert_array_equal(np.asarray(rows["error"][4]), before)
    assert np.isfinite(np.asarray(rows["error"])).all()
    assert np.isfinite(_flat(st)).all()


# --------------------------------------------------------- fault-plan surface


def test_client_fault_kinds_parse_and_coerce():
    plan = FaultPlan.parse(
        "client_drop@2:clients=0+3;client_poison@2:clients=1,value=big;"
        "client_straggle@1:clients=2,secs=0.01;host_preempt@3:host=1"
    )
    assert plan.spec("client_drop", 2).params["clients"] == (0, 3)
    assert plan.spec("client_poison", 2).params["value"] == "big"
    assert plan.spec("client_straggle", 1).params["secs"] == 0.01
    assert plan.spec("host_preempt", 3).params["host"] == 1
    # coerce-and-error discipline, same as the existing sites
    with pytest.raises(ValueError, match="bad value"):
        FaultPlan.parse("client_drop@1:clients=a+b")
    with pytest.raises(ValueError, match="bad value"):
        FaultPlan.parse("client_poison@1:value=huge")
    with pytest.raises(ValueError, match="bad value"):
        FaultPlan.parse("host_preempt@1:host=zero")
    with pytest.raises(ValueError, match="unknown param"):
        FaultPlan.parse("client_drop@1:client=0")
    # "big" is poison-only: nonfinite keeps its nan/inf contract
    with pytest.raises(ValueError, match="bad value"):
        FaultPlan.parse("nonfinite@1:value=big")


def test_validate_rounds_rejects_unreachable_client_sites():
    plan = FaultPlan.parse("client_drop@7:clients=0;preempt@9")
    with pytest.raises(ValueError, match="can never fire"):
        plan.validate_rounds(6)
    plan.validate_rounds(8)  # client_drop@7 in range; preempt not a client site
    FaultPlan.parse("client_poison:clients=0").validate_rounds(1)  # unscheduled


def test_validate_wire_context_rejects_wire_kinds_without_payload_path():
    # wire_* kinds inject at the serving payload seam only: a plan naming
    # them on a run without --serve_payload sketch would pass vacuously
    # (zero injections, chaos run green) — reject it at launch instead
    plan = FaultPlan.parse("wire_corrupt@1:clients=0;conn_drop@2:clients=1")
    with pytest.raises(ValueError, match="can never fire"):
        plan.validate_wire_context(False)
    plan.validate_wire_context(True)  # payload path armed: fine
    # a plan with no wire kinds never cares about the payload path
    FaultPlan.parse("client_drop@1:clients=0").validate_wire_context(False)


def test_client_faults_apply_and_requeue_positions():
    plan = FaultPlan.parse(
        "client_drop@2:clients=0+3;client_poison@2:clients=1,value=nan")
    W = 4
    batch = {"x": np.ones((W, 2, 3), np.float32),
             "y": np.ones((W, 2), np.int32),
             "mask": np.ones((W, 2), np.float32),
             "_valid": np.ones(W, np.float32)}
    out, valid, dropped = plan.client_faults(2, batch, None, W)
    assert sorted(dropped) == [0, 3]
    np.testing.assert_array_equal(valid, [0.0, 1.0, 1.0, 0.0])
    assert (out["x"][0] == 0).all() and (out["y"][3] == 0).all()
    assert np.isnan(out["x"][1]).all() and np.isnan(out["mask"][1]).all()
    assert (out["x"][2] == 1).all()  # untouched client
    # reserved control rows are never poisoned or zeroed
    np.testing.assert_array_equal(out["_valid"], np.ones(W, np.float32))
    # wrong round: everything passes through untouched
    b2, v2, d2 = plan.client_faults(1, batch, None, W)
    assert d2 == [] and v2 is None and b2 is batch
    # out-of-range positions fail the chaos run loudly
    with pytest.raises(ValueError, match="out of range"):
        FaultPlan.parse("client_drop@0:clients=9").client_faults(
            0, batch, None, W)


def test_client_straggle_sleeps_once():
    plan = FaultPlan.parse("client_straggle@1:clients=0,secs=0.05")
    batch = {"x": np.ones((2, 2), np.float32)}
    t0 = time.monotonic()
    plan.client_faults(1, batch, None, 2)
    stalled = time.monotonic() - t0
    t0 = time.monotonic()
    plan.client_faults(1, batch, None, 2)  # one-shot per round
    again = time.monotonic() - t0
    assert stalled >= 0.05 and again < 0.05


def test_coordinated_preemption_max_reduces_across_hosts(monkeypatch):
    """resilience.coordinated = max over hosts of the local flag: a host
    WITHOUT a local SIGTERM must still see True when any peer flags (the
    one-host-preempted pod case), and single-process stays the identity
    without touching a collective."""
    from commefficient_tpu.parallel import distributed
    from commefficient_tpu.resilience import coordinated

    assert coordinated(False) is False and coordinated(True) is True
    monkeypatch.setattr(distributed, "all_hosts_max", lambda v: 1)
    assert coordinated(False) is True  # a peer host was signalled


# ------------------------------------------------- windowed quarantine median


def test_quarantine_window_default_keeps_state_tree_and_threshold():
    """quarantine_window=1 (the default) is the pre-window behavior: the
    server state carries ONLY {"median"} (so existing checkpoints stay
    shape-compatible) and the active threshold after each round is exactly
    that round's live-cohort median — which a window=K run must also agree
    with while its ring is what the window median reduces to."""
    W, K = 8, 4
    params, cfg1 = _cfg(client_update_clip=10.0)
    _, cfgK = _cfg(client_update_clip=10.0, quarantine_window=K)
    lr = jnp.float32(0.1)
    step1 = jax.jit(engine.make_round_step(quad_loss, cfg1))
    stepK = jax.jit(engine.make_round_step(quad_loss, cfgK))
    s1 = engine.init_server_state(cfg1, jax.tree.map(jnp.copy, params), {})
    sK = engine.init_server_state(cfgK, jax.tree.map(jnp.copy, params), {})
    assert set(s1["quarantine"]) == {"median"}
    assert set(sK["quarantine"]) == {"median", "window", "count"}
    assert sK["quarantine"]["window"].shape == (K,)

    meds = []  # per-round live-cohort medians (window=1 active threshold)
    for r in range(3):
        b = _batch(jax.random.PRNGKey(40 + r), W)
        s1, _, m1 = step1(s1, b, {}, lr, jax.random.PRNGKey(60 + r))
        sK, _, mK = stepK(sK, b, {}, lr, jax.random.PRNGKey(60 + r))
        meds.append(float(m1["quarantine_median"]))
        # clean data: neither run quarantines, so the cohorts (and the
        # per-round medians feeding both baselines) stay identical
        assert float(m1["clients_quarantined"]) == 0.0
        assert float(mK["clients_quarantined"]) == 0.0
        # the window=K active threshold is the median over the filled ring
        # slots — the window=1 run's per-round medians, reduced
        np.testing.assert_allclose(
            float(mK["quarantine_median"]), float(np.median(meds[-K:])),
            rtol=1e-6)
        assert int(sK["quarantine"]["count"]) == min(r + 1, K)
    # params identical too: the window only changes the THRESHOLD, and the
    # clean run never trips it
    np.testing.assert_array_equal(_flat(s1), _flat(sK))


def test_quarantine_window_tolerates_one_collapsed_round():
    """The drift scenario the window exists for: one round whose cohort
    update norms COLLAPSE (near-converged batch, lr pivot) drags the
    window=1 threshold down with it, so the NEXT round's healthy clients
    all screen as 'adversarially large' and quarantine; a window=4 baseline
    moves at window speed — one outlier round perturbs one slot — and the
    healthy cohort passes."""
    W, K = 8, 4
    params, cfg1 = _cfg(client_update_clip=10.0)
    _, cfgK = _cfg(client_update_clip=10.0, quarantine_window=K)
    lr = jnp.float32(0.1)
    b_normal = [_batch(jax.random.PRNGKey(70 + r), W) for r in range(4)]
    # the collapsed round: example masks scaled 1e-4 scale the whole loss
    # (count floors at 1.0), so every client's update norm collapses with
    # them — small but finite, the shape of a near-converged / lr-pivot
    # round
    b_tiny = {k: (v * 1e-4 if k == "mask" else v)
              for k, v in _batch(jax.random.PRNGKey(80), W).items()}
    schedule = [b_normal[0], b_normal[1], b_tiny, b_normal[2]]

    for cfg, expect_quarantined in ((cfg1, W), (cfgK, 0)):
        step = jax.jit(engine.make_round_step(quad_loss, cfg))
        s = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
        last = None
        for r, b in enumerate(schedule):
            s, _, last = step(s, b, {}, lr, jax.random.PRNGKey(90 + r))
        assert float(last["clients_quarantined"]) == expect_quarantined, (
            cfg.quarantine_window, float(last["clients_quarantined"]))


def test_quarantine_window_rejected_on_split_compile_paths():
    """The split-compile program boundary threads ONE scalar median; a
    K-slot ring cannot cross it — the combination must fail loudly at
    build time, not silently run window=1."""
    params, cfg = _cfg(client_update_clip=10.0, quarantine_window=4)
    with pytest.raises(ValueError, match="fused-paths-only"):
        engine.make_split_round_step(quad_loss, cfg)
