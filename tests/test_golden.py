"""Golden-value convergence regression (SURVEY.md §4 "Golden-value"): pins
rounds-to-loss-threshold on a fixed-seed synthetic task so optimizer/mode
regressions show up as test failures, not silent curve drift.

The committed `results/cifar10_smoke_*.jsonl` artifacts are the full-size
counterpart (ResNet-9 on synthetic CIFAR, uncompressed vs sketch, 48 rounds —
see results/README.md); this test is the fast engine-level pin.

Calibration (recorded 2026-07-29, CPU, jax_threefry_partitionable=True):
uncompressed first crosses loss 0.2 at round 15, final(40) = 0.007;
sketch k=60 c=256 final(40) = 0.26 (identical to true_topk because c >= d
makes the rotation sketch collision-free, i.e. lossless).
"""

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from commefficient_tpu.federated import engine
from commefficient_tpu.modes.config import ModeConfig

from test_engine import _data, init_mlp, mlp_loss


def _run(mode_kw, rounds=40, lr=0.2):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    cfg = engine.EngineConfig(mode=ModeConfig(d=d, **mode_kw))
    state = engine.init_server_state(cfg, params, {})
    step = jax.jit(engine.make_round_step(mlp_loss, cfg))
    data = _data(jax.random.PRNGKey(1), 64)
    batch = jax.tree.map(lambda a: a.reshape((8, 8) + a.shape[1:]), data)
    losses = []
    for r in range(rounds):
        state, _, m = step(state, batch, {}, jnp.float32(lr), jax.random.PRNGKey(r))
        losses.append(float(m["loss_sum"]) / float(m["count"]))
    return losses


def test_golden_uncompressed_rounds_to_threshold():
    losses = _run(dict(mode="uncompressed", momentum_type="virtual",
                       momentum=0.9, error_type="none"))
    first_below = next((i for i, l in enumerate(losses) if l < 0.2), None)
    assert first_below is not None and first_below <= 25, (
        f"uncompressed regressed: loss<0.2 first at round {first_below} "
        f"(calibrated: 15; pinned bound: 25)"
    )
    assert losses[-1] < 0.05, f"final loss {losses[-1]:.4f} (calibrated 0.007)"


def test_golden_sketch_rounds_to_threshold():
    losses = _run(dict(mode="sketch", k=60, num_rows=5, num_cols=256,
                       momentum_type="virtual", error_type="virtual"))
    assert losses[-1] < 0.35, f"sketch final loss {losses[-1]:.4f} (calibrated 0.26)"
    assert losses[-1] < losses[0] / 3, "sketch no longer converging"
