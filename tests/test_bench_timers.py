"""bench.py timing discipline: the class of bug that invalidated rounds 2-3
(async-dispatch illusions, chains shorter than the tunnel RTT clamping to 0)
now has unit pins. Runs bench helpers in-process on the CPU mesh."""

import math
import time

import jax
import jax.numpy as jnp
import pytest


def _import_bench(monkeypatch, **env):
    """Fresh bench import under `env`, with teardown that restores
    COMMEFFICIENT_NO_PALLAS: importing bench mutates it process-wide
    (bench.py's engine-routing knob: oracle mode SETS =1, the round-5
    default auto mode POPS it); without restore, every later in-process
    test sees the pallas library force-toggled — test_pallas's routing
    assertions fail by test ORDER, not by code (observed: 187/188 with
    this fixture first, in the oracle-default era)."""
    import importlib
    import os
    import sys

    for k, v in env.items():
        monkeypatch.setenv(k, v)
    prior = os.environ.get("COMMEFFICIENT_NO_PALLAS")
    sys.modules.pop("bench", None)
    mod = importlib.import_module("bench")

    def teardown():
        sys.modules.pop("bench", None)
        if prior is None:
            os.environ.pop("COMMEFFICIENT_NO_PALLAS", None)
        else:
            os.environ["COMMEFFICIENT_NO_PALLAS"] = prior

    return mod, teardown


@pytest.fixture()
def bench_mod(monkeypatch):
    mod, teardown = _import_bench(monkeypatch, BENCH_MODEL="resnet9")
    yield mod
    teardown()


def test_time_adaptive_measures_real_compute(bench_mod):
    """A chain whose cost is ~linear in n: the per-iteration estimate must be
    positive, finite, and flagged trustworthy (not rtt_dominated) when the
    chain dwarfs the claimed round-trip."""

    def fn_of_n(n):
        def run(x):
            def body(c, _):
                # real work XLA cannot elide: the carry feeds itself
                return c @ c / jnp.maximum(jnp.abs(c).max(), 1.0), ()

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y[0, 0]

        return run

    x = jnp.eye(256) * 1.1
    per, n, rtt_dominated = bench_mod._time_adaptive(fn_of_n, (x,), 4, rt_ms=0.0)
    assert per > 0 and n >= 4
    assert not rtt_dominated


def test_time_adaptive_flags_rtt_dominated(bench_mod):
    """An ultra-cheap chain against a huge claimed RTT must come back flagged
    rtt_dominated — round 3's 0.504 ms kernel 'measurement' was exactly this
    case silently passing as a number."""

    def fn_of_n(n):
        def run(x):
            def body(c, _):
                return c + 1.0, ()

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y

        return run

    per, n, rtt_dominated = bench_mod._time_adaptive(
        fn_of_n, (jnp.float32(0.0),), 2, rt_ms=60_000.0, cap=8)
    assert rtt_dominated  # the cap bites long before 4x a 60 s RTT
    assert per >= 0.0 and math.isfinite(per)  # clamped, never negative


def test_time_adaptive_grows_chain_toward_target(bench_mod):
    """When the first chain is too short for the 4x-RTT target, the helper
    must retry with a longer chain (growth is the fix for the clamp bug)."""
    calls = []

    def fn_of_n(n):
        calls.append(n)

        def run(x):
            def body(c, _):
                return c + 1.0, ()

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y

        return run

    bench_mod._time_adaptive(fn_of_n, (jnp.float32(0.0),), 2, rt_ms=50.0, cap=64)
    assert len(calls) == 2 and calls[1] > calls[0]  # grew once, toward cap
    assert calls[1] <= 64


def test_server_split_reports_all_ops(bench_mod, monkeypatch):
    """_server_split at tiny dims returns every attribution key with finite
    values and no error (the GPT-2 wall attribution path)."""
    from commefficient_tpu.modes.config import ModeConfig

    monkeypatch.setattr(bench_mod, "PHASE_CHAIN", 2)
    cfg = ModeConfig(mode="sketch", d=4096, k=64, num_rows=3, num_cols=1024,
                     momentum_type="virtual", error_type="virtual")
    out = bench_mod._server_split(cfg, rt_ms=0.0)
    assert "error" not in out, out
    for key in ("accumulate_ms", "estimates_ms", "topk_exact_ms",
                "topk_approx_ms", "topk_oversample_ms", "algebra_sketch_ms",
                "delta_apply_sparse_ms", "delta_apply_dense_ms",
                "ravel_unravel_ms"):
        assert key in out and out[key] >= 0.0, (key, out)
    assert out["d"] == 4096 and out["k"] == 64


def test_server_split_topk_runs_at_engine_recall(bench_mod, monkeypatch):
    """ADVICE r5: the isolated topk_approx/oversample chains must run at the
    recall the ENGINE actually runs (mode_cfg.topk_recall), not topk_abs's
    default 0.95 — approx_max_k's cost depends on recall_target, so the
    attribution would otherwise measure a different op."""
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.sketch import csvec

    calls = []
    real = csvec.topk_abs

    def spy(x, k, approx=False, recall=0.95, impl=None):
        calls.append((impl, recall))
        return real(x, k, approx=approx, recall=recall, impl=impl)

    monkeypatch.setattr(csvec, "topk_abs", spy)
    monkeypatch.setattr(bench_mod, "PHASE_CHAIN", 2)
    cfg = ModeConfig(mode="sketch", d=4096, k=64, num_rows=3, num_cols=1024,
                     momentum_type="virtual", error_type="virtual",
                     topk_recall=0.7)
    out = bench_mod._server_split(cfg, rt_ms=0.0)
    assert "error" not in out, out
    assert out["topk_recall"] == 0.7
    recalls = {r for impl, r in calls if impl in ("approx", "oversample")}
    assert recalls == {0.7}, calls


def test_run_loop_bench_measures_both_arms(monkeypatch):
    """bench's run_loop section must drive a real FederatedSession through
    the shared harness in BOTH loop modes and report the acceptance pair
    (wall_clock_updates_per_sec, host_overhead_ms) per arm, plus fold an
    injected fault's footprint into nonfinite_rounds."""
    bench, teardown = _import_bench(
        monkeypatch, BENCH_MODEL="resnet9", BENCH_WORKERS="2",
        BENCH_LOCAL_BATCH="2", BENCH_COLS="512", BENCH_TOPK="32",
        BENCH_BLOCKS="1", BENCH_DTYPE="float32",
        BENCH_RUN_LOOP_ROUNDS="3",
        # nonfinite@3 lands inside the timed sync arm (rounds 2-4 after the
        # 2-round warmup); preempt@4 must be STRIPPED, not SIGTERM the bench
        BENCH_FAULT_PLAN="nonfinite@3;preempt@4",
    )
    try:
        import flax.linen as nn

        from commefficient_tpu.models.losses import make_classification_loss

        class _TinyNet(nn.Module):
            num_classes: int = 10
            dtype: str = "float32"

            @nn.compact
            def __call__(self, x, train=False):
                x = x.reshape((x.shape[0], -1))
                return nn.Dense(self.num_classes)(x)

        def tiny_workload():
            model = _TinyNet()
            x0 = jnp.zeros((1, 32, 32, 3), jnp.float32)
            params = model.init(jax.random.PRNGKey(0), x0, train=False)["params"]
            loss_fn = make_classification_loss(model, train=True)
            sketch_kw = dict(k=32, num_rows=3, num_cols=512, num_blocks=1)
            return params, {}, None, loss_fn, "tiny", sketch_kw, 2

        monkeypatch.setattr(bench, "_resnet9_workload", tiny_workload)
        out = bench._run_loop_bench(round_ms=0.0)
        assert "error" not in out, out
        for arm in ("sync", "async"):
            assert out[arm]["wall_clock_updates_per_sec"] > 0
            assert "host_overhead_ms" in out[arm]
            assert out[arm]["drains"] >= 1
        assert out["async_speedup_vs_sync"] > 0
        assert out["nonfinite_rounds"] == 1  # the injected burst, counted
        assert "stripped" in out["fault_plan_note"]
    finally:
        teardown()


def test_flops_chunked_matches_unchunked(monkeypatch):
    """XLA cost analysis counts a lax.scan body ONCE, so the chunked client
    step (BENCH_CLIENT_CHUNK > 0) undercounts flops by the trip count —
    BENCH_flagship_w256_r05.json carried W=64's flops at W=256 and an MFU
    understated 4x. _flops_per_round's chunk_trips rescaling must bring the
    chunked estimate back to the unchunked one (same W, same dims)."""
    bench, teardown = _import_bench(
        monkeypatch, BENCH_MODEL="resnet9", BENCH_WORKERS="4",
        BENCH_LOCAL_BATCH="1", BENCH_COLS="256", BENCH_TOPK="32",
        BENCH_BLOCKS="1", BENCH_DTYPE="float32",
    )
    try:
        from jax.flatten_util import ravel_pytree

        params, net_state, batch, loss_fn, _, sketch_kw, workers = (
            bench._resnet9_workload())
        d = ravel_pytree(params)[0].size

        def build(chunk):
            monkeypatch.setenv("BENCH_CLIENT_CHUNK", str(chunk))
            eng, mode_cfg, cfg, step = bench._make_step(loss_fn, sketch_kw, d)
            state = eng.init_server_state(
                cfg, jax.tree.map(jnp.copy, params),
                jax.tree.map(jnp.copy, net_state))
            return cfg, step, state

        _, step0, state0 = build(0)
        f0, note0 = bench._flops_per_round(step0, state0, batch, 1)
        cfg1, step1, state1 = build(2)
        trips = workers // cfg1.client_chunk
        assert trips == 2
        f1, note1 = bench._flops_per_round(step1, state1, batch, trips)
        assert note0 is None and note1 is not None
        assert f0 and f1
        # scan plumbing adds epsilon; the convs dominate, so within 10%
        assert abs(f1 - f0) / f0 < 0.10, (f0, f1)
    finally:
        teardown()


def test_gpt2_chunk_default_divides_any_cohort(monkeypatch):
    """The gpt2 client_chunk default must divide W for ANY BENCH_WORKERS a
    smoke run might set (the engine raises on non-divisors): gcd(8, W)
    degrades gracefully — 8 for the W=64 default, 2 for a W=6 smoke."""
    monkeypatch.delenv("BENCH_CLIENT_CHUNK", raising=False)
    for w, expect in (("64", 8), ("6", 2), ("3", 1), ("16", 8)):
        bench, teardown = _import_bench(
            monkeypatch, BENCH_MODEL="gpt2", BENCH_GPT2_SIZE="tiny",
            BENCH_WORKERS=w, BENCH_COLS="1024", BENCH_TOPK="16",
            BENCH_BLOCKS="1", BENCH_SEQ="16")
        try:
            def dummy_loss(params, net_state, batch, rng):
                raise AssertionError("never traced at build time")
            _, _, cfg, _ = bench._make_step(
                dummy_loss, dict(k=16, num_rows=3, num_cols=1024,
                                 num_blocks=1), d=4096)
            assert cfg.client_chunk == expect, (w, cfg.client_chunk)
            assert int(w) % cfg.client_chunk == 0
        finally:
            teardown()
