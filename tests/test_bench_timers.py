"""bench.py timing discipline: the class of bug that invalidated rounds 2-3
(async-dispatch illusions, chains shorter than the tunnel RTT clamping to 0)
now has unit pins. Runs bench helpers in-process on the CPU mesh."""

import math
import time

import jax
import jax.numpy as jnp
import pytest


@pytest.fixture()
def bench_mod(monkeypatch):
    import importlib
    import os
    import sys

    monkeypatch.setenv("BENCH_MODEL", "resnet9")
    # importing bench mutates COMMEFFICIENT_NO_PALLAS process-wide
    # (bench.py's engine-routing knob: oracle mode SETS =1, the round-5
    # default auto mode POPS it); without restore, every later in-process
    # test sees the pallas library force-toggled — test_pallas's routing
    # assertions fail by test ORDER, not by code (observed: 187/188 with
    # this fixture first, in the oracle-default era)
    prior = os.environ.get("COMMEFFICIENT_NO_PALLAS")
    sys.modules.pop("bench", None)
    mod = importlib.import_module("bench")
    yield mod
    sys.modules.pop("bench", None)
    if prior is None:
        os.environ.pop("COMMEFFICIENT_NO_PALLAS", None)
    else:
        os.environ["COMMEFFICIENT_NO_PALLAS"] = prior


def test_time_adaptive_measures_real_compute(bench_mod):
    """A chain whose cost is ~linear in n: the per-iteration estimate must be
    positive, finite, and flagged trustworthy (not rtt_dominated) when the
    chain dwarfs the claimed round-trip."""

    def fn_of_n(n):
        def run(x):
            def body(c, _):
                # real work XLA cannot elide: the carry feeds itself
                return c @ c / jnp.maximum(jnp.abs(c).max(), 1.0), ()

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y[0, 0]

        return run

    x = jnp.eye(256) * 1.1
    per, n, rtt_dominated = bench_mod._time_adaptive(fn_of_n, (x,), 4, rt_ms=0.0)
    assert per > 0 and n >= 4
    assert not rtt_dominated


def test_time_adaptive_flags_rtt_dominated(bench_mod):
    """An ultra-cheap chain against a huge claimed RTT must come back flagged
    rtt_dominated — round 3's 0.504 ms kernel 'measurement' was exactly this
    case silently passing as a number."""

    def fn_of_n(n):
        def run(x):
            def body(c, _):
                return c + 1.0, ()

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y

        return run

    per, n, rtt_dominated = bench_mod._time_adaptive(
        fn_of_n, (jnp.float32(0.0),), 2, rt_ms=60_000.0, cap=8)
    assert rtt_dominated  # the cap bites long before 4x a 60 s RTT
    assert per >= 0.0 and math.isfinite(per)  # clamped, never negative


def test_time_adaptive_grows_chain_toward_target(bench_mod):
    """When the first chain is too short for the 4x-RTT target, the helper
    must retry with a longer chain (growth is the fix for the clamp bug)."""
    calls = []

    def fn_of_n(n):
        calls.append(n)

        def run(x):
            def body(c, _):
                return c + 1.0, ()

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y

        return run

    bench_mod._time_adaptive(fn_of_n, (jnp.float32(0.0),), 2, rt_ms=50.0, cap=64)
    assert len(calls) == 2 and calls[1] > calls[0]  # grew once, toward cap
    assert calls[1] <= 64


def test_server_split_reports_all_ops(bench_mod, monkeypatch):
    """_server_split at tiny dims returns every attribution key with finite
    values and no error (the GPT-2 wall attribution path)."""
    from commefficient_tpu.modes.config import ModeConfig

    monkeypatch.setattr(bench_mod, "PHASE_CHAIN", 2)
    cfg = ModeConfig(mode="sketch", d=4096, k=64, num_rows=3, num_cols=1024,
                     momentum_type="virtual", error_type="virtual")
    out = bench_mod._server_split(cfg, rt_ms=0.0)
    assert "error" not in out, out
    for key in ("accumulate_ms", "estimates_ms", "topk_exact_ms", "topk_approx_ms"):
        assert key in out and out[key] >= 0.0, (key, out)
    assert out["d"] == 4096 and out["k"] == 64
