"""Byzantine-robust always-on aggregation (async x robust composition).

The pins, in dependency order:

- the WEIGHTED robust merge (per-buffer union stack {current buffer +
  staleness-weighted stale folds}) against a numpy reference, incl. the
  unit-weight reduction to the PR 10 unweighted forms and the winsorized
  error-feedback residual's boundedness;
- program identity: a zero-stale async ROBUST round == the sync robust
  round (params + every logged row, bitwise), and trimmed@0 async
  on-time == the sync sum run bitwise;
- THE seeded A/B: under the ADAPTIVE attackers (client_normride riding
  just under the quarantine multiple, client_stale_poison submitting into
  the stale band), async `--merge_policy trimmed|median` stays within the
  PR 10 eps-band of its OWN clean async run while the attacked async sum
  degrades measurably;
- error feedback: `verror_ratio` (the PR 12 telescoping-health estimator)
  stays bounded over a sustained-attack robust-merge run with
  --robust_residual on;
- the stale-buffer checkpoint discipline: band state rides meta.json, a
  CLI async preempt -> --resume with a NON-EMPTY stale buffer mid-flight
  is bit-identical to the uninterrupted twin (params + rows + ledger
  fingerprints), and session reuse prunes/rewinds the checkpointed band.
"""

from __future__ import annotations

import collections
import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

import cv_train
from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated import engine
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.obs import registry as obreg
from commefficient_tpu.resilience import FaultPlan
from commefficient_tpu.runner.loop import EXIT_RESUMABLE
from commefficient_tpu.serve.ingest import ACCEPTED_STALE
from commefficient_tpu.serve.service import AggregationService, ServeConfig
from commefficient_tpu.serve.traffic import TraceConfig, TrafficGenerator

LR = 0.05


# ------------------------------------------------------------------ fixtures


def quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0), {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


_RS = np.random.RandomState(0)
_X = _RS.randn(240, 6).astype(np.float32)
_Y = (_X @ _RS.randn(6, 3).astype(np.float32)).argmax(-1).astype(np.int32)


def make_session(num_workers=12, stale_slots=0, seed=0, **kw):
    train = FedDataset(_X, _Y,
                       shard_iid(len(_X), 12, np.random.RandomState(1)))
    params = {"w": jnp.full((6, 3), 0.1, jnp.float32), "b": jnp.zeros(3)}
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=quad_loss, eval_loss_fn=quad_loss,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="sketch", d=d, k=8, num_rows=3,
                            num_cols=16, momentum=0.0, momentum_type="none",
                            error_type="virtual"),
        train_set=train, num_workers=num_workers, local_batch_size=16,
        seed=seed, wire_payloads=True, stale_slots=stale_slots, **kw)


def flat_params(session) -> np.ndarray:
    return np.asarray(
        ravel_pytree(jax.device_get(session.state["params"]))[0])


def serve_rounds(session, cfg, rounds, trace_seed=5):
    """Drive served rounds through the runner dispatch shape (the
    test_pipeline_serve harness); returns the metric rows."""
    svc = AggregationService(
        session, cfg,
        traffic=TrafficGenerator(
            TraceConfig(population=session.train_set.num_clients,
                        seed=trace_seed))).start()
    rows = []
    try:
        src = svc.source()
        for _ in range(rounds):
            prep = src.next()
            rows.append(session.commit_round(
                session.dispatch_round(prep, LR))[0])
            src.on_dispatched(session.round - 1)
            src.on_committed(session.round)
        src.stop()
        with session.mutate_lock:
            rng_state, rng_key = session.rng_snapshot
            session.rng.set_state(rng_state)
            session._rng_key = rng_key
            session._requeue = collections.deque(
                session._requeue_committed)
            session._requeue_enqueued = dict(
                session._requeue_ages_committed)
    finally:
        svc.close()
    return rows


def _assert_params_equal(sa, sb):
    np.testing.assert_array_equal(flat_params(sa), flat_params(sb))


def _assert_rows_equal(ra, rb):
    for a, b in zip(ra, rb):
        assert set(a) == set(b)
        for k in a:
            assert a[k] == b[k], (k, a[k], b[k])


# ------------------------------------- the weighted union-stack robust merge


def _np_weighted_trimmed(tables, weights, trim):
    """Per-coordinate python reference: rank the positive-weight FINITE
    entries by (value, stack index), drop `trim` from each end, weighted
    mean of the survivors. Returns (robust, total_weight)."""
    W = tables.shape[0]
    flat = tables.reshape(W, -1)
    w = np.array([weights[i] if np.isfinite(flat[i]).all() else 0.0
                  for i in range(W)])
    n = int((w > 0).sum())
    res = np.zeros(flat.shape[1], np.float64)
    for c in range(flat.shape[1]):
        rows = sorted((flat[i, c], i) for i in range(W) if w[i] > 0)
        kept = rows[trim:n - trim]
        if kept and n > 2 * trim:
            num = sum(v * w[i] for v, i in kept)
            den = sum(w[i] for _, i in kept)
            res[c] = num / den
    return res.reshape(tables.shape[1:]).astype(np.float32), w.sum()


def _np_weighted_median(tables, weights):
    W = tables.shape[0]
    flat = tables.reshape(W, -1)
    w = np.array([weights[i] if np.isfinite(flat[i]).all() else 0.0
                  for i in range(W)])
    total = w.sum()
    res = np.zeros(flat.shape[1], np.float64)
    for c in range(flat.shape[1]):
        rows = sorted((flat[i, c], i) for i in range(W) if w[i] > 0)
        if not rows:
            continue
        cum, lo, hi = 0.0, None, None
        for v, i in rows:
            cum += w[i]
            if lo is None and cum >= total / 2:
                lo = v
            if hi is None and cum > total / 2:
                hi = v
        if hi is None:
            hi = rows[-1][0]
        res[c] = 0.5 * (lo + hi)
    return res.reshape(tables.shape[1:]).astype(np.float32)


def test_weighted_union_merge_matches_numpy_reference():
    rs = np.random.RandomState(3)
    tables = rs.randn(5, 2, 4).astype(np.float32)
    stale = rs.randn(3, 2, 4).astype(np.float32)
    live = np.array([1, 0, 1, 1, 1], np.float32)
    sw = np.array([2 ** -0.5, 3 ** -0.5, 0.0], np.float32)  # slot 2 empty
    union = np.concatenate([tables, stale])
    uw = np.concatenate([live, sw])

    got, total, extras = modes._robust_table_merge(
        jnp.asarray(tables), jnp.asarray(live), "trimmed", 1,
        stale_tables=jnp.asarray(stale), stale_weights=jnp.asarray(sw))
    ref, ref_total = _np_weighted_trimmed(union, uw, 1)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-7)
    assert float(total) == pytest.approx(ref_total, rel=1e-6)
    assert int(extras["stale_folded"]) == 2  # the empty slot excluded
    assert float(extras["stale_weight"]) == pytest.approx(sw.sum(), 1e-6)

    got_m, total_m, _ = modes._robust_table_merge(
        jnp.asarray(tables), jnp.asarray(live), "median", 0,
        stale_tables=jnp.asarray(stale), stale_weights=jnp.asarray(sw))
    np.testing.assert_allclose(np.asarray(got_m),
                               _np_weighted_median(union, uw),
                               rtol=1e-5, atol=1e-7)
    assert float(total_m) == pytest.approx(ref_total, rel=1e-6)


def test_weighted_unit_weights_reduce_to_unweighted():
    """The extended path with zero stale entries reduces VALUE-exactly to
    the PR 10 unweighted forms (the bitwise async==sync contract rides on
    program identity, but the weighted math itself must also agree)."""
    rs = np.random.RandomState(7)
    tables = rs.randn(6, 3, 5).astype(np.float32)
    live = np.array([1, 0, 1, 1, 1, 1], np.float32)
    for policy, trim in (("trimmed", 1), ("median", 0)):
        old = np.asarray(modes._robust_table_merge(
            jnp.asarray(tables), jnp.asarray(live), policy, trim))
        new, total, extras = modes._robust_table_merge(
            jnp.asarray(tables), jnp.asarray(live), policy, trim,
            want_residual=True)
        np.testing.assert_array_equal(old, np.asarray(new))
        assert float(total) == live.sum()
        assert np.isfinite(np.asarray(extras["residual"])).all()


def test_residual_is_winsorized_and_bounded():
    """The error-feedback residual clamps every contribution into the
    policy's kept window before averaging: an adversarial outlier moves
    the residual at most to the kept range's edge — never by its own
    magnitude. (The naive mean-vs-robust residual would re-inject the
    full attack mass into Verror, defeating the robust merge.)"""
    honest = np.linspace(-1.0, 1.0, 5, dtype=np.float32).reshape(5, 1, 1)
    attacked = honest.copy()
    attacked[0] = 1e6  # a huge in-stack outlier
    live = jnp.ones(5)
    _, _, ex_h = modes._robust_table_merge(
        jnp.asarray(honest), live, "trimmed", 1, want_residual=True)
    _, _, ex_a = modes._robust_table_merge(
        jnp.asarray(attacked), live, "trimmed", 1, want_residual=True)
    r_h = float(np.asarray(ex_h["residual"]).squeeze())
    r_a = float(np.asarray(ex_a["residual"]).squeeze())
    # the outlier is clamped to the kept window's upper edge (value 1.0 at
    # rank n-trim-1 = 0.5's neighbor): the residual shift is bounded by
    # the clean value range, nowhere near 1e6 / 5
    assert abs(r_a - r_h) <= 2.0, (r_h, r_a)
    # and a reference check: residual == winsorized weighted mean - robust
    vals = np.sort(attacked.squeeze())
    clamped = np.clip(attacked.squeeze(), vals[1], vals[3])
    robust = np.mean(np.sort(attacked.squeeze())[1:4])
    assert r_a == pytest.approx(clamped.mean() - robust, rel=1e-5)


def test_robust_residual_changes_params_and_stays_finite():
    a = make_session(merge_policy="trimmed", merge_trim=3)
    b = make_session(merge_policy="trimmed", merge_trim=3,
                     robust_residual=True)
    for _ in range(4):
        a.run_round(LR)
        b.run_round(LR)
    fa, fb = flat_params(a), flat_params(b)
    assert np.isfinite(fb).all()
    assert not np.array_equal(fa, fb)  # the residual really entered Verror


# ----------------------------------------------- program-identity pins


def test_async_robust_zero_stale_bitwise_equals_sync_robust():
    """An async ROBUST run where every submission answers the open round
    dispatches the plain robust merge program every round — the PR 10
    sync robust round by program identity: params + every logged row
    bitwise equal to the sync robust run."""
    for policy, kw in (("median", {}), ("trimmed", {"merge_trim": 3})):
        a = make_session(merge_policy=policy, **kw)
        ra = serve_rounds(a, ServeConfig(quorum=12, deadline_s=1e9,
                                         payload="sketch"), 4)
        b = make_session(merge_policy=policy, stale_slots=12, **kw)
        rb = serve_rounds(b, ServeConfig(quorum=12, deadline_s=1e9,
                                         payload="sketch", async_mode=True,
                                         buffer_size=12), 4)
        _assert_rows_equal(ra, rb)
        _assert_params_equal(a, b)


def test_trimmed_zero_async_on_time_bitwise_equals_sync_sum():
    """trimmed@0 + zero stale: the async run compiles and dispatches the
    plain SUM program (robust_policy resolves to None), pinned bitwise
    against the sync sum run."""
    a = make_session()
    ra = serve_rounds(a, ServeConfig(quorum=12, deadline_s=1e9,
                                     payload="sketch"), 4)
    b = make_session(merge_policy="trimmed", merge_trim=0, stale_slots=12)
    rb = serve_rounds(b, ServeConfig(quorum=12, deadline_s=1e9,
                                     payload="sketch", async_mode=True,
                                     buffer_size=12), 4)
    _assert_rows_equal(ra, rb)
    _assert_params_equal(a, b)


def test_async_robust_straggler_folds_into_union_stack():
    """With the buffer trigger below the arrival count, a robust async
    round's stragglers JOIN the weighted order statistics (stale_folded /
    stale_weight metrics emitted by the union-stack merge) and the
    trajectory differs from the sync robust run that drops them."""
    reg = obreg.default()
    base = reg.counter("serve_stale_folded_total").value
    a = make_session(merge_policy="trimmed", merge_trim=3, stale_slots=12)
    ra = serve_rounds(a, ServeConfig(quorum=12, deadline_s=60.0,
                                     payload="sketch", async_mode=True,
                                     buffer_size=6), 5)
    assert reg.counter("serve_stale_folded_total").value > base
    folded_rows = [r for r in ra if r.get("stale_folded", 0) > 0]
    assert folded_rows, ra
    for r in folded_rows:
        assert 0 < r["stale_weight"] < r["stale_folded"]  # (1+lag)^-0.5 < 1
    assert np.isfinite(flat_params(a)).all()
    b = make_session(merge_policy="trimmed", merge_trim=3)
    serve_rounds(b, ServeConfig(quorum=6, deadline_s=60.0,
                                payload="sketch"), 5)
    assert not np.array_equal(flat_params(a), flat_params(b))


def test_async_robust_union_stack_shard_invariant():
    """Per-client tables make the union-stack robust statistic
    shard-count-invariant, stale folds included: client_shards=2 bitwise
    equals the unsharded async robust run (the mesh-shape-invariance
    claim, on the CPU reference execution)."""

    def run(shards):
        s = make_session(merge_policy="trimmed", merge_trim=3,
                         stale_slots=12, client_shards=shards)
        serve_rounds(s, ServeConfig(quorum=12, deadline_s=60.0,
                                    payload="sketch", async_mode=True,
                                    buffer_size=6), 4)
        return s

    a, b = run(0), run(2)
    _assert_params_equal(a, b)


# ------------------------------------------------- THE adaptive-attack A/B


_AB_ROUNDS = 6
_AB_ALL = ",".join(str(r) for r in range(_AB_ROUNDS))
# normride makes the sign-flip maximal: the flipped table rides at
# 0.95 x clip x running_median — the largest in-screen poison there is
ATTACKS = {
    "client_normride": (
        f"client_signflip@{_AB_ALL}:clients=0+1;"
        f"client_normride@{_AB_ALL}:clients=0+1,ride=0.95"),
    "client_stale_poison": (
        f"client_stale_poison@{','.join(str(r) for r in range(_AB_ROUNDS - 1))}"
        ":clients=0+1,factor=-5"),
}

_AB_POLICIES = {
    "sum": {"merge_policy": "trimmed", "merge_trim": 0},
    "trimmed": {"merge_policy": "trimmed", "merge_trim": 3},
    "median": {"merge_policy": "median"},
}


def _ab_arm(policy_kw, plan_text=None) -> float:
    s = make_session(
        stale_slots=12, client_update_clip=10.0,
        fault_plan=FaultPlan.parse(plan_text) if plan_text else None,
        **policy_kw)
    # buffer 10-of-12: a withheld stale-poison client's table enters the
    # band late; the clean arms fold their own (honest) stragglers — the
    # union stack is exercised in EVERY arm
    serve_rounds(s, ServeConfig(quorum=12, deadline_s=1e9,
                                payload="sketch", async_mode=True,
                                buffer_size=10), _AB_ROUNDS)
    ds = FedDataset(_X, _Y, shard_iid(len(_X), 12, np.random.RandomState(1)))
    ev = s.evaluate(ds, batch_size=64)
    return ev["loss_sum"] / max(ev["count"], 1)


@pytest.mark.parametrize("kind", list(ATTACKS))
def test_adaptive_attack_degrades_async_sum_robust_recovers(kind):
    """THE acceptance A/B, fully seeded, on the BUFFERED path: under the
    adaptive attackers the async linear sum ends measurably worse than
    its own clean async run, while async trimmed AND median stay within
    the PR 10 eps-band (0.75 x the sum's damage, one-sided) of their OWN
    clean async runs and strictly beat the attacked sum — the per-buffer
    robust merge answering what the screens cannot."""
    clean = {p: _ab_arm(dict(kw)) for p, kw in _AB_POLICIES.items()}
    att = {p: _ab_arm(dict(kw), ATTACKS[kind])
           for p, kw in _AB_POLICIES.items()}
    deg = att["sum"] - clean["sum"]
    assert deg > 0.05, (
        f"{kind} under the async linear sum should degrade the eval loss "
        f"measurably (clean {clean['sum']:.4f}, attacked {att['sum']:.4f})")
    eps = 0.75 * deg
    for policy in ("trimmed", "median"):
        gap = att[policy] - clean[policy]
        assert gap < eps, (
            f"{kind} under async {policy}: attacked {att[policy]:.4f} vs "
            f"own clean {clean[policy]:.4f} — gap {gap:.4f} exceeds "
            f"eps={eps:.4f} (sum degraded by {deg:.4f})")
        assert att[policy] < att["sum"], (
            f"{kind}: async {policy} ({att[policy]:.4f}) should strictly "
            f"beat the attacked async sum ({att['sum']:.4f})")


def test_stale_poison_is_wire_faithful():
    """The attack's two halves land where a real adversary's would: the
    withheld position no-shows its round (masked + requeued), the late
    poisoned table is ACCEPTED_STALE through the real admission band
    (counters + instants), and the per-kind attack counter fires."""
    reg = obreg.default()
    before = {
        "attack": reg.counter("resilience_attack_stale_poison_total").value,
        "admitted": reg.counter("serve_stale_admitted_total").value,
    }
    s = make_session(stale_slots=12, client_update_clip=10.0,
                     fault_plan=FaultPlan.parse(
                         "client_stale_poison@1:clients=0"))
    rows = serve_rounds(s, ServeConfig(quorum=12, deadline_s=1e9,
                                       payload="sketch", async_mode=True,
                                       buffer_size=10), 4)
    assert reg.counter("resilience_attack_stale_poison_total").value \
        == before["attack"] + 1
    assert reg.counter("serve_stale_admitted_total").value \
        > before["admitted"]
    # the withheld client was masked out of round 1 like any no-show
    assert rows[1]["clients_dropped"] >= 1, rows[1]
    # and its poisoned table folded into round 2's merge
    assert rows[2].get("stale_folded", 0) >= 1, rows[2]


def test_normride_rides_under_the_quarantine():
    """The rider probes the running median from BELOW the multiple: the
    quarantine never fires on it (that is the attack's whole point), the
    per-kind counter does, and the trajectory moves measurably."""
    reg = obreg.default()
    base = reg.counter("resilience_attack_normride_total").value
    plan = FaultPlan.parse("client_normride@1,2,3:clients=0,ride=0.9")
    a = make_session(client_update_clip=3.0, fault_plan=plan)
    ra = [a.run_round(LR) for _ in range(4)]
    assert reg.counter("resilience_attack_normride_total").value > base
    assert all(r.get("clients_quarantined", 0) == 0 for r in ra), ra
    b = make_session(client_update_clip=3.0)
    [b.run_round(LR) for _ in range(4)]
    assert not np.array_equal(flat_params(a), flat_params(b))
    assert np.isfinite(flat_params(a)).all()


def test_normride_validation():
    with pytest.raises(ValueError, match="client_update_clip"):
        make_session(fault_plan=FaultPlan.parse(
            "client_normride@1:clients=0"))
    with pytest.raises(ValueError, match="ride fraction"):
        FaultPlan.parse("client_normride@1:clients=0,ride=1.5")


def test_stale_poison_context_validation():
    plan = FaultPlan.parse("client_stale_poison@1:clients=0")
    with pytest.raises(ValueError, match="stale"):
        plan.validate_stale_context(False)
    plan.validate_stale_context(True)  # armed: fine
    # factor=0 is a drop in disguise, rejected at parse like client_scale
    with pytest.raises(ValueError, match="finite nonzero"):
        FaultPlan.parse("client_stale_poison@1:clients=0,factor=0")
    # scheduled at the FINAL round the withhold would fire (and the
    # counter tick) but the late submission could never land — rejected
    # one round earlier than the generic schedule check
    plan.validate_rounds(3)  # round 1 of 3: lands during round 2 — fine
    with pytest.raises(ValueError, match="NEXT round"):
        plan.validate_rounds(2)  # round 1 of 2 == the final round


# ----------------------------------------- verror telescoping under attack


def test_verror_ratio_bounded_under_sustained_attack():
    """--robust_residual on + --health_every 1: over a sustained
    norm-riding sign-flip attack against the trimmed merge, the PR 12
    `verror_ratio` estimator (Verror mass vs round-update mass) stays
    bounded — the winsorized residual re-enters honest mass through error
    feedback without accumulating the attack (telescoping holds)."""
    from commefficient_tpu.obs.health import HealthMonitor

    def run(plan_text, rounds=16):
        plan = FaultPlan.parse(plan_text) if plan_text else None
        s = make_session(merge_policy="trimmed", merge_trim=3,
                         robust_residual=True, client_update_clip=10.0,
                         health_every=1, fault_plan=plan)
        s.health_monitor = HealthMonitor(
            mode_cfg=s.cfg.mode, num_workers=s.num_workers, health_every=1)
        for _ in range(rounds):
            s.run_round(LR)
        return s.health_monitor.series("verror_ratio")

    rng = ",".join(str(r) for r in range(1, 16))
    attacked = run(f"client_signflip@{rng}:clients=0+1;"
                   f"client_normride@{rng}:clients=0+1,ride=0.9")
    clean = run(None)
    assert len(attacked) >= 14 and len(clean) >= 14
    assert all(np.isfinite(v) for v in attacked), attacked
    # bounded: the attacked run's telescoping profile tracks the CLEAN
    # run's own warm-up — the residual re-entered honest mass without
    # accumulating the attack (a naive mean residual grows this ratio
    # with the attack mass round over round, without limit)
    assert max(attacked) < 25.0, attacked
    assert max(attacked) <= 1.5 * max(clean) + 0.1, (attacked, clean)
    assert attacked[-1] <= 1.5 * clean[-1] + 0.1, (attacked, clean)


# --------------------------------------- stale-buffer checkpoint discipline


def test_band_state_rides_serve_meta_and_restores():
    """A non-empty stale band (parked arrival + straggler stash + poison
    in flight) round-trips through the serve_meta checkpoint payload into
    a fresh service on a restored session."""
    s = make_session(stale_slots=12, client_update_clip=10.0,
                     fault_plan=FaultPlan.parse(
                         "client_stale_poison@2:clients=0"))
    svc = AggregationService(
        s, ServeConfig(quorum=12, deadline_s=1e9, payload="sketch",
                       async_mode=True, buffer_size=10),
        traffic=TrafficGenerator(
            TraceConfig(population=12, seed=5))).start()
    try:
        src = svc.source()
        for _ in range(3):
            prep = src.next()
            s.commit_round(s.dispatch_round(prep, LR))
            src.on_dispatched(s.round - 1)
            src.on_committed(s.round)
        meta = s.serve_meta()
        assert meta["round"] == 3
        band = meta.get("band")
        assert band is not None
        # something is genuinely in flight mid-run: stragglers stashed
        # and/or a poison pending and/or parked arrivals
        depth = (len(band["stale"]) + len(band["stash"])
                 + len(band["poison"]))
        assert depth >= 1, band
        src.stop()
    finally:
        svc.close()
    # a fresh service on a "restored" session picks the band up
    s2 = make_session(stale_slots=12, client_update_clip=10.0)
    s2.restored_serve_meta = meta
    svc2 = AggregationService(
        s2, ServeConfig(quorum=12, deadline_s=1e9, payload="sketch",
                        async_mode=True, buffer_size=10),
        traffic=TrafficGenerator(
            TraceConfig(population=12, seed=5))).start()
    try:
        qb = svc2.queue.band_snapshot()
        assert len(qb["stale"]) == len(band["stale"])
        assert len(svc2._stale_stash) == len(band["stash"])
        assert len(svc2._stale_poison_pending) == len(band["poison"])
        # tables decoded base64-exact
        for enc, dec in zip(band["stash"], svc2._stale_stash):
            got = np.asarray(dec[3], np.float32)
            assert got.dtype == np.float32
            assert list(got.shape) == enc[3]["shape"]
    finally:
        svc2.close()


def test_rewind_restores_checkpointed_band_on_session_reuse():
    """Session + service reuse after an interrupted async loop: the band
    rewinds to the committed boundary SNAPSHOT (parked entries, retained
    screen state, recv counter, stash), so the continued run replays the
    stale folds bit-identically with an uninterrupted twin."""
    from commefficient_tpu.runner import RunnerConfig, run_loop
    from commefficient_tpu.federated.api import FedOptimizer

    def build():
        s = make_session(merge_policy="trimmed", merge_trim=3,
                         stale_slots=12)
        svc = AggregationService(
            s, ServeConfig(quorum=12, deadline_s=60.0, payload="sketch",
                           async_mode=True, buffer_size=6),
            traffic=TrafficGenerator(
                TraceConfig(population=12, seed=5))).start()
        return s, svc

    a, svc_a = build()
    try:
        opt = FedOptimizer(lambda e: LR, 3)
        run_loop(a, opt, RunnerConfig(total_rounds=2, eval_every=100),
                 source=svc_a.source())
        # the stop/rewind between loops restores the committed band
        run_loop(a, opt, RunnerConfig(total_rounds=5, eval_every=100),
                 source=svc_a.source())
    finally:
        svc_a.close()
    b, svc_b = build()
    try:
        run_loop(b, FedOptimizer(lambda e: LR, 3),
                 RunnerConfig(total_rounds=5, eval_every=100),
                 source=svc_b.source())
    finally:
        svc_b.close()
    assert a.round == b.round == 5
    _assert_params_equal(a, b)


# --------------------------------------------------------------- CLI chaos


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


@pytest.mark.chaos
def test_cli_async_preempt_resume_nonempty_stale_buffer(tiny_cv, tmp_path):
    """THE resume acceptance: an async CLI run whose stale buffer is
    NON-EMPTY mid-flight (a wire-delayed straggler crossing the round
    boundary), preempted and resumed, is bit-identical to the
    uninterrupted twin — params, every ledger row's fingerprints, and the
    requeue — because the band rode meta.json with the committed
    snapshot."""
    from commefficient_tpu.obs import ledger as obledger

    led = str(tmp_path / "run.jsonl")
    led2 = str(tmp_path / "twin.jsonl")
    base = [
        "--dataset", "cifar10", "--mode", "sketch",
        "--k", "64", "--num_rows", "3", "--num_cols", "256",
        "--num_clients", "8", "--num_workers", "4",
        "--local_batch_size", "4", "--lr_scale", "0.05",
        "--weight_decay", "0", "--data_root", "/nonexistent",
        "--num_rounds", "6", "--eval_every", "3",
        "--serve", "inproc", "--serve_payload", "sketch",
        "--serve_async", "--serve_buffer", "3",
        "--serve_deadline", "30.0", "--merge_policy", "trimmed",
        "--merge_trim", "1",
        # the delayed payloads miss round 2/3's trigger and land in the
        # stale band — the buffer is NON-EMPTY exactly when the preempt
        # hits round 3
        "--fault_plan", "wire_delay@2,3:clients=1,secs=5;preempt@3",
    ]
    before = {t.name for t in threading.enumerate()}
    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--checkpoint_every", "1",
             "--ledger", led]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(base + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    # the emergency checkpoint really carried a non-empty band
    import glob
    import os

    metas = sorted(glob.glob(os.path.join(ckdir, "round_*", "meta.json")))
    assert metas
    with open(metas[-1]) as f:
        meta = json.load(f)
    band = meta.get("serve", {}).get("band")
    assert band is not None
    assert (len(band.get("stale", [])) + len(band.get("stash", []))) >= 1, (
        "the preempted checkpoint's stale band is empty — the scenario "
        "did not exercise the non-empty-band resume")
    # same argv + --resume: the plan replays by GLOBAL round, and the
    # emergency checkpoint committed past round 3, so preempt@3 never
    # re-fires (the faults.py round-schedule contract)
    sc = cv_train.main(base + chaos + ["--resume"])
    assert sc.round == 6
    # the uninterrupted twin (same plan minus the preempt, its own ledger)
    sa = cv_train.main(
        [x.replace(";preempt@3", "") for x in base] + ["--ledger", led2])
    _assert_params_equal(sa, sc)
    assert list(sa._requeue) == list(sc._requeue)
    recs = obledger.round_records(led)
    twin = obledger.round_records(led2)
    assert [r["round"] for r in recs] == [r["round"] for r in twin] \
        == list(range(6))
    assert [r.get("fingerprint") for r in recs] \
        == [r.get("fingerprint") for r in twin]
    # stale-fold activity really appears in the committed record stream
    assert any(r.get("metrics", {}).get("stale_folded", 0) > 0
               for r in twin), "no stale fold committed — vacuous scenario"
    leaked = {t.name for t in threading.enumerate()} - before
    assert not {t for t in leaked if "serve" in t}, leaked


# ------------------------------------------------------------- validation


def test_engine_accepts_async_robust_composition():
    mc = ModeConfig(mode="sketch", d=16, k=4, num_rows=2, num_cols=8,
                    momentum_type="virtual", error_type="virtual")
    cfg = engine.EngineConfig(mode=mc, stale_slots=4, wire_payloads=True,
                              merge_policy="trimmed", merge_trim=1)
    assert engine.robust_policy(cfg) == "trimmed"
    # and the builder compiles the stale robust variant without complaint
    client_p, merge_p = engine.make_payload_round_steps(
        quad_loss, cfg, allow_batch_tables=True, stale_slots=4)
    assert callable(client_p) and callable(merge_p)


def test_engine_rejects_residual_without_robust_policy():
    """robust_residual through the LIBRARY API with no effective robust
    policy is a silent no-op waiting to be discovered at the postmortem —
    EngineConfig rejects it like the CLI does (sum AND trimmed@0)."""
    mc = ModeConfig(mode="sketch", d=16, k=4, num_rows=2, num_cols=8,
                    momentum_type="virtual", error_type="virtual")
    with pytest.raises(ValueError, match="robust_residual"):
        engine.EngineConfig(mode=mc, robust_residual=True)
    with pytest.raises(ValueError, match="robust_residual"):
        engine.EngineConfig(mode=mc, robust_residual=True,
                            merge_policy="trimmed", merge_trim=0)
    cfg = engine.EngineConfig(mode=mc, robust_residual=True,
                              merge_policy="median")
    assert cfg.robust_residual


def test_cli_robust_residual_validation():
    from commefficient_tpu.utils.config import make_parser, resolve_defaults

    base = ["--dataset", "cifar10", "--mode", "sketch", "--k", "4"]
    with pytest.raises(SystemExit, match="robust_residual|merge_policy"):
        resolve_defaults(make_parser("cv").parse_args(
            base + ["--robust_residual", "on"]))
    with pytest.raises(SystemExit, match="robust_residual|trimmed@0|sum"):
        resolve_defaults(make_parser("cv").parse_args(
            base + ["--robust_residual", "on", "--merge_policy", "trimmed"]))
    args = resolve_defaults(make_parser("cv").parse_args(
        base + ["--robust_residual", "on", "--merge_policy", "trimmed",
                "--merge_trim", "1"]))
    assert args.robust_residual == "on"


def test_slo_attack_spike_and_tuned_stale_runaway():
    """The new default rules: attack_spike fires on a sustained attack-
    counter delta; the tuned stale_runaway stays QUIET on a healthy
    small-buffer async profile (stale_fraction ~ 0.6) and fires on a
    sustained near-total stale takeover."""
    from commefficient_tpu.obs import slo as obslo

    reg = obreg.default()
    eng = obslo.SloEngine(obslo.parse_rules(""), mode="warn",
                          alert=lambda m: None)
    fired: list = []
    # healthy buffered profile: trigger 2-of-8, three stale folds per
    # round — the OLD 0.5@5 rule fired here; the tuned one must not
    healthy = {"participants": 2.0, "stale_folded": 3.0,
               "nonfinite_rounds": 0.0}
    for rnd in range(10):
        fired += eng.on_round(rnd, healthy)
    assert not [e for e in fired if e["rule"] == "stale_runaway"], fired
    # near-total takeover: 1 on-time vs 19 stale, sustained
    takeover = {"participants": 1.0, "stale_folded": 19.0,
                "nonfinite_rounds": 0.0}
    for rnd in range(10, 20):
        fired += eng.on_round(rnd, takeover)
    assert [e for e in fired if e["rule"] == "stale_runaway"], fired
    # attack_spike: a sustained per-round attack-counter delta
    eng2 = obslo.SloEngine(obslo.parse_rules(""), mode="warn",
                           alert=lambda m: None)
    fired2: list = []
    for rnd in range(5):
        reg.counter("resilience_attack_normride_total").inc(2)
        fired2 += eng2.on_round(rnd, {"participants": 8.0,
                                      "nonfinite_rounds": 0.0})
    assert [e for e in fired2 if e["rule"] == "attack_spike"], fired2
