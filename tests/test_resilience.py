"""Fault-injection + failure-recovery tests (resilience/ and the hardening it
proves out: atomic+checksummed checkpoints, the non-finite round guard, retry
wrappers, preemption handling).

The `chaos`-marked tests drive the REAL cv_train path (build/main) on a tiny
MLP (the checkpoint/recovery logic is model-agnostic; ResNet-9 compiles for
minutes on this 1-core box). Everything is seeded — FaultPlan, data, init —
so a failure here reproduces, it doesn't flake. scripts/chaos_smoke.sh runs
exactly this marker."""

import os
import signal
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp  # noqa: F401 — chaos fixtures build jax models

import cv_train
from commefficient_tpu.resilience import (
    EXIT_RESUMABLE, FaultPlan, InjectedTransientError, PreemptionHandler,
    RetryPolicy, with_retries,
)
from commefficient_tpu.utils import checkpoint as ckpt
from commefficient_tpu.utils.config import make_parser, resolve_defaults

LR = 0.05


def _argv(extra=()):
    return [
        "--dataset", "cifar10", "--mode", "uncompressed", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--lr_scale", "0.05",
        "--weight_decay", "0", "--data_root", "/nonexistent", *extra,
    ]


def _args(extra=()):
    return resolve_defaults(make_parser("cv").parse_args(_argv(extra)))


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    """cv_train with a synthetic 64-image CIFAR shard and a 2-layer MLP in
    place of ResNet-9 (same trick as test_checkpoint: recovery logic is
    model-agnostic; the real model's CLI path is covered by
    test_determinism/test_golden)."""
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


# ------------------------------------------------------------- faults.py unit


def test_fault_plan_parse():
    plan = FaultPlan.parse(
        "preempt@3;nonfinite@4:value=inf;data_fail@1,2:times=2;seed=9"
    )
    assert plan.seed == 9
    assert plan.spec("preempt", 3).rounds == (3,)
    assert plan.spec("preempt", 4) is None
    assert plan.spec("nonfinite", 4).params == {"value": "inf"}
    assert plan.spec("data_fail", 2).params["times"] == 2  # coerced at parse
    # round-less spec matches any round (e.g. dist_init has no round)
    assert FaultPlan.parse("dist_init:times=2").spec("dist_init") is not None
    # off-by-default contract
    assert FaultPlan.parse("") is None and FaultPlan.parse(None) is None
    with pytest.raises(ValueError):
        FaultPlan.parse("explode@1")
    with pytest.raises(ValueError):
        FaultPlan.parse("stall@1:secs")
    # a typo'd param key must fail parse, not silently under-inject
    with pytest.raises(ValueError, match="unknown param"):
        FaultPlan.parse("data_fail@1:time=5")
    # a bad param VALUE must reject the plan at launch, not crash at the
    # scheduled round hours into the run
    with pytest.raises(ValueError, match="bad value"):
        FaultPlan.parse("data_fail@1:times=two")
    with pytest.raises(ValueError, match="bad value"):
        FaultPlan.parse("nonfinite@1:value=infinity")
    # dist_init fires at bootstrap (rnd=None): a round schedule would
    # silently never inject, so it must not parse
    with pytest.raises(ValueError, match="bootstrap"):
        FaultPlan.parse("dist_init@0:times=2")


def test_fire_transient_budget_is_per_round_site():
    plan = FaultPlan.parse("data_fail@1:times=2")
    plan.fire_transient("data_fail", 0)  # not scheduled for round 0
    for _ in range(2):
        with pytest.raises(InjectedTransientError):
            plan.fire_transient("data_fail", 1)
    plan.fire_transient("data_fail", 1)  # budget spent -> succeeds


def test_stall_site_sleeps_once():
    plan = FaultPlan.parse("stall@0:secs=0.05")
    t0 = time.monotonic()
    plan.data_load(0)
    first = time.monotonic() - t0
    t0 = time.monotonic()
    plan.data_load(0)  # one-shot: a retried/repeated hit must not re-stall
    again = time.monotonic() - t0
    assert first >= 0.05 and again < 0.05


def test_eval_stall_site_sleeps_once_on_scheduled_round():
    plan = FaultPlan.parse("eval_stall@2:secs=0.05")
    t0 = time.monotonic()
    plan.eval_load(0)  # not scheduled for round 0
    assert time.monotonic() - t0 < 0.05
    t0 = time.monotonic()
    plan.eval_load(2)
    assert time.monotonic() - t0 >= 0.05
    t0 = time.monotonic()
    plan.eval_load(2)  # one-shot per round
    assert time.monotonic() - t0 < 0.05
    # the training-loader `stall` spec must NOT leak into the eval site
    assert FaultPlan.parse("stall@2:secs=9").spec("eval_stall", 2) is None


def test_retry_counts_surface_failed_attempts():
    """Chaos runs are benchmarkable: every failed attempt bumps the per-site
    process counter bench.py publishes in its JSON."""
    from commefficient_tpu.resilience import reset_retry_counts, retry_counts

    reset_retry_counts()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("flake")
        return "ok"

    with_retries(flaky, site="countme",
                 policy=RetryPolicy(max_retries=3, base_delay_s=0.0),
                 sleep=lambda d: None, log=lambda m: None)
    assert retry_counts()["countme"] == 2
    assert "neverfailed" not in retry_counts()
    reset_retry_counts()
    assert retry_counts() == {}


# -------------------------------------------------------------- retry.py unit


def test_with_retries_recovers_then_exhausts():
    calls, logs = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient flake")
        return "ok"

    out = with_retries(
        flaky, site="t", policy=RetryPolicy(max_retries=3, base_delay_s=0.0),
        sleep=lambda d: None, log=logs.append,
    )
    assert out == "ok" and len(calls) == 3
    assert len(logs) == 2 and all("retry[t]" in line for line in logs)

    attempts = []

    def always_fails():
        attempts.append(1)
        raise OSError("permanent")

    with pytest.raises(OSError, match="permanent"):
        with_retries(
            always_fails, site="t",
            policy=RetryPolicy(max_retries=2, base_delay_s=0.0),
            sleep=lambda d: None, log=logs.append,
        )
    assert len(attempts) == 3  # 1 try + 2 retries, last error re-raised


def test_dist_init_retry_tears_down_half_initialized_client(monkeypatch):
    """Regression: jax assigns its global distributed client BEFORE
    connect(), so a failed first attempt used to make every retry raise
    'initialize should only be called once' — masking the real connectivity
    error and guaranteeing exhaustion. The join must shutdown() between
    attempts so each retry is genuine."""
    import jax

    from commefficient_tpu.parallel import distributed

    calls = {"init": 0, "shutdown": 0}
    client_assigned = {"v": False}

    def fake_initialize(**kw):
        if client_assigned["v"]:
            raise RuntimeError("initialize should only be called once")
        client_assigned["v"] = True  # assigned before connect, like real jax
        calls["init"] += 1
        if calls["init"] < 3:
            raise OSError("coordinator not listening yet")

    def fake_shutdown():
        calls["shutdown"] += 1
        client_assigned["v"] = False

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(jax.distributed, "shutdown", fake_shutdown)
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    monkeypatch.setattr(
        "commefficient_tpu.utils.hermetic.backends_initialized", lambda: False
    )
    assert distributed.initialize(
        force=True, retry_policy=RetryPolicy(max_retries=3, base_delay_s=0.0)
    )
    assert calls["init"] == 3  # two real failures, then a genuine success
    assert calls["shutdown"] == 2  # teardown between every failed attempt


def test_retry_jitter_is_seeded():
    pol = RetryPolicy(max_retries=3, base_delay_s=0.1)
    a = [pol.delay_s(i, np.random.RandomState(5)) for i in range(3)]
    b = [pol.delay_s(i, np.random.RandomState(5)) for i in range(3)]
    assert a == b
    assert a[1] > a[0]  # exponential backoff grows


# -------------------------------------------------------- preemption.py unit


def test_preemption_handler_sets_flag_and_restores_previous():
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        with PreemptionHandler() as pre:
            assert not pre.triggered
            os.kill(os.getpid(), signal.SIGTERM)
            assert pre.triggered  # flag only — no exit, no exception
        # the previous handler is back in place after exit
        os.kill(os.getpid(), signal.SIGTERM)
        assert seen == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)
    assert EXIT_RESUMABLE == 75  # EX_TEMPFAIL: the supervisor contract


# ----------------------------------------------------- chaos: engine recovery


@pytest.mark.chaos
def test_data_load_retry_replays_identical_round(tiny_cv):
    """A transiently-failing data load must recover AND yield the exact batch
    the clean run sees: the injection site fires before any host RNG is
    consumed and a failed attempt restores the RNG snapshot."""
    a, _ = cv_train.build(_args())
    ma = a.run_round(LR)
    b, _ = cv_train.build(_args(("--fault_plan", "data_fail@0:times=2")))
    mb = b.run_round(LR)
    assert ma["loss_sum"] == mb["loss_sum"]
    for x, y in zip(
        jax.tree.leaves(jax.device_get(a.state["params"])),
        jax.tree.leaves(jax.device_get(b.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.chaos
def test_eval_stall_fires_in_real_eval_path(tiny_cv):
    """The eval_stall site is wired into FederatedSession.evaluate (the eval
    loader the round-5 FEMNIST stall actually lived in): scheduled round
    stalls once, and eval results are unaffected."""
    s, test_set = cv_train.build(
        _args(("--fault_plan", "eval_stall@1:secs=0.3"))
    )
    ev0 = s.evaluate(test_set, 32)  # round 0: no stall; compiles eval
    s.run_round(LR)  # -> round 1
    t0 = time.monotonic()
    ev1 = s.evaluate(test_set, 32)
    stalled = time.monotonic() - t0
    t0 = time.monotonic()
    ev2 = s.evaluate(test_set, 32)  # one-shot: same round, no re-stall
    clean = time.monotonic() - t0
    assert stalled >= 0.3 and stalled - clean >= 0.25
    assert ev1 == ev2 and ev0.keys() == ev1.keys()


def _snap(session):
    st = jax.device_get(session.state)
    from jax.flatten_util import ravel_pytree

    return (
        np.asarray(ravel_pytree(st["params"])[0]),
        np.asarray(st["mode_state"]["Vvelocity"]),
        np.asarray(st["mode_state"]["Verror"]),
    )


@pytest.mark.chaos
def test_nonfinite_round_skipped_keeps_state_clean(tiny_cv):
    """An injected NaN burst through the real gradient path is skipped like a
    fully-dropped cohort: momentum decays (V2 = rho*V1), error feedback and
    params never absorb the poison — pinned against the clean run's state —
    and the skip is visible in metrics."""
    a, _ = cv_train.build(_args())
    for _ in range(2):
        a.run_round(LR)
    p1, v1, e1 = _snap(a)

    b, _ = cv_train.build(_args(("--fault_plan", "nonfinite@2")))
    ms = [b.run_round(LR) for _ in range(3)]
    assert [m["nonfinite_rounds"] for m in ms] == [0.0, 0.0, 1.0]
    p2, v2, e2 = _snap(b)
    # clean prefix: rounds 0-1 bit-identical to the un-faulted run
    rho = np.float32(0.9)
    np.testing.assert_allclose(v2, rho * v1, rtol=1e-6)
    np.testing.assert_array_equal(e2, e1)
    np.testing.assert_allclose(p2, p1 - np.float32(LR) * v2, rtol=1e-6, atol=1e-7)
    assert np.isfinite(p2).all() and np.isfinite(v2).all()
    # the session keeps training normally after the skipped round
    m = b.run_round(LR)
    assert m["nonfinite_rounds"] == 0.0
    assert np.isfinite(_snap(b)[0]).all()

    # and the guard is load-bearing: --on_nonfinite off lets the poison in
    c, _ = cv_train.build(
        _args(("--fault_plan", "nonfinite@2", "--on_nonfinite", "off"))
    )
    for _ in range(3):
        c.run_round(LR)
    assert not np.isfinite(_snap(c)[0]).all()


@pytest.mark.chaos
def test_donate_state_off_is_bit_transparent(tiny_cv, tmp_path):
    """--checkpoint_dir disables state-buffer donation (so the watchdog's
    mid-round emergency save can read the live state on real accelerators);
    donation only changes buffer reuse, never numerics — pin that."""
    a, _ = cv_train.build(_args())
    assert a._donate_state
    b, _ = cv_train.build(_args(("--checkpoint_dir", str(tmp_path / "ck"))))
    assert not b._donate_state
    # the HBM opt-out keeps donation (and gives up the mid-round save)
    opt, _ = cv_train.build(_args(("--checkpoint_dir", str(tmp_path / "ck"),
                                   "--no_emergency_checkpoint")))
    assert opt._donate_state
    for _ in range(2):
        a.run_round(LR)
        b.run_round(LR)
    for x, y in zip(
        jax.tree.leaves(jax.device_get(a.state["params"])),
        jax.tree.leaves(jax.device_get(b.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.chaos
def test_nonfinite_round_with_dp_releases_no_noise(tiny_cv):
    """A skipped round transmits nothing, so it must release nothing: with
    central DP on, the poisoned round's update must be EXACTLY the momentum
    decay (V2 = rho*V1, p2 = p1 - lr*V2) — any leaked DP noise on the zeroed
    aggregate would shift both and feed pure noise into the params."""
    ex = ("--dp_clip", "1.0", "--dp_noise", "0.5",
          "--fault_plan", "nonfinite@2")
    b, _ = cv_train.build(_args(ex))
    for _ in range(2):
        b.run_round(LR)
    p1, v1, _ = _snap(b)
    m = b.run_round(LR)
    assert m["nonfinite_rounds"] == 1.0
    p2, v2, _ = _snap(b)
    rho = np.float32(0.9)
    np.testing.assert_allclose(v2, rho * v1, rtol=1e-6)
    np.testing.assert_allclose(p2, p1 - np.float32(LR) * v2, rtol=1e-6,
                               atol=1e-7)


# --------------------------------------------- chaos: checkpoint IO recovery


@pytest.mark.chaos
def test_checkpoint_write_retries_recover(tiny_cv, tmp_path):
    s, _ = cv_train.build(_args())
    s.run_round(LR)  # session.round -> 1
    path = ckpt.save(
        str(tmp_path / "ck"), s, fault_plan=FaultPlan.parse("ckpt_fail@1:times=2"),
        retry_policy=RetryPolicy(max_retries=3, base_delay_s=0.001),
    )
    assert ckpt.verify(path) is True  # recovered write is complete + clean
    with pytest.raises(InjectedTransientError):
        ckpt.save(
            str(tmp_path / "ck2"), s,
            fault_plan=FaultPlan.parse("ckpt_fail@1:times=5"),
            retry_policy=RetryPolicy(max_retries=1, base_delay_s=0.001),
        )
    # the failed save left no committed round_* dir behind
    ck2 = tmp_path / "ck2"
    assert not ck2.is_dir() or not any(
        d.startswith("round_") for d in os.listdir(ck2)
    )


@pytest.mark.chaos
def test_same_round_resave_overwrites_cleanly(tiny_cv, tmp_path):
    """An emergency save of a round that already has a committed checkpoint
    (watchdog stage 3 after a scheduled save) replaces it via rename-aside —
    the result verifies and no displaced .old copy lingers."""
    s, _ = cv_train.build(_args())
    s.run_round(LR)
    ckdir = str(tmp_path / "ck")
    p1 = ckpt.save(ckdir, s)
    p2 = ckpt.save(ckdir, s)
    assert p1 == p2 and ckpt.verify(p2) is True
    assert not [d for d in os.listdir(ckdir) if d.endswith(".displaced")]
    # crash window between the two renames: only the displaced copy exists,
    # and restore_latest must recover the round from it
    os.rename(p2, p2 + ".displaced")
    s2, _ = cv_train.build(_args())
    restored = ckpt.restore_latest(ckdir, s2)
    assert restored.endswith(".displaced") and s2.round == 1


@pytest.mark.chaos
def test_corrupt_and_truncated_checkpoints_fall_back(tiny_cv, tmp_path, capsys):
    """The headline recovery guarantee of the manifest: a damaged latest
    checkpoint costs one checkpoint interval, not the run."""
    ckdir = str(tmp_path / "ck")
    s, _ = cv_train.build(_args())
    for _ in range(3):
        s.run_round(LR)
        ckpt.save(ckdir, s)
    names = sorted(d for d in os.listdir(ckdir) if d.startswith("round_"))
    assert len(names) == 3
    # newest: simulated partial write (truncation); middle: bit-flip
    t = FaultPlan._largest_data_file(os.path.join(ckdir, names[-1]))
    with open(t, "r+b") as f:
        f.truncate(os.path.getsize(t) // 2)
    c = FaultPlan._largest_data_file(os.path.join(ckdir, names[-2]))
    with open(c, "r+b") as f:
        f.seek(os.path.getsize(c) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))

    s2, _ = cv_train.build(_args())
    restored = ckpt.restore_latest(ckdir, s2)
    err = capsys.readouterr().err
    assert restored.endswith(names[0]) and s2.round == 1
    assert err.count("FAILED integrity") == 2
    assert "recovered" in err and "skipping 2 damaged" in err


@pytest.mark.chaos
def test_fault_plan_corrupts_committed_checkpoint(tiny_cv, tmp_path):
    """ckpt_corrupt lands AFTER the atomic commit + manifest, so verification
    (not luck) catches it; with every candidate damaged, restore_latest
    refuses to silently restart from round 0."""
    s, _ = cv_train.build(_args(("--fault_plan", "ckpt_corrupt@1")))
    s.run_round(LR)
    path = ckpt.save(str(tmp_path / "ck"), s, fault_plan=s.fault_plan)
    assert ckpt.verify(path) is False
    s2, _ = cv_train.build(_args())
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        ckpt.restore_latest(str(tmp_path / "ck"), s2)
    # an empty/missing dir is a fresh run, not an error
    assert ckpt.restore_latest(str(tmp_path / "fresh"), s2) is None


@pytest.mark.chaos
def test_resume_replays_dropout_masks(tiny_cv, tmp_path):
    """The device-side PRNG stream (participation masks) is checkpointed, so
    a resumed run under client dropout replays the uninterrupted run's
    cohorts bit-for-bit — not just the host-side client sampling."""
    ex = ("--client_dropout", "0.5")
    a, _ = cv_train.build(_args(ex))
    parts_a = [a.run_round(LR)["participants"] for _ in range(6)]
    # the seed produces at least one non-full cohort (note: the 8-way CPU
    # mesh rounds num_workers up to 8, so "full" is a.num_workers, not 2)
    assert min(parts_a) < a.num_workers

    b, _ = cv_train.build(_args(ex))
    for _ in range(3):
        b.run_round(LR)
    path = ckpt.save(str(tmp_path / "ckd"), b)
    c, _ = cv_train.build(_args(ex))
    ckpt.restore(path, c)
    parts_c = [c.run_round(LR)["participants"] for _ in range(3)]
    assert parts_c == parts_a[3:]
    for x, y in zip(
        jax.tree.leaves(jax.device_get(a.state["params"])),
        jax.tree.leaves(jax.device_get(c.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.chaos
def test_emergency_save_mid_round_keeps_rng_consistent(tiny_cv, tmp_path):
    """A watchdog emergency checkpoint fires from the timer thread while the
    in-flight round has already advanced the host sampling RNG. save() must
    write the round-boundary snapshot, not the live stream — otherwise the
    resumed run re-samples that round from a stream advanced past its draws
    and trains a cohort no deterministic run of this seed produces."""
    a, _ = cv_train.build(_args())
    for _ in range(2):
        a.run_round(LR)
    # the stuck round 2 has already consumed the host RNG for its sampling
    a.train_set.sample_clients(a.rng, a.num_workers)
    path = ckpt.save(str(tmp_path / "ck"), a)

    b, _ = cv_train.build(_args())
    ckpt.restore(path, b)
    c, _ = cv_train.build(_args())  # clean reference: RNG never torn
    for _ in range(2):
        c.run_round(LR)
    mb, mc = b.run_round(LR), c.run_round(LR)
    assert mb["loss_sum"] == mc["loss_sum"]
    for x, y in zip(
        jax.tree.leaves(jax.device_get(b.state["params"])),
        jax.tree.leaves(jax.device_get(c.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------- chaos: cohort-level fault tolerance


@pytest.mark.chaos
def test_client_drop_degrades_round_and_requeues(tiny_cv):
    """An injected client_drop degrades ONE round (participants down by the
    dropped count, clients_dropped counted, requeue depth visible) and the
    dropped client is served back into the next cohort instead of losing its
    data; training continues normally."""
    s, _ = cv_train.build(
        _args(("--fault_plan", "client_drop@1:clients=0")))
    W = s.num_workers  # the 8-way CPU mesh rounds the cohort up to 8
    m0 = s.run_round(LR)
    assert m0["participants"] == W and m0["clients_dropped"] == 0.0
    m1 = s.run_round(LR)
    assert m1["clients_dropped"] == 1.0
    assert m1["participants"] == W - 1
    assert m1["requeue_depth"] == 1.0
    assert len(s._requeue) == 1
    m2 = s.run_round(LR)  # the queued client is substituted into round 2
    assert m2["requeue_depth"] == 0.0 and len(s._requeue) == 0
    assert m2["participants"] == W
    assert np.isfinite(_snap(s)[0]).all()


@pytest.mark.chaos
def test_overlapping_drop_specs_requeue_each_client_once(tiny_cv):
    """Two client_drop specs naming the same position in the same round must
    queue that client ONCE — a double-queued id would displace two sampled
    clients in later rounds and train the same shard twice."""
    s, _ = cv_train.build(_args((
        "--fault_plan", "client_drop@1:clients=0;client_drop@1:clients=0+2")))
    s.run_round(LR)
    m = s.run_round(LR)
    assert m["clients_dropped"] == 2.0
    assert len(s._requeue) == len(set(s._requeue)) == 2


@pytest.mark.chaos
def test_requeue_policy_fifo_is_bit_unchanged(tiny_cv):
    """The --requeue_policy knob's compatibility pin: the default (fifo)
    serves the queue in exactly the pre-knob order and the whole run —
    params, metrics, queue state — is bit-identical to a session built
    without the kwarg at all. Drops in two consecutive rounds build a
    2-deep queue so the ORDER of substitution is actually exercised."""
    plan = ("client_drop@1:clients=0;client_drop@2:clients=1",)

    def run(extra=()):
        s, _ = cv_train.build(_args(("--fault_plan",) + plan + extra))
        rows = [s.run_round(LR) for _ in range(5)]
        return s, rows

    s_default, rows_default = run()
    s_fifo, rows_fifo = run(("--requeue_policy", "fifo"))
    assert s_default._requeue_policy == "fifo"  # the default IS fifo
    for a, b in zip(rows_default, rows_fifo):
        assert a == b
    np.testing.assert_array_equal(*map(lambda s: _snap(s)[0],
                                       (s_default, s_fifo)))
    assert list(s_default._requeue) == list(s_fifo._requeue)


@pytest.mark.chaos
def test_requeue_policy_aged_is_deterministic_and_serves_all(tiny_cv):
    """The aged stub: weighted-by-rounds-waiting serving order from a
    pinned dedicated seed — two identical sessions agree bit-for-bit
    (deterministic), every dropped client is eventually served (no
    starvation in the drained case), and the SAMPLED cohort stream is
    policy-invariant (the dedicated RandomState consumes no host-sampling
    RNG: a later clean round samples the same cohort under both policies)."""
    plan = ("--fault_plan", "client_drop@1:clients=0+1", "--num_workers", "2")

    def run(policy):
        s, _ = cv_train.build(_args(plan + ("--requeue_policy", policy)))
        rows = [s.run_round(LR) for _ in range(6)]
        return s, rows

    s_a, rows_a = run("aged")
    s_b, rows_b = run("aged")
    for a, b in zip(rows_a, rows_b):
        assert a == b  # pinned seed: deterministic replay
    np.testing.assert_array_equal(_snap(s_a)[0], _snap(s_b)[0])
    assert not s_a._requeue  # both dropped clients were served back
    # policy-invariant sampling: the host RNG state after the run is the
    # same under fifo — the aged draw came from the dedicated stream
    s_f, _ = run("fifo")[0], None
    assert s_f.rng.get_state()[1].tolist() == s_a.rng.get_state()[1].tolist()

    # the weighted order itself: with strongly unequal ages the older
    # client wins the front slot for this pinned seed deterministically
    s_a._requeue.extend([3, 4])
    s_a._requeue_enqueued.update({3: 0, 4: s_a.round - 1})
    order1 = s_a._aged_order(list(s_a._requeue), s_a.round)
    order2 = s_a._aged_order(list(s_a._requeue), s_a.round)
    assert order1 == order2 and set(order1) == {3, 4}


@pytest.mark.chaos
def test_periodic_saves_gated_to_process_zero(tiny_cv, tmp_path, monkeypatch):
    """make_save_ckpt is the one-writer-per-job gate for EVERY save the
    runner schedules (periodic, halt, final, emergency — not just the
    preemption path): a non-zero process writes nothing and returns None."""
    from commefficient_tpu.runner.loop import make_save_ckpt

    s, _ = cv_train.build(_args())
    s.run_round(LR)
    ckdir = str(tmp_path / "ck")
    save = make_save_ckpt(s, ckdir)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert save() is None and not os.path.isdir(ckdir)
    monkeypatch.undo()
    path = save()  # process 0: the real write
    assert path and ckpt.verify(path) is True


@pytest.mark.chaos
def test_cli_rejects_unreachable_client_fault_schedule(tiny_cv):
    """A client_* site scheduled past the run's end fails at LAUNCH (the CLI
    validates against the full run length), not silently never-fires."""
    with pytest.raises(ValueError, match="can never fire"):
        cv_train.main(_argv(
            ("--num_rounds", "3", "--fault_plan", "client_drop@5:clients=0")))


@pytest.mark.chaos
def test_client_straggle_is_slow_but_bit_transparent(tiny_cv):
    """A straggling client stalls its round's preparation (watchdog/overlap
    fodder) but changes no bits: the run equals the un-faulted run exactly."""
    a, _ = cv_train.build(_args())
    b, _ = cv_train.build(
        _args(("--fault_plan", "client_straggle@1:clients=0,secs=0.3")))
    for _ in range(2):
        a.run_round(LR)
    t0 = time.monotonic()
    for _ in range(2):
        b.run_round(LR)
    assert time.monotonic() - t0 >= 0.3
    for x, y in zip(
        jax.tree.leaves(jax.device_get(a.state["params"])),
        jax.tree.leaves(jax.device_get(b.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.chaos
def test_client_poison_quarantined_like_a_drop(tiny_cv):
    """The quarantine acceptance pin through the real CLI path: a
    client_poison update (adversarially large, through the real gradients)
    is rejected with params bit-equal to the run where that client is
    DROPPED instead — and the identical clean run quarantines nothing."""
    clip = ("--client_update_clip", "10")
    a, _ = cv_train.build(_args((
        *clip, "--fault_plan", "client_poison@1:clients=1,value=big")))
    ma = [a.run_round(LR) for _ in range(2)]
    assert [m["clients_quarantined"] for m in ma] == [0.0, 1.0]
    assert ma[1]["participants"] == a.num_workers - 1
    assert np.isfinite(_snap(a)[0]).all()

    b, _ = cv_train.build(_args((
        *clip, "--fault_plan", "client_drop@1:clients=1")))
    for _ in range(2):
        b.run_round(LR)
    for x, y in zip(
        jax.tree.leaves(jax.device_get(a.state["params"])),
        jax.tree.leaves(jax.device_get(b.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    c, _ = cv_train.build(_args(clip))  # clean run, quarantine armed
    mc = [c.run_round(LR) for _ in range(2)]
    assert all(m["clients_quarantined"] == 0.0 for m in mc)
    assert all(m["participants"] == c.num_workers for m in mc)


@pytest.mark.chaos
def test_client_drop_resume_mid_degraded_run_bit_identical(tiny_cv, tmp_path):
    """Checkpoint + resume MID-degraded-run: preempted in the same round the
    drop fired, the re-queue state rides the checkpoint (meta.json), so the
    resumed run serves the dropped client at the same later round the
    uninterrupted run does — final params bit-identical."""
    base = _argv(("--num_rounds", "6"))
    fault = "client_drop@2:clients=0"
    sa = cv_train.main(base + ["--fault_plan", fault])
    assert sa.round == 6
    params_a = jax.device_get(sa.state["params"])

    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir,
             "--fault_plan", f"{fault};preempt@2"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(base + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    # the emergency checkpoint carries the un-served re-queue
    import json

    latest = sorted(d for d in os.listdir(ckdir)
                    if d.startswith("round_") and "." not in d)[-1]
    with open(os.path.join(ckdir, latest, "meta.json")) as f:
        assert len(json.load(f)["requeued"]) == 1

    sc = cv_train.main(base + chaos + ["--resume"])
    assert sc.round == 6
    for x, y in zip(
        jax.tree.leaves(params_a),
        jax.tree.leaves(jax.device_get(sc.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.chaos
def test_host_preempt_fires_only_on_matching_host(tiny_cv, tmp_path):
    """host_preempt targets ONE simulated host by jax.process_index(): host=0
    preempts this (single-process, index 0) run through the coordinated
    path; host=1 does not exist in a single-process job and is rejected at
    LAUNCH (an unfireable site = a vacuous chaos run), as is a round past
    the run's end."""
    base = _argv(("--num_rounds", "4"))
    ck = ["--checkpoint_dir", str(tmp_path / "ck")]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(base + ck + ["--fault_plan", "host_preempt@1:host=0"])
    assert ei.value.code == EXIT_RESUMABLE
    with pytest.raises(ValueError, match="can never fire"):
        cv_train.main(base + ["--fault_plan", "host_preempt@1:host=1"])
    with pytest.raises(ValueError, match="can never fire"):
        cv_train.main(base + ["--fault_plan", "host_preempt@9:host=0"])


@pytest.mark.chaos
def test_coordinated_preemption_stops_unsignalled_host(tiny_cv, tmp_path,
                                                       monkeypatch):
    """The multi-host acceptance pin, simulated: this 'host' receives NO
    SIGTERM, but the cross-host max-reduce reports a peer was signalled —
    the loop must still drain, checkpoint the agreed round, and exit 75
    (without agreement this host would run to completion while the
    signalled peer exited, desyncing the job)."""
    from commefficient_tpu.parallel import distributed
    from commefficient_tpu.runner import loop as rloop

    calls = {"n": 0}

    def fake_all_hosts_max(v):
        calls["n"] += 1
        return 1 if calls["n"] >= 3 else int(v)

    monkeypatch.setattr(rloop, "_process_count", lambda: 2)
    monkeypatch.setattr(distributed, "all_hosts_max", fake_all_hosts_max)
    ckdir = str(tmp_path / "ck")
    with pytest.raises(SystemExit) as ei:
        cv_train.main(_argv(("--num_rounds", "8", "--checkpoint_dir", ckdir)))
    assert ei.value.code == EXIT_RESUMABLE
    assert calls["n"] >= 3  # the agreement ran at round boundaries
    names = sorted(d for d in os.listdir(ckdir) if d.startswith("round_"))
    assert names and names[-1] == "round_00000003"  # the agreed round
    assert ckpt.verify(os.path.join(ckdir, names[-1])) is True


# ------------------------------------------- chaos: damaged-checkpoint GC


@pytest.mark.chaos
def test_damaged_checkpoints_set_aside_and_garbage_collected(
        tiny_cv, tmp_path, capsys):
    """restore_latest renames failed candidates to *.damaged (they stop
    being restore/prune candidates) and bounds the graveyard to the newest
    KEEP_DAMAGED, counting deletions — chaos ckpt_corrupt runs no longer
    accumulate damaged trees unboundedly."""
    ckdir = str(tmp_path / "ck")
    s, _ = cv_train.build(_args())
    for _ in range(3):
        s.run_round(LR)
        ckpt.save(ckdir, s)
    names = sorted(d for d in os.listdir(ckdir) if d.startswith("round_"))
    for name in names[-2:]:  # damage the newest two
        t = FaultPlan._largest_data_file(os.path.join(ckdir, name))
        with open(t, "r+b") as f:
            f.truncate(os.path.getsize(t) // 2)

    s2, _ = cv_train.build(_args())
    restored = ckpt.restore_latest(ckdir, s2)
    assert restored.endswith(names[0]) and s2.round == 1
    damaged = sorted(d for d in os.listdir(ckdir) if d.endswith(".damaged"))
    assert damaged == [f"{names[-2]}.damaged", f"{names[-1]}.damaged"]
    # damaged trees are no longer candidates: latest() sees only the good one
    assert ckpt.latest(ckdir) == os.path.abspath(os.path.join(ckdir, names[0]))

    # a third damaged checkpoint pushes past KEEP_DAMAGED=2: GC deletes the
    # oldest, loudly
    for _ in range(3):
        s2.run_round(LR)
    p4 = ckpt.save(ckdir, s2)  # round_00000004
    t = FaultPlan._largest_data_file(p4)
    with open(t, "r+b") as f:
        f.truncate(os.path.getsize(t) // 2)
    s3, _ = cv_train.build(_args())
    ckpt.restore_latest(ckdir, s3)
    err = capsys.readouterr().err
    assert "checkpoint GC: deleted 1 damaged" in err
    damaged = sorted(d for d in os.listdir(ckdir) if d.endswith(".damaged"))
    assert len(damaged) == 2 and f"{names[-2]}.damaged" not in damaged


@pytest.mark.chaos
def test_all_damaged_dir_refuses_fresh_restart(tiny_cv, tmp_path):
    """A directory whose every checkpoint was set aside as damaged is NOT a
    fresh run: a later resume must refuse to silently restart from round 0."""
    ckdir = str(tmp_path / "ck")
    s, _ = cv_train.build(_args(("--fault_plan", "ckpt_corrupt@1")))
    s.run_round(LR)
    ckpt.save(ckdir, s, fault_plan=s.fault_plan)
    s2, _ = cv_train.build(_args())
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        ckpt.restore_latest(ckdir, s2)  # renames the only candidate aside
    with pytest.raises(RuntimeError, match="only damaged"):
        ckpt.restore_latest(ckdir, s2)  # second resume: still not "fresh"


# ------------------------------------- chaos: the headline preempt -> resume


@pytest.mark.chaos
def test_preempt_resume_bit_identical(tiny_cv, tmp_path):
    """The acceptance headline: a run SIGTERM'd mid-round by the fault plan
    takes an emergency checkpoint, exits EXIT_RESUMABLE, and the relaunched
    --resume run (same argv, as a supervisor would issue) finishes with
    params bit-identical to the uninterrupted run."""
    base = _argv(("--num_rounds", "6"))
    sa = cv_train.main(base)
    assert sa.round == 6
    params_a = jax.device_get(sa.state["params"])

    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--fault_plan", "preempt@3"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(base + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    # SIGTERM fired as round 3 ran; the handler let it finish, then saved
    names = sorted(d for d in os.listdir(ckdir) if d.startswith("round_"))
    assert names[-1] == "round_00000004"
    assert ckpt.verify(os.path.join(ckdir, names[-1])) is True

    # relaunch with identical argv + --resume: preempt@3 must NOT re-fire
    # (round-indexed schedule; the resumed run starts at round 4)
    sc = cv_train.main(base + chaos + ["--resume"])
    assert sc.round == 6
    for x, y in zip(
        jax.tree.leaves(params_a),
        jax.tree.leaves(jax.device_get(sc.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
