"""Byzantine-robust sketch aggregation (PR 10).

Pins, per the acceptance bar:

- `--merge_policy trimmed` with trim=0 BIT-identical to `sum` (params +
  every logged row) on the fused announce path AND the payload round —
  and it must not silently reroute the session through the table round.
- the robust table merge against a numpy reference (live-mask exclusion,
  client-index tie-breaks), and its mesh-/shard-shape invariance.
- the adversarial suite: each new attack kind degrades the linear sum
  measurably while trimmed/median recover final loss to within a stated
  eps of the clean run, same seed, same (table) round shape.
- per-layer quarantine: single-leaf window=1 bitwise equal to the scalar
  screen; the per-leaf screen catches a one-layer attack the diluted flat
  norm misses; per-leaf rings advance exactly like L scalar rings.
- `--quarantine_window` on the sharded and payload paths: the windowed
  threshold equals the rolling median of the per-round medians.
- the satellite fix: a wire (gauntlet) rejection and an in-round merge
  quarantine of the same client are bitwise-equivalent rounds.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated import engine
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.resilience import FaultPlan
from commefficient_tpu.serve.ingest import (
    ACCEPTED,
    QUARANTINED,
    PayloadPolicy,
    validate_payload,
)


def quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0), {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def single_leaf_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0), {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def make_session(loss_fn=quad_loss, single_leaf=False, num_workers=4,
                 seed=0, **kw):
    rs = np.random.RandomState(0)
    x = rs.randn(96, 6).astype(np.float32)
    w_true = rs.randn(6, 3).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), 12, np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(6, 3).astype(np.float32) * 0.1)}
    if not single_leaf:
        params["b"] = jnp.zeros(3)
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=loss_fn, eval_loss_fn=loss_fn,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="sketch", d=d, k=4, num_rows=3, num_cols=8,
                            momentum=0.9, momentum_type="virtual",
                            error_type="virtual"),
        train_set=train, num_workers=num_workers, local_batch_size=4,
        seed=seed, **kw)


def flat_params(session) -> np.ndarray:
    return np.asarray(
        ravel_pytree(jax.device_get(session.state["params"]))[0])


def run(session, n=4, lr=0.05):
    return [session.run_round(lr) for _ in range(n)]


# ------------------------------------------------- robust merge, unit level


def _np_trimmed_mean(tables, live, trim):
    """Per-coordinate numpy reference: drop the trim lowest/highest LIVE
    values (ties by client index) and average the survivors."""
    W = tables.shape[0]
    out = np.zeros(tables.shape[1:], np.float32)
    flat = tables.reshape(W, -1)
    n = int(live.sum())
    res = np.zeros(flat.shape[1], np.float32)
    for c in range(flat.shape[1]):
        rows = [(flat[i, c], i) for i in range(W) if live[i] > 0]
        rows.sort()  # value, then client index — the stable tie-break
        kept = rows[trim:n - trim]
        res[c] = (sum(v for v, _ in kept) / max(n - 2 * trim, 1)
                  if kept else 0.0)
    return res.reshape(out.shape)


def _np_median(tables, live):
    W = tables.shape[0]
    flat = tables.reshape(W, -1)
    n = int(live.sum())
    res = np.zeros(flat.shape[1], np.float32)
    for c in range(flat.shape[1]):
        vals = sorted(flat[i, c] for i in range(W) if live[i] > 0)
        if not vals:
            continue
        lo, hi = (n - 1) // 2, n // 2
        res[c] = 0.5 * (vals[lo] + vals[hi])
    return res.reshape(tables.shape[1:])


@pytest.mark.parametrize("live_mask", [
    np.ones(6, np.float32),
    np.array([1, 0, 1, 1, 0, 1], np.float32),
])
def test_robust_merge_matches_numpy_reference(live_mask):
    rs = np.random.RandomState(3)
    tables = rs.randn(6, 3, 5).astype(np.float32)
    live = jnp.asarray(live_mask)
    got_med = np.asarray(modes._robust_table_merge(
        jnp.asarray(tables), live, "median", 0))
    np.testing.assert_allclose(got_med, _np_median(tables, live_mask),
                               rtol=1e-6)
    got_tr = np.asarray(modes._robust_table_merge(
        jnp.asarray(tables), live, "trimmed", 1))
    np.testing.assert_allclose(got_tr, _np_trimmed_mean(tables, live_mask, 1),
                               rtol=1e-6)


def test_trimmed_tie_break_is_by_client_index():
    """Duplicate values: the stable argsort ranks ties by client index, so
    the kept set — and therefore the fp sum — is deterministic."""
    tables = jnp.asarray(np.array(
        [[[1.0]], [[1.0]], [[1.0]], [[5.0]]], np.float32))
    live = jnp.ones(4)
    # trim=1 drops rank 0 (client 0, the first 1.0) and rank 3 (the 5.0):
    # survivors are clients 1 and 2 -> mean exactly 1.0
    got = np.asarray(modes._robust_table_merge(tables, live, "trimmed", 1))
    np.testing.assert_array_equal(got, np.array([[1.0]], np.float32))


def test_robust_merge_excludes_dead_rows_from_order_stats():
    """A dead client's value must not shift the median — dead rows are
    excluded, not treated as zero-valued contributions."""
    tables = jnp.asarray(np.array(
        [[[10.0]], [[-100.0]], [[12.0]], [[14.0]]], np.float32))
    live = jnp.asarray(np.array([1, 0, 1, 1], np.float32))
    got = np.asarray(modes._robust_table_merge(tables, live, "median", 0))
    np.testing.assert_array_equal(got, np.array([[12.0]], np.float32))


def test_robust_merge_excludes_nonfinite_live_rows():
    """A live NaN/Inf row is excluded like a dead one — from the order
    statistics AND the live count — so it can neither poison the estimate
    nor burn a slot of the trim budget (a NaN client + trim oversized
    clients must not smuggle an outlier past the trimmed window)."""
    tables = jnp.asarray(np.array(
        [[[np.nan]], [[1.0]], [[2.0]], [[3.0]], [[100.0]]], np.float32))
    live = jnp.ones(5)
    # trim=1 over the 4 FINITE rows: drop 1.0 and 100.0 -> mean(2, 3)
    got = np.asarray(modes._robust_table_merge(tables, live, "trimmed", 1))
    np.testing.assert_array_equal(got, np.array([[2.5]], np.float32))
    got_med = np.asarray(modes._robust_table_merge(tables, live, "median", 0))
    np.testing.assert_array_equal(got_med, np.array([[2.5]], np.float32))


def test_robust_round_masks_nonfinite_client_without_quarantine():
    """A NaN table under a robust policy with the quarantine UNARMED must
    leave the round like a dropped client — masked out of the survivor
    count, the rescale, and the metric folds — never a committed round
    rescaled by the wrong live count (the sum policy skips such a round
    via the non-finite guard; the robust policies degrade it instead)."""
    s = make_session(merge_policy="median",
                     fault_plan=FaultPlan.parse(
                         "client_poison@1:clients=2,value=nan"))
    ms = run(s, 3)
    assert ms[1]["participants"] == 3.0, ms[1]  # the NaN client masked
    assert all(np.isfinite(m["loss_sum"]) for m in ms), ms
    assert np.isfinite(flat_params(s)).all()


def test_robust_merge_degraded_below_trim_is_zero():
    tables = jnp.asarray(np.ones((4, 2, 2), np.float32))
    live = jnp.asarray(np.array([1, 0, 0, 0], np.float32))
    got = np.asarray(modes._robust_table_merge(tables, live, "trimmed", 1))
    np.testing.assert_array_equal(got, np.zeros((2, 2), np.float32))


def test_merge_partial_wires_rejects_bad_robust_calls():
    cfg = ModeConfig(mode="uncompressed", d=4, momentum_type="none",
                     error_type="none")
    with pytest.raises(ValueError, match="no table wire"):
        modes.merge_partial_wires(cfg, {"dense": jnp.zeros((2, 4))},
                                  policy="median", live=jnp.ones(2))
    scfg = ModeConfig(mode="sketch", d=4, k=2, num_rows=2, num_cols=4)
    with pytest.raises(ValueError, match="live-client mask"):
        modes.merge_partial_wires(scfg, {"table": jnp.zeros((2, 2, 4))},
                                  policy="median")
    with pytest.raises(ValueError, match="trim the whole cohort"):
        modes.merge_partial_wires(scfg, {"table": jnp.zeros((2, 2, 4))},
                                  policy="trimmed", live=jnp.ones(2), trim=1)


# --------------------------------------------------- trim=0 == sum, pinned


def test_trimmed_zero_is_sum_bitwise_fused():
    """trimmed@0 on the announce path: params + EVERY logged row bitwise,
    and no silent reroute through the table round."""
    a, b = make_session(), make_session(merge_policy="trimmed", merge_trim=0)
    ra, rb = run(a), run(b)
    assert ra == rb
    np.testing.assert_array_equal(flat_params(a), flat_params(b))
    assert b._payload_client is None
    assert not b._table_round


def test_trimmed_zero_is_sum_bitwise_payload():
    """trimmed@0 on the wire-payload round compiles the exact sum merge."""
    a = make_session(wire_payloads=True)
    b = make_session(wire_payloads=True, merge_policy="trimmed",
                     merge_trim=0)
    ra, rb = run(a), run(b)
    assert ra == rb
    np.testing.assert_array_equal(flat_params(a), flat_params(b))


def test_robust_policy_validation():
    with pytest.raises(ValueError, match="mode='sketch'"):
        engine.EngineConfig(
            mode=ModeConfig(mode="uncompressed", d=8, momentum_type="none",
                            error_type="none"),
            merge_policy="median")
    with pytest.raises(ValueError, match="merge_trim"):
        engine.EngineConfig(
            mode=ModeConfig(mode="sketch", d=8, k=2, num_rows=2, num_cols=4),
            merge_policy="median", merge_trim=1)
    with pytest.raises(ValueError, match="ravel"):
        make_session(merge_policy="median", sketch_path="layerwise")
    with pytest.raises(ValueError, match="split_compile|table-round"):
        make_session(merge_policy="median", split_compile=True)
    # the linear builders refuse a robust cfg outright
    cfg = engine.EngineConfig(
        mode=ModeConfig(mode="sketch", d=8, k=2, num_rows=2, num_cols=4),
        merge_policy="trimmed", merge_trim=1)
    with pytest.raises(ValueError, match="make_payload_round_steps"):
        engine.make_round_step(quad_loss, cfg)


def test_robust_session_falls_back_to_per_round_blocks():
    """run_rounds on a robust session must fall back to per-round dispatch
    (the table round has no fused multi-round program) and still equal the
    sequential rounds bitwise."""
    a = make_session(merge_policy="median")
    assert not a.supports_block_dispatch
    b = make_session(merge_policy="median")
    ra = a.run_rounds([0.05, 0.05, 0.05])
    rb = [b.run_round(0.05) for _ in range(3)]
    assert ra == rb
    np.testing.assert_array_equal(flat_params(a), flat_params(b))


@pytest.mark.parametrize("policy,kw", [
    ("median", {}), ("trimmed", {"merge_trim": 1})])
def test_robust_merge_shard_invariant(policy, kw):
    """Per-client tables make the robust statistic shard-count-invariant:
    client_shards=2 bitwise equals the unsharded table round."""
    a = make_session(merge_policy=policy, **kw)
    b = make_session(merge_policy=policy, client_shards=2, **kw)
    ra, rb = run(a), run(b)
    assert ra == rb
    np.testing.assert_array_equal(flat_params(a), flat_params(b))


def test_robust_merge_mesh_matches_single_device():
    from commefficient_tpu.parallel import mesh as meshlib

    if jax.device_count() < 4:
        pytest.skip("needs the forced multi-device CPU mesh")
    mesh = meshlib.make_mesh_from_spec("clients=4")
    plan = "client_collude@1:frac=0.5"
    a = make_session(merge_policy="median",
                     fault_plan=FaultPlan.parse(plan))
    b = make_session(merge_policy="median", mesh=mesh,
                     fault_plan=FaultPlan.parse(plan))
    run(a, 3), run(b, 3)
    np.testing.assert_array_equal(flat_params(a), flat_params(b))


# ------------------------------------------------------- adversarial suite


def _final_loss(ms):
    """Last round's train loss from a metrics-row list (probe helper)."""
    return ms[-1]["loss_sum"] / max(ms[-1]["count"], 1.0)


# the acceptance A/B harness: W=12, concentrated per-client gradients
# (local_batch 16 of a 20-example shard), no momentum (the attack's effect
# isn't laundered through the momentum EMA), and the metric is the EXACT
# eval loss over the whole dataset — batch noise out of the measurement.
_AB_ROUNDS = 6
_AB_ALL = ",".join(str(r) for r in range(_AB_ROUNDS))
ATTACKS = {
    "client_signflip": f"client_signflip@{_AB_ALL}:clients=0+1",
    "client_scale": f"client_scale@{_AB_ALL}:clients=0+1,factor=25",
    "client_collude": f"client_collude@{_AB_ALL}:frac=0.15",
}

_AB_RS = np.random.RandomState(0)
_AB_X = _AB_RS.randn(240, 6).astype(np.float32)
_AB_Y = (_AB_X @ _AB_RS.randn(6, 3).astype(np.float32)
         ).argmax(-1).astype(np.int32)


def _ab_session(**kw):
    train = FedDataset(_AB_X, _AB_Y,
                       shard_iid(len(_AB_X), 12, np.random.RandomState(1)))
    params = {"w": jnp.full((6, 3), 0.1, jnp.float32), "b": jnp.zeros(3)}
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=quad_loss, eval_loss_fn=quad_loss,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="sketch", d=d, k=8, num_rows=3,
                            num_cols=16, momentum=0.0, momentum_type="none",
                            error_type="virtual"),
        train_set=train, num_workers=12, local_batch_size=16, seed=0, **kw)


def _ab_eval_loss(s) -> float:
    ds = FedDataset(_AB_X, _AB_Y,
                    shard_iid(len(_AB_X), 12, np.random.RandomState(1)))
    ev = s.evaluate(ds, batch_size=64)
    return ev["loss_sum"] / max(ev["count"], 1)


def _ab_arm(policy_kw, plan_text=None) -> float:
    s = _ab_session(
        fault_plan=FaultPlan.parse(plan_text) if plan_text else None,
        **policy_kw)
    for _ in range(_AB_ROUNDS):
        s.run_round(0.05)
    return _ab_eval_loss(s)


_AB_POLICIES = {
    # "sum" as the table round (trimmed@0 IS the sum program), so every arm
    # shares one round shape and damage is attack-caused, not shape-caused
    "sum": {"merge_policy": "trimmed", "merge_trim": 0,
            "wire_payloads": True},
    "trimmed": {"merge_policy": "trimmed", "merge_trim": 3},
    "median": {"merge_policy": "median"},
}


@pytest.mark.parametrize("kind", list(ATTACKS))
def test_attack_degrades_sum_robust_recovers(kind):
    """THE acceptance A/B, fully seeded: the attacked linear sum ends
    measurably worse than its clean run, while trimmed AND median stay
    within the stated eps — 0.75 x the sum's damage, one-sided (a robust
    arm may end BETTER than clean; what it must never do is carry the
    attack) — of their OWN clean runs, and strictly beat the attacked
    sum. Comparing each policy against its own clean baseline is the
    honest frame: robust estimators pay a small clean-accuracy tax (the
    README trade-off), and the defense claim is attack-INVARIANCE."""
    clean = {p: _ab_arm(dict(kw)) for p, kw in _AB_POLICIES.items()}
    plan = ATTACKS[kind]

    def attacked_arm(p):
        kw = dict(_AB_POLICIES[p])
        kw.pop("wire_payloads", None)  # adversarial kinds force the shape
        return _ab_arm(kw, plan)

    att = {p: attacked_arm(p) for p in _AB_POLICIES}
    deg = att["sum"] - clean["sum"]
    assert deg > 0.05, (
        f"{kind} under the linear sum should degrade the eval loss "
        f"measurably (clean {clean['sum']:.4f}, attacked {att['sum']:.4f})")
    eps = 0.75 * deg  # the stated recovery bar
    for policy in ("trimmed", "median"):
        gap = att[policy] - clean[policy]
        assert gap < eps, (
            f"{kind} under {policy}: attacked {att[policy]:.4f} vs own "
            f"clean {clean[policy]:.4f} — gap {gap:.4f} exceeds "
            f"eps={eps:.4f} (sum degraded by {deg:.4f})")
        assert att[policy] < att["sum"], (
            f"{kind}: {policy} ({att[policy]:.4f}) should strictly beat "
            f"the attacked sum ({att['sum']:.4f})")


def test_scale_attack_quarantined_params_equal_drop():
    """A model-replacement scaler caught by the sketch-space screen is —
    in params — the round without that client (the quarantine's original
    contract, extended to the attack kinds)."""
    plan = "client_scale@2:clients=1,factor=100"
    # both sessions run the SAME table-round program (wire_payloads), so
    # the only difference is quarantine-in-merge vs dropped-at-prepare;
    # compare THROUGH the attacked round (a dropped client is additionally
    # re-queued into a later cohort — recovery the quarantine deliberately
    # does not grant an attacker, so later rounds diverge by design)
    a = make_session(client_update_clip=3.0, wire_payloads=True,
                     fault_plan=FaultPlan.parse(plan))
    ms = run(a, 3)
    assert sum(m["clients_quarantined"] for m in ms) == 1
    b = make_session(client_update_clip=3.0, wire_payloads=True,
                     fault_plan=FaultPlan.parse("client_drop@2:clients=1"))
    run(b, 3)
    np.testing.assert_array_equal(flat_params(a), flat_params(b))


def test_adversarial_plan_is_seeded_and_deterministic():
    p1 = FaultPlan.parse("seed=7;client_collude@3:frac=0.5")
    p2 = FaultPlan.parse("seed=7;client_collude@3:frac=0.5")
    s1 = p1.adversarial_plan(3, 8)
    s2 = p2.adversarial_plan(3, 8)
    np.testing.assert_array_equal(s1[0], s2[0])
    np.testing.assert_array_equal(s1[1], s2[1])
    # a different seed picks different colluders (with overwhelming prob.)
    p3 = FaultPlan.parse("seed=8;client_collude@3:frac=0.5")
    s3 = p3.adversarial_plan(3, 8)
    assert not (np.array_equal(s1[1], s3[1])
                and np.array_equal(s1[0], s3[0]))
    # off-schedule rounds return the identity transform and fire nothing
    s_off = p1.adversarial_plan(4, 8)
    np.testing.assert_array_equal(s_off[0], np.ones(8, np.float32))
    np.testing.assert_array_equal(s_off[1], np.arange(8))


def test_collude_source_excludes_co_attacked_positions():
    """With a signflip co-scheduled on the lowest indices, the collusion's
    clone source must skip them — colluders clone an HONEST table, never
    an already-attacked wire (which would amplify the other attack
    instead of staging the documented one)."""
    plan = FaultPlan.parse(
        "seed=7;client_signflip@3:clients=0+1;client_collude@3:frac=0.25")
    scale, src = plan.adversarial_plan(3, 8)
    colluders = [p for p in range(8) if src[p] != p]
    assert colluders, "collusion never fired"
    sources = {int(src[p]) for p in colluders}
    assert len(sources) == 1
    source = sources.pop()
    assert source not in (0, 1), f"clone source {source} is an attacked client"
    assert scale[source] == 1.0 and src[source] == source


def test_collude_single_worker_is_loud_noop():
    """num_workers=1 leaves no honest source: the injection must be a loud
    no-op (identity transform), never an unhandled crash at round prep."""
    plan = FaultPlan.parse("client_collude@1:frac=0.5")
    scale, src = plan.adversarial_plan(1, 1)
    np.testing.assert_array_equal(scale, np.ones(1, np.float32))
    np.testing.assert_array_equal(src, np.arange(1))


def test_adversarial_parse_validation():
    with pytest.raises(ValueError, match="finite nonzero"):
        FaultPlan.parse("client_scale@1:clients=0,factor=0")
    with pytest.raises(ValueError, match="majority"):
        FaultPlan.parse("client_collude@1:frac=0.9")
    with pytest.raises(ValueError, match="unknown param"):
        FaultPlan.parse("client_signflip@1:factor=2")
    # dead schedule rejected at launch like every client_* kind
    plan = FaultPlan.parse("client_signflip@9:clients=0")
    with pytest.raises(ValueError, match="can never fire"):
        plan.validate_rounds(5)


def test_adversarial_kinds_need_table_round():
    with pytest.raises(ValueError, match="mode='sketch'"):
        rs = np.random.RandomState(0)
        x = rs.randn(32, 4).astype(np.float32)
        y = rs.randint(0, 3, 32).astype(np.int32)
        FederatedSession(
            train_loss_fn=quad_loss, eval_loss_fn=quad_loss,
            params={"w": jnp.zeros((4, 3)), "b": jnp.zeros(3)},
            net_state={},
            mode_cfg=ModeConfig(mode="uncompressed", d=15,
                                momentum_type="none", error_type="none"),
            train_set=FedDataset(x, y, shard_iid(32, 8,
                                                 np.random.RandomState(1))),
            num_workers=4, local_batch_size=4, seed=0,
            fault_plan=FaultPlan.parse("client_signflip@1:clients=0"))


# ------------------------------------------------------ per-layer quarantine


def test_layer_scope_single_leaf_bitwise_equals_cohort():
    """Single-leaf model, window=1: the per-leaf norm IS the flat norm, so
    layer scope is bit-identical to the scalar screen — params + rows."""
    plan = "client_poison@2:clients=1,value=big"
    a = make_session(loss_fn=single_leaf_loss, single_leaf=True,
                     client_update_clip=3.0,
                     fault_plan=FaultPlan.parse(plan))
    b = make_session(loss_fn=single_leaf_loss, single_leaf=True,
                     client_update_clip=3.0, quarantine_scope="layer",
                     fault_plan=FaultPlan.parse(plan))
    ra, rb = run(a), run(b)
    assert ra == rb
    assert sum(m["clients_quarantined"] for m in rb) == 1
    np.testing.assert_array_equal(flat_params(a), flat_params(b))


def test_layer_mask_catches_what_flat_norm_dilutes():
    """A client hiding a one-leaf attack inside an in-bounds flat norm: the
    scalar screen passes it, the per-leaf screen trips it."""
    cfg = engine.EngineConfig(
        mode=ModeConfig(mode="sketch", d=1000, k=4, num_rows=2, num_cols=16),
        client_update_clip=2.0, quarantine_scope="layer")
    # leaf medians: a big first leaf, a tiny second leaf
    lmed = jnp.asarray([10.0, 0.1])
    qmed = jnp.asarray(10.0)  # flat norms dominated by leaf 0
    # client 1 moved ALL its mass into leaf 1 (20x that leaf's median)
    # while its flat norm stays ~10 — inside the scalar screen
    norms = jnp.asarray([10.0, 10.2])
    lnorms = jnp.asarray([[10.0, 0.1], [10.0, 2.0]])
    scalar_bad = engine._quarantine_mask(cfg, norms, qmed)
    layer_bad = engine._quarantine_layer_mask(cfg, lnorms, lmed)
    assert not bool(scalar_bad[1]), "scalar screen should miss the attack"
    assert bool(layer_bad[1]), "per-leaf screen should catch it"
    assert not bool(layer_bad[0])


def test_layer_rings_advance_like_L_scalar_rings():
    cfg = engine.EngineConfig(
        mode=ModeConfig(mode="sketch", d=100, k=4, num_rows=2, num_cols=16),
        client_update_clip=2.0, quarantine_scope="layer",
        quarantine_window=3)
    L, W, K = 3, 5, 3
    rs = np.random.RandomState(0)
    lnorms = jnp.asarray(rs.rand(W, L).astype(np.float32) + 0.5)
    part = jnp.asarray(np.array([1, 1, 0, 1, 1], np.float32))
    qstate = {
        "layer_median": jnp.zeros(L), "layer_window": jnp.zeros((L, K)),
        "layer_count": jnp.zeros(L, jnp.int32),
    }
    got = engine._advance_quarantine_layers(cfg, qstate, lnorms, part)
    for leaf in range(L):
        ref = engine._advance_quarantine(
            cfg, {"median": qstate["layer_median"][leaf],
                  "window": qstate["layer_window"][leaf],
                  "count": qstate["layer_count"][leaf]},
            lnorms[:, leaf], part)
        np.testing.assert_array_equal(
            np.asarray(got["layer_median"])[leaf], np.asarray(ref["median"]))
        np.testing.assert_array_equal(
            np.asarray(got["layer_window"])[leaf], np.asarray(ref["window"]))


def test_layer_scope_quarantines_poison_on_payload_and_sharded_paths():
    plan = "client_poison@2:clients=1,value=big"
    for kw in ({"wire_payloads": True}, {"client_shards": 2}):
        s = make_session(client_update_clip=3.0, quarantine_scope="layer",
                         fault_plan=FaultPlan.parse(plan), **kw)
        ms = run(s, 4)
        assert sum(m["clients_quarantined"] for m in ms) == 1, kw
        assert np.isfinite(flat_params(s)).all()
        q = jax.device_get(s.state["quarantine"])
        assert q["layer_median"].shape == (2,)  # w and b leaves


def test_layer_scope_validation():
    with pytest.raises(ValueError, match="client_update_clip"):
        make_session(quarantine_scope="layer")
    with pytest.raises(ValueError, match="fused-paths-only"):
        make_session(client_update_clip=3.0, quarantine_scope="layer",
                     split_compile=True)


# --------------------------------------- quarantine window, sharded/payload


def _rolling_median(vals, k):
    out = []
    for i in range(len(vals)):
        w = vals[max(0, i - k + 1):i + 1]
        out.append(float(np.median(w)))
    return out


@pytest.mark.parametrize("kw", [{"client_shards": 2},
                                {"wire_payloads": True}])
def test_quarantine_window_on_sharded_and_payload_paths(kw):
    """window=K on the sharded and payload paths: on a clean run (nothing
    quarantined, so thresholds never feed back) the windowed threshold
    metric equals the rolling median of the window=1 per-round medians."""
    base = make_session(client_update_clip=50.0, **kw)
    m1 = run(base, 5)
    per_round = [m["quarantine_median"] for m in m1]
    assert not any(m["clients_quarantined"] for m in m1)
    win = make_session(client_update_clip=50.0, quarantine_window=3, **kw)
    m3 = run(win, 5)
    got = [m["quarantine_median"] for m in m3]
    np.testing.assert_allclose(got, _rolling_median(per_round, 3), rtol=1e-6)


# ------------------------------- wire rejection == merge quarantine, bitwise


def test_wire_rejection_equals_merge_quarantine_bitwise():
    """The satellite fix's regression: the SAME attacked payload, once
    rejected at the wire (gauntlet QUARANTINED -> arrived=0, zero table)
    and once admitted but quarantined in the merge (table screen), must
    produce bitwise-identical committed params — and the gauntlet screens
    against the exact scalar ring the merge advances."""

    def served_round(reject_at_wire: bool):
        s = make_session(wire_payloads=True, client_update_clip=3.0,
                         quarantine_window=2)
        # round 0: clean, seeds the table-space median ring
        run(s, 1)
        rnd = s.round
        ids = s.sample_cohort(rnd)
        prep = s.prepare_served_round(rnd, ids,
                                      np.ones(len(ids), np.float32))
        tables, aux = s.compute_client_tables(prep)
        attacked = np.array(tables, copy=True)
        attacked[1] *= 100.0  # model replacement on position 1
        qmed = s.quarantine_median_host()
        assert qmed > 0.0, "ring must be seeded after the clean round"
        policy = PayloadPolicy(
            rows=s.cfg.mode.num_rows, cols=s.cfg.mode.num_cols,
            clip_multiple=3.0, quarantine_median=lambda: qmed)
        arrived = np.ones(len(ids), np.float32)
        wire_tables = np.array(attacked, copy=True)
        if reject_at_wire:
            t, decision, _ = validate_payload(attacked[1], policy)
            assert decision == QUARANTINED
            arrived[1] = 0.0
            wire_tables[1] = 0.0  # a rejected frame never reaches the merge
        else:
            # wire screen disarmed: the merge's table screen must catch it
            t, decision, _ = validate_payload(
                attacked[1],
                PayloadPolicy(rows=policy.rows, cols=policy.cols))
            assert decision == ACCEPTED
        prep = s.finish_served_payload(prep, arrived, wire_tables, aux)
        m = s.commit_round(s.dispatch_round(prep, 0.05))[0]
        return s, m

    a, ma = served_round(reject_at_wire=True)
    b, mb = served_round(reject_at_wire=False)
    assert ma["clients_quarantined"] == 0.0  # never arrived
    assert mb["clients_quarantined"] == 1.0  # caught in-merge
    assert ma["participants"] == mb["participants"]
    np.testing.assert_array_equal(flat_params(a), flat_params(b))
