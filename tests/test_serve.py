"""Streaming aggregation service (serve/) — ISSUE 6 tentpole.

Four layers:

1. Host-pure unit coverage of the ingest layer (admission control:
   backpressure, duplicate, out-of-round, early buffering), the W-of-N
   assembler, the O(1) fold_in client state, the traffic generator, and
   both transports (in-process + loopback socket).
2. THE acceptance pin: a served W-of-N round — same arrivals — is
   bit-identical (params + logged metrics) to the batch-simulator round
   that drops the same cohort positions via the fault plan, fused AND on
   the sharded single-device reference program.
3. Checkpoint discipline: requeue AGES and the pending arrival queue
   round-trip through meta.json; a preempted --serve run resumes
   bit-identical to the uninterrupted one through the real CLI.
4. The ops surface: /metrics endpoint fields over a live service.

The session-level tests use the same tiny-MLP/synthetic-data substitution
as tests/test_runner.py (serving logic is model-agnostic)."""

import json
import os
import tracemalloc
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

import cv_train
from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.resilience import EXIT_RESUMABLE, FaultPlan
from commefficient_tpu.serve import (
    AggregationService,
    CohortAssembler,
    IngestQueue,
    ServeConfig,
    SocketTransport,
    Submission,
    TraceConfig,
    TrafficGenerator,
    submit_over_socket,
)
from commefficient_tpu.serve import clients as cl
from commefficient_tpu.serve.ingest import (
    ACCEPTED,
    BUFFERED,
    DUPLICATE,
    NOT_INVITED,
    OUT_OF_ROUND,
    QUEUE_FULL,
)
from commefficient_tpu.serve.metrics import MetricsServer
from commefficient_tpu.utils import checkpoint as ckpt
from commefficient_tpu.utils.config import make_parser, resolve_defaults

LR = 0.05


# ---------------------------------------------------------------- ingest layer


def _sub(cid, rnd=0, latency=0.1):
    return Submission(client_id=cid, round=rnd, latency_s=latency)


def test_ingest_accepts_invited_and_rejects_uninvited():
    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2, 3])
    assert q.submit(_sub(1)) == ACCEPTED
    assert q.submit(_sub(9)) == NOT_INVITED
    assert q.counters()["accepted"] == 1
    assert q.counters()["rejected_uninvited"] == 1


def test_ingest_rejects_duplicate_submission():
    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2])
    assert q.submit(_sub(1)) == ACCEPTED
    assert q.submit(_sub(1)) == DUPLICATE  # at-least-once transport retry
    assert q.counters()["rejected_dup"] == 1
    assert len(q.arrivals()) == 1  # the merge never double-counts


def test_ingest_backpressure_on_full_queue():
    q = IngestQueue(capacity=2)
    q.open_round(0, [1, 2, 3])
    assert q.submit(_sub(1)) == ACCEPTED
    assert q.submit(_sub(2)) == ACCEPTED
    assert q.submit(_sub(3)) == QUEUE_FULL  # the backpressure signal
    assert q.counters()["rejected_full"] == 1


def test_ingest_rejects_late_out_of_round():
    q = IngestQueue(capacity=8)
    q.open_round(3, [1, 2])
    assert q.submit(_sub(1, rnd=2)) == OUT_OF_ROUND  # already-closed round
    assert q.submit(_sub(1, rnd=9)) == OUT_OF_ROUND  # far-future round
    assert q.counters()["rejected_out_of_round"] == 2


def test_ingest_buffers_early_submission_for_next_round():
    """A push for round r+1 while r is open parks in the pending buffer and
    admits the moment r+1 opens — a pushing client never resubmits."""
    q = IngestQueue(capacity=8, pending_capacity=4)
    q.open_round(0, [1, 2])
    assert q.submit(_sub(5, rnd=1, latency=0.7)) == BUFFERED
    assert q.depth() == 1  # parked submissions count toward queue depth
    q.close_round()
    q.open_round(1, [5, 6])
    arr = q.arrivals()
    assert [a.client_id for a in arr] == [5]
    assert arr[0].latency_s == 0.7
    # a parked client NOT invited to round 1 stays parked
    q.close_round()
    q.open_round(2, [7])
    assert q.submit(_sub(9, rnd=3)) == BUFFERED
    q.close_round()
    assert q.pending_snapshot() == [(9, 0.1)]


def test_ingest_buffers_early_push_during_mid_merge_window():
    """The server is mid-merge between close_round(r) and open_round(r+1)
    (no round open): a push for r+1 must BUFFER, not bounce OUT_OF_ROUND —
    a pushing client never resubmits just because it raced the merge."""
    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2])
    q.close_round()  # mid-merge: nothing open
    assert q.submit(_sub(1, rnd=1, latency=0.2)) == BUFFERED
    assert q.submit(_sub(1, rnd=2)) == OUT_OF_ROUND  # beyond next: rejected
    q.open_round(1, [1, 9])
    assert [a.client_id for a in q.arrivals()] == [1]


def test_ingest_pending_buffer_is_bounded():
    q = IngestQueue(capacity=8, pending_capacity=1)
    q.open_round(0, [1])
    assert q.submit(_sub(5, rnd=1)) == BUFFERED
    assert q.submit(_sub(6, rnd=1)) == QUEUE_FULL
    assert q.submit(_sub(5, rnd=1)) == DUPLICATE


# ------------------------------------------------------------ W-of-N assembler


def _closed(latencies, quorum, deadline, invited=None):
    inv = list(invited or range(len(latencies)))
    q = IngestQueue(capacity=64)
    q.open_round(0, inv)
    for cid, lat in zip(inv, latencies):
        if np.isfinite(lat) and lat <= deadline:
            q.submit(Submission(client_id=cid, round=0, latency_s=lat))
    asm = CohortAssembler(q, quorum, deadline)
    return asm.close_virtual(0, inv), asm


def test_assembler_closes_at_quorum():
    """5 invited, quorum 3: the 3 fastest make the cut; the 4th (finite but
    slower than the close) is a straggler; inf is a no-show."""
    closed, asm = _closed([0.5, 0.1, 2.0, 0.3, np.inf], quorum=3, deadline=3.0)
    assert closed.closed_by == "quorum"
    np.testing.assert_array_equal(closed.arrived, [1, 1, 0, 1, 0])
    assert closed.close_latency_s == 0.5
    assert closed.stragglers == 1 and closed.no_shows == 1
    assert asm.counters()["closed_by_quorum"] == 1


def test_assembler_closes_at_deadline_when_short_of_quorum():
    closed, asm = _closed([0.5, np.inf, np.inf, 9.0], quorum=3, deadline=1.0)
    assert closed.closed_by == "deadline"
    np.testing.assert_array_equal(closed.arrived, [1, 0, 0, 0])
    assert closed.survivors == 1
    # 9.0 > deadline: the traffic layer never submitted it -> no-show
    assert closed.no_shows == 3
    assert asm.counters()["closed_by_deadline"] == 1


def test_assembler_wall_close_cuts_at_recv_order():
    q = IngestQueue(capacity=8)
    inv = [10, 11, 12]
    q.open_round(0, inv)
    q.submit(_sub(12, latency=0.9))
    q.submit(_sub(10, latency=0.1))
    asm = CohortAssembler(q, quorum=2, deadline_s=0.05)
    closed = asm.close_wall(0, inv)
    # recv order (12 then 10) decides, not the latency metadata
    np.testing.assert_array_equal(closed.arrived, [1.0, 0.0, 1.0])
    assert closed.closed_by == "quorum"


# ------------------------------------------------- O(1) fold_in client state


def test_fold_in_host_deterministic_and_vectorized():
    ids = np.array([0, 1, 2, 10_000_000 - 1], np.int64)
    a = cl.fold_in_host(42, ids)
    b = cl.fold_in_host(42, ids)
    np.testing.assert_array_equal(a, b)
    assert len(set(a.tolist())) == len(ids)  # no trivial collisions
    assert cl.fold_in_host(42, 1) != cl.fold_in_host(43, 1)  # seed folds in
    # scalar == vectorized element
    assert cl.fold_in_host(42, 2) == a[2]


def test_device_class_stable_and_weighted():
    ids = np.arange(20_000)
    idx = cl.device_class_index(7, ids)
    np.testing.assert_array_equal(idx, cl.device_class_index(7, ids))
    frac = np.bincount(idx, minlength=3) / len(ids)
    want = np.array([c.weight for c in cl.DEFAULT_CLASSES])
    np.testing.assert_allclose(frac, want / want.sum(), atol=0.02)


def test_response_latency_mixes_classes_and_no_shows():
    ids = np.arange(10_000)
    lat = cl.response_latency_s(3, ids, rnd=5)
    assert np.isinf(lat).any() and np.isfinite(lat).any()
    assert (lat[np.isfinite(lat)] > 0).all()
    # round folds in: a different round redraws
    lat2 = cl.response_latency_s(3, ids, rnd=6)
    assert not np.array_equal(lat, lat2)
    np.testing.assert_array_equal(lat, cl.response_latency_s(3, ids, rnd=5))


def test_client_state_is_o1_at_10m_population():
    """The 10M-ID acceptance check in unit form: deriving latencies for
    invite batches drawn from a 10M-ID universe allocates memory
    proportional to the BATCH, never the population (no table anywhere)."""
    def peak(population):
        rs = np.random.RandomState(0)
        tracemalloc.start()
        for rnd in range(8):
            ids = rs.randint(0, population, size=2048)
            cl.response_latency_s(11, ids, rnd)
        _, p = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        return p

    small, big = peak(10_000), peak(10_000_000)
    assert big <= 2 * small, (small, big)
    assert big < 32 << 20  # and absolutely tiny vs any 10M-row table


# ------------------------------------------------------------------- traffic


def test_trace_config_parse_and_rejects_unknown_keys():
    t = TraceConfig.parse("population=500,base_rate=9.5,burst_rate=0.25")
    assert (t.population, t.base_rate, t.burst_rate) == (500, 9.5, 0.25)
    assert TraceConfig.parse("") == TraceConfig()
    with pytest.raises(ValueError, match="unknown key"):
        TraceConfig.parse("populaton=5")
    with pytest.raises(ValueError, match="bad value"):
        TraceConfig.parse("population=lots")


def test_diurnal_rate_shape():
    g = TrafficGenerator(TraceConfig(base_rate=100, diurnal_amplitude=0.5,
                                     diurnal_period_s=86400))
    trough, peak = g.rate_at(0.0), g.rate_at(43200.0)
    assert trough == pytest.approx(50.0) and peak == pytest.approx(150.0)


def test_arrival_events_deterministic_and_window_independent():
    g = TrafficGenerator(TraceConfig(population=1000, base_rate=50, seed=9))
    a = [(t, ids.tolist()) for t, ids in g.arrival_events(0.0, 10.0)]
    b = [(t, ids.tolist()) for t, ids in g.arrival_events(0.0, 10.0)]
    assert a == b and a
    assert all(0 <= i < 1000 for _, ids in a for i in ids)


def test_respond_to_invites_submits_in_latency_order_within_deadline():
    g = TrafficGenerator(TraceConfig(population=100, seed=1))
    got = []
    sent = g.respond_to_invites(0, np.arange(40), lambda s: got.append(s),
                                deadline_s=2.0)
    assert sent == len(got) > 0
    lats = [s.latency_s for s in got]
    assert lats == sorted(lats)
    assert all(lat <= 2.0 for lat in lats)
    expected = g.invite_latencies(0, np.arange(40))
    assert sent == int((expected[np.isfinite(expected)] <= 2.0).sum())


# ---------------------------------------------------------- socket transport


def test_socket_transport_round_trips_admission_decisions():
    q = IngestQueue(capacity=4)
    q.open_round(2, [7, 8])
    t = SocketTransport(q)
    t.start()
    try:
        addr = t.address
        assert submit_over_socket(
            addr, Submission(client_id=7, round=2, latency_s=0.3)) == ACCEPTED
        assert t.submit(
            Submission(client_id=7, round=2)) == DUPLICATE
        assert submit_over_socket(
            addr, Submission(client_id=7, round=0)) == OUT_OF_ROUND
        assert submit_over_socket(
            addr, Submission(client_id=99, round=2)) == NOT_INVITED
    finally:
        t.stop()
    arr = q.arrivals()
    assert [a.client_id for a in arr] == [7]
    assert arr[0].latency_s == 0.3


# --------------------------------------------------- session-level fixtures


def _quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / count, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def _tiny_session(shards=0, seed=0, fault_plan=None, requeue_policy="fifo",
                  num_clients=12, workers=4, din=6, dout=3):
    rs = np.random.RandomState(0)
    x = rs.randn(96, din).astype(np.float32)
    w_true = rs.randn(din, dout).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), num_clients,
                                       np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(din, dout).astype(np.float32) * 0.1),
              "b": jnp.zeros(dout)}
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="uncompressed", d=d, momentum=0.9,
                            momentum_type="virtual", error_type="none"),
        train_set=train, num_workers=workers, local_batch_size=4,
        seed=seed, client_shards=shards, fault_plan=fault_plan,
        requeue_policy=requeue_policy,
    )


def _serve_rounds(session, n, quorum=2, deadline=1.0, trace_seed=5):
    """Run n served rounds; returns (metrics rows, per-round dropped
    positions)."""
    svc = AggregationService(
        session, ServeConfig(quorum=quorum, deadline_s=deadline),
        traffic=TrafficGenerator(
            TraceConfig(population=session.train_set.num_clients,
                        seed=trace_seed)),
    ).start()
    src = svc.source()
    rows, drops = [], []
    try:
        for _ in range(n):
            prep = src.next()
            drops.append(sorted(
                int(p) for p in
                np.flatnonzero(np.asarray(prep.batch["_valid"]) == 0.0)))
            rows.append(session.commit_round(
                session.dispatch_round(prep, LR))[0])
    finally:
        svc.close()
    return rows, drops


def _drop_plan(drops):
    return ";".join(
        f"client_drop@{r}:clients=" + "+".join(map(str, pos))
        for r, pos in enumerate(drops) if pos)


def _assert_params_equal(sa, sb):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------- THE parity acceptance pin


@pytest.mark.parametrize("shards", [0, 2], ids=["fused", "sharded"])
def test_served_round_bit_identical_to_batch_simulator(shards):
    """A served W-of-N round — quorum close, stragglers/no-shows masked and
    re-queued — is bit-identical (params + every logged metric) to the
    batch-simulator round that drops the SAME positions via the fault plan,
    on the fused path and on the sharded single-device reference program."""
    a = _tiny_session(shards=shards)
    rows_a, drops = _serve_rounds(a, 3, quorum=2, deadline=1.0)
    assert any(drops), "trace produced no casualties; pin would be vacuous"

    plan = FaultPlan.parse(_drop_plan(drops))
    b = _tiny_session(shards=shards, fault_plan=plan)
    rows_b = [b.run_round(LR) for _ in range(3)]

    for ra, rb in zip(rows_a, rows_b):
        assert set(ra) == set(rb)
        for k in ra:
            assert ra[k] == rb[k], (k, ra[k], rb[k])
    _assert_params_equal(a, b)
    # the re-queues evolved identically too (served no-shows == faulted drops)
    assert list(a._requeue) == list(b._requeue)
    assert a._requeue_enqueued == b._requeue_enqueued


def test_full_arrival_round_is_bit_identical_to_plain_round():
    """When every invitee arrives inside the quorum window the served round
    must be EXACTLY the batch-simulator round: same cohort, same batch,
    same key chain — the serving layer is a pure re-plumbing."""
    a = _tiny_session()
    svc = AggregationService(
        a, ServeConfig(quorum=a.num_workers, deadline_s=1e9),
        traffic=TrafficGenerator(
            TraceConfig(population=a.train_set.num_clients, seed=5)),
    ).start()
    try:
        src = svc.source()
        rows_a = [a.commit_round(a.dispatch_round(src.next(), LR))[0]
                  for _ in range(2)]
    finally:
        svc.close()
    b = _tiny_session()
    rows_b = [b.run_round(LR) for _ in range(2)]
    for ra, rb in zip(rows_a, rows_b):
        for k in ra:
            assert ra[k] == rb[k], k
    _assert_params_equal(a, b)


# ----------------------------------------------- checkpoint: ages + pending


def test_requeue_ages_persist_through_checkpoint(tmp_path):
    """Satellite: --requeue_policy aged ages resume their REAL rounds-waiting
    from meta.json instead of restarting at 1 — the aged serving order after
    resume matches the uninterrupted session's exactly."""
    plan = FaultPlan.parse("client_drop@0:clients=0+1;client_drop@1:clients=2")
    a = _tiny_session(fault_plan=plan, requeue_policy="aged", workers=3)
    a.run_round(LR)
    a.run_round(LR)
    assert a._requeue_enqueued  # queued casualties carry their drop rounds
    path = ckpt.save(str(tmp_path), a)

    b = _tiny_session(requeue_policy="aged", workers=3)
    ckpt.restore(path, b)
    assert b._requeue_enqueued == a._requeue_enqueued
    assert list(b._requeue) == list(a._requeue)
    # behavioral pin: the aged weighted order (a function of the AGES) now
    # serves identically on both sessions for the rounds that follow
    for _ in range(3):
        ma, mb = a.run_round(LR), b.run_round(LR)
        assert ma["loss_sum"] == mb["loss_sum"]
    assert list(a._requeue) == list(b._requeue)
    _assert_params_equal(a, b)


def test_pending_arrival_queue_persists_through_checkpoint(tmp_path):
    """The early-submission buffer rides meta.json: a service rebuilt on a
    restored session sees the parked pushes again."""
    a = _tiny_session()
    svc = AggregationService(
        a, ServeConfig(quorum=2, deadline_s=1.0),
        traffic=TrafficGenerator(
            TraceConfig(population=a.train_set.num_clients, seed=5)),
    ).start()
    try:
        src = svc.source()
        prep = src.next()
        # park an early push for the NEXT round while round 1 is not open
        a.commit_round(a.dispatch_round(prep, LR))
        svc.queue.open_round(1, [])  # open so round-2 pushes are "early"
        assert svc.queue.submit(
            Submission(client_id=3, round=2, latency_s=0.4)) == BUFFERED
        svc._record_boundary(1)
        path = ckpt.save(str(tmp_path), a)
    finally:
        svc.close()

    b = _tiny_session()
    ckpt.restore(path, b)
    assert b.restored_serve_meta["pending"] == [[3, 0.4]]
    svc_b = AggregationService(
        b, ServeConfig(quorum=2, deadline_s=1.0),
        traffic=TrafficGenerator(
            TraceConfig(population=b.train_set.num_clients, seed=5)))
    try:
        assert svc_b.queue.pending_snapshot() == [(3, 0.4)]
    finally:
        svc_b.close()


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


def _argv(extra=()):
    return [
        "--dataset", "cifar10", "--mode", "uncompressed", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--lr_scale", "0.05",
        "--weight_decay", "0", "--data_root", "/nonexistent", *extra,
    ]


@pytest.mark.chaos
def test_cli_serve_preempt_resume_bit_identical(tiny_cv, tmp_path):
    """The served CLI run (W-of-N, requeue, trace traffic) preempted
    mid-run resumes BIT-IDENTICAL to the uninterrupted served run — the
    arrival stream, requeue ages, and pending queue all restore from
    meta.json (acceptance criterion 3's checkpoint half)."""
    serve_flags = ("--serve", "inproc", "--serve_quorum", "5",
                   "--serve_deadline", "2.0", "--num_rounds", "4")
    sa = cv_train.main(_argv(serve_flags))  # uninterrupted reference

    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--checkpoint_every", "2",
             "--fault_plan", "preempt@2"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(_argv(serve_flags) + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    sc = cv_train.main(_argv(serve_flags) + chaos + ["--resume"])
    assert sc.round == 4
    _assert_params_equal(sa, sc)
    assert list(sa._requeue) == list(sc._requeue)
    assert sa._requeue_enqueued == sc._requeue_enqueued


@pytest.mark.chaos
def test_cli_serve_end_to_end_with_aged_requeue(tiny_cv):
    """--serve inproc + --requeue_policy aged through the real CLI: the run
    finishes every round with finite params and no leaked service threads."""
    import threading

    before = {t.name for t in threading.enumerate()}
    s = cv_train.main(_argv(("--serve", "inproc", "--serve_quorum", "5",
                             "--serve_deadline", "2.0", "--num_rounds", "4",
                             "--requeue_policy", "aged")))
    assert s.round == 4
    flat = np.asarray(ravel_pytree(jax.device_get(s.state["params"]))[0])
    assert np.isfinite(flat).all()
    leaked = {t.name for t in threading.enumerate()} - before
    assert not {n for n in leaked if n.startswith("serve-")}, leaked


# --------------------------------------------------------------- ops surface


def test_metrics_endpoint_serves_service_snapshot():
    a = _tiny_session()
    svc = AggregationService(
        a, ServeConfig(quorum=2, deadline_s=1.0, metrics_port=0),
        traffic=TrafficGenerator(
            TraceConfig(population=a.train_set.num_clients, seed=5)),
    ).start()
    try:
        src = svc.source()
        a.commit_round(a.dispatch_round(src.next(), LR))
        host, port = svc.metrics_server.address
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5) as resp:
            m = json.loads(resp.read())
        for field in ("round", "queue_depth", "arrival_rate_per_s",
                      "submissions", "rounds", "requeue_depth",
                      "clients_dropped", "clients_quarantined", "quorum"):
            assert field in m, field
        assert m["round"] == 1
        assert m["rounds"]["rounds_closed"] == 1
        assert m["submissions"]["accepted"] >= 2
        # non-metrics paths 404
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{host}:{port}/other", timeout=5)
    finally:
        svc.close()


def test_service_refuses_bad_configs():
    a = _tiny_session()
    with pytest.raises(ValueError, match="quorum"):
        AggregationService(a, ServeConfig(quorum=99),
                           traffic=TrafficGenerator(TraceConfig()))
    with pytest.raises(ValueError, match="traffic"):
        AggregationService(a, ServeConfig(quorum=2))
    with pytest.raises(ValueError, match="transport"):
        AggregationService(a, ServeConfig(quorum=2, transport="carrier-pigeon"),
                           traffic=TrafficGenerator(TraceConfig()))


# ------------------------------------------------------------- untrusted wire
# ISSUE 9: client-computed sketch payloads, the server-side validation
# gauntlet, transport chaos, and overload shedding.

from commefficient_tpu.resilience.faults import FaultPlan as _FP  # noqa: E402
from commefficient_tpu.serve import abort_over_socket  # noqa: E402
from commefficient_tpu.serve import submit_with_retries  # noqa: E402
from commefficient_tpu.serve.clients import DeviceClass  # noqa: E402
from commefficient_tpu.serve.ingest import (  # noqa: E402
    MALFORMED,
    QUARANTINED,
    SHEDDING,
    STALE_SCHEMA,
    PayloadPolicy,
    validate_payload,
)
from commefficient_tpu.sketch.payload import (  # noqa: E402
    SCHEMA_VERSION,
    encode_frame,
)

# a device-class mix with no organic no-shows/straggle, so wire-chaos tests
# target exactly the clients the fault plan names
RELIABLE_CLASSES = (
    DeviceClass("lab", weight=1.0, latency_median_s=0.1,
                latency_sigma=0.1, no_show_prob=0.0),
)

_PAYLOAD_SHAPE = (3, 8)  # (num_rows, num_cols) of the tiny sketch sessions


def _sketch_session(shards=0, seed=0, fault_plan=None, clip=0.0, window=1,
                    num_clients=12, workers=4, din=6, dout=3):
    """_tiny_session's sketch-mode twin: wire_payloads=True, so the round is
    the two-program payload shape (client tables + table merge)."""
    rs = np.random.RandomState(0)
    x = rs.randn(96, din).astype(np.float32)
    w_true = rs.randn(din, dout).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), num_clients,
                                       np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(din, dout).astype(np.float32) * 0.1),
              "b": jnp.zeros(dout)}
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="sketch", d=d, k=4,
                            num_rows=_PAYLOAD_SHAPE[0],
                            num_cols=_PAYLOAD_SHAPE[1],
                            momentum=0.9, momentum_type="virtual",
                            error_type="virtual"),
        train_set=train, num_workers=workers, local_batch_size=4,
        seed=seed, client_shards=shards, fault_plan=fault_plan,
        wire_payloads=True, client_update_clip=clip,
        quarantine_window=window,
    )


def _serve_payload_rounds(session, n, transport="inproc", quorum=2,
                          deadline=5.0, trace_seed=5,
                          classes=RELIABLE_CLASSES, fastpath=False):
    """Run n served wire-payload rounds; returns (service, per-round dropped
    positions). The service is closed before returning."""
    svc = AggregationService(
        session,
        ServeConfig(quorum=quorum, deadline_s=deadline, transport=transport,
                    payload="sketch", fastpath=fastpath),
        traffic=TrafficGenerator(
            TraceConfig(population=session.train_set.num_clients,
                        seed=trace_seed), classes=classes),
    ).start()
    src = svc.source()
    drops = []
    try:
        for _ in range(n):
            prep = src.next()
            arrived = prep.payload[1]
            drops.append(sorted(
                int(p) for p in np.flatnonzero(arrived == 0.0)))
            session.commit_round(session.dispatch_round(prep, LR))
    finally:
        svc.close()
    return svc, drops


def _policy(clip=0.0, median=None):
    return PayloadPolicy(rows=_PAYLOAD_SHAPE[0], cols=_PAYLOAD_SHAPE[1],
                         clip_multiple=clip,
                         quarantine_median=(None if median is None
                                            else (lambda: median)))


def _table(fill=0.5):
    return np.full(_PAYLOAD_SHAPE, fill, np.float32)


# ---------------------------------------------------- the validation gauntlet


def test_validate_payload_accepts_clean_frame_and_raw_array():
    t = _table()
    for payload in (encode_frame(t), t):
        out, decision, detail = validate_payload(payload, _policy())
        assert decision == ACCEPTED, (decision, detail)
        np.testing.assert_array_equal(out, t)
        assert out.dtype == np.float32


def test_validate_payload_rejects_checksum_flip():
    frame = _FP.corrupt_frame(encode_frame(_table()))
    out, decision, detail = validate_payload(frame, _policy())
    assert (out, decision) == (None, MALFORMED)
    assert "checksum" in detail


def test_validate_payload_rejects_truncation_by_length_prefix():
    frame = _FP.truncate_frame(encode_frame(_table()))
    out, decision, detail = validate_payload(frame, _policy())
    assert (out, decision) == (None, MALFORMED)
    assert "length prefix" in detail or "decoded" in detail


def test_validate_payload_rejects_stale_schema():
    frame = encode_frame(_table(), schema=SCHEMA_VERSION + 1)
    out, decision, detail = validate_payload(frame, _policy())
    assert (out, decision) == (None, STALE_SCHEMA)


def test_validate_payload_rejects_shape_dtype_and_garbage():
    good = encode_frame(_table())
    cases = [
        None,                                    # no payload at all
        "zzz",                                   # not a frame
        {**good, "shape": [4, 8]},               # shape vs the SERVER's spec
        {**good, "dtype": "<f8"},                # wrong wire dtype
        {**good, "nbytes": 12},                  # lying length prefix
        {**good, "data": "!!!notbase64!!!"},     # undecodable data
        {k: v for k, v in good.items() if k != "schema"},  # missing field
        np.zeros((4, 4), np.float32),            # raw array, wrong shape
        np.zeros(_PAYLOAD_SHAPE, np.float64),    # raw array, wrong dtype
    ]
    for payload in cases:
        out, decision, _ = validate_payload(payload, _policy())
        assert (out, decision) == (None, MALFORMED), payload


def test_validate_payload_quarantines_nonfinite_and_oversized():
    bad = _table()
    bad[1, 2] = np.nan
    out, decision, detail = validate_payload(encode_frame(bad), _policy())
    assert (out, decision) == (None, QUARANTINED)
    assert "non-finite" in detail
    # sketch-space L2 screen against the running median, at the wire
    out, decision, detail = validate_payload(
        encode_frame(_table(100.0)), _policy(clip=2.0, median=1.0))
    assert (out, decision) == (None, QUARANTINED)
    assert "median" in detail
    # same table under a healthy median passes
    out, decision, _ = validate_payload(
        encode_frame(_table(100.0)), _policy(clip=2.0, median=1e3))
    assert decision == ACCEPTED


def test_payload_queue_runs_gauntlet_and_counts_rejections():
    q = IngestQueue(capacity=8, payload_policy=_policy())
    q.open_round(0, [1, 2, 3, 4])
    ok = encode_frame(_table())
    assert q.submit(Submission(1, 0, 0.1, payload=ok)) == ACCEPTED
    assert q.submit(Submission(
        2, 0, 0.1, payload=_FP.corrupt_frame(ok))) == MALFORMED
    assert q.submit(Submission(
        3, 0, 0.1, payload=encode_frame(_table(), schema=99))) == STALE_SCHEMA
    assert q.submit(Submission(4, 0, 0.1, payload=None)) == MALFORMED
    c = q.counters()
    assert c["rejected_malformed"] == 2
    assert c["rejected_stale_schema"] == 1
    # a rejected client may retry with a GOOD frame: rejection != admission
    assert q.submit(Submission(2, 0, 0.2, payload=ok)) == ACCEPTED
    arr = q.arrivals()
    assert sorted(a.client_id for a in arr) == [1, 2]
    for a in arr:
        np.testing.assert_array_equal(a.table, _table())


def test_payload_round_rejects_early_push():
    """A sketch payload is a function of the OPEN round's params — a table
    'for the next round' cannot exist yet, so the pending buffer is closed
    on the payload path."""
    q = IngestQueue(capacity=8, payload_policy=_policy())
    q.open_round(0, [1])
    assert q.submit(Submission(
        5, 1, 0.1, payload=encode_frame(_table()))) == OUT_OF_ROUND
    assert q.counters()["buffered"] == 0


# ------------------------------------------------------------- load shedding


def test_shedding_turns_overload_away_before_other_work():
    q = IngestQueue(capacity=4, pending_capacity=0, shed_watermark=0.5,
                    shed_retry_after_s=2.5)
    q.open_round(0, [1, 2, 3, 4, 5])
    assert q.submit(_sub(1)) == ACCEPTED
    assert q.submit(_sub(2)) == ACCEPTED  # depth 2 = watermark (0.5 * 4)
    # sheds before the expensive work (invite lookup, payload decode) —
    # a fresh or uninvited client costs only the depth comparison plus one
    # O(1) set probe under a flood
    assert q.submit(_sub(3)) == SHEDDING
    assert q.submit(_sub(99)) == SHEDDING
    # ...but a retry of an ALREADY-ADMITTED submission hears DUPLICATE
    # (== success: the reply was lost, the merge will count it) — shedding
    # must not make an at-least-once client burn its retry budget on a
    # submission the server already took
    assert q.submit(_sub(1)) == DUPLICATE
    assert q.counters()["shed"] == 2
    assert q.counters()["rejected_dup"] == 1
    assert q.shed_retry_after_s == 2.5
    assert q.depth() == 2  # bounded: nothing queued past the watermark


def test_shedding_off_by_default_keeps_queue_full_semantics():
    q = IngestQueue(capacity=2)
    q.open_round(0, [1, 2, 3])
    assert q.submit(_sub(1)) == ACCEPTED
    assert q.submit(_sub(2)) == ACCEPTED
    assert q.submit(_sub(3)) == QUEUE_FULL
    assert q.counters()["shed"] == 0


def test_socket_shed_reply_carries_retry_after_hint():
    q = IngestQueue(capacity=4, pending_capacity=0, shed_watermark=0.25,
                    shed_retry_after_s=1.5)
    q.open_round(0, [1, 2])
    t = SocketTransport(q)
    t.start()
    try:
        assert submit_over_socket(t.address, _sub(1)) == ACCEPTED
        from commefficient_tpu.serve.transport import _roundtrip

        reply = _roundtrip(t.address, _sub(2))
        assert reply["status"] == SHEDDING
        assert reply["retry_after_s"] == 1.5
    finally:
        t.stop()


# -------------------------------------------------------- client-side retries


def test_submit_with_retries_backs_off_on_shedding_with_hint_floor():
    from commefficient_tpu.serve import transport as tmod

    replies = [{"status": SHEDDING, "retry_after_s": 0.8},
               {"status": SHEDDING, "retry_after_s": 0.8},
               {"status": ACCEPTED}]
    calls, sleeps = [], []

    def fake_roundtrip(addr, sub, timeout_s=5.0):
        calls.append(sub)
        return replies[len(calls) - 1]

    orig = tmod._roundtrip
    tmod._roundtrip = fake_roundtrip
    try:
        status = submit_with_retries(
            ("h", 1), _sub(7), max_retries=3, base_backoff_s=0.05,
            sleep=sleeps.append)
    finally:
        tmod._roundtrip = orig
    assert status == ACCEPTED
    assert len(calls) == 3
    # every backoff is floored at the server's hint
    assert all(s >= 0.8 for s in sleeps)


def test_submit_with_retries_duplicate_is_success_and_returns_immediately():
    from commefficient_tpu.serve import transport as tmod

    def fake_roundtrip(addr, sub, timeout_s=5.0):
        return {"status": DUPLICATE}

    sleeps = []
    orig = tmod._roundtrip
    tmod._roundtrip = fake_roundtrip
    try:
        status = submit_with_retries(("h", 1), _sub(7), sleep=sleeps.append)
    finally:
        tmod._roundtrip = orig
    # at-least-once: the first attempt's admission survived a lost reply —
    # a DUPLICATE on retry IS success, and no backoff is spent on it
    assert status == DUPLICATE
    assert sleeps == []


def test_submit_with_retries_bounded_budget_and_deterministic_jitter():
    from commefficient_tpu.serve import transport as tmod

    def fake_roundtrip(addr, sub, timeout_s=5.0):
        raise ConnectionRefusedError("down")

    schedules = []
    for _ in range(2):
        sleeps = []
        orig = tmod._roundtrip
        tmod._roundtrip = fake_roundtrip
        try:
            status = submit_with_retries(
                ("h", 1), _sub(7, rnd=3), max_retries=3,
                base_backoff_s=0.05, max_backoff_s=0.4, sleep=sleeps.append)
        finally:
            tmod._roundtrip = orig
        assert status == "CONN_FAILED"
        assert len(sleeps) == 3  # bounded: exactly max_retries backoffs
        schedules.append(tuple(sleeps))
    # jitter is a pure function of (client, round, attempt): replayable
    assert schedules[0] == schedules[1]
    # exponential growth with jitter in [0.5, 1.5)x, capped
    assert all(0.5 * 0.05 * 2**i <= s <= 1.5 * min(0.05 * 2**i, 0.4)
               for i, s in enumerate(schedules[0]))


# -------------------------------------------- payload parity (acceptance pin)


@pytest.mark.parametrize("shards", [0, 2], ids=["fused", "sharded"])
def test_served_payload_round_bit_identical_to_batch_round(shards):
    """THE wire acceptance pin: a served round whose submissions carry REAL
    client-computed sketch tables — with wire_corrupt + wire_dup +
    client_poison injected at the transport seam — commits params
    BIT-identical to the batch wire-payload round that drops the same
    casualties, fused AND sharded. Every rejection class fired as an
    admission counter."""
    plan = _FP.parse(
        "wire_corrupt@1:clients=0;wire_dup@1:clients=1;"
        "client_poison@2:clients=3,value=nan")
    a = _sketch_session(shards=shards, fault_plan=plan, clip=3.0)
    svc, drops = _serve_payload_rounds(a, 3, quorum=4, deadline=30.0)
    c = svc.queue.counters()
    assert c["rejected_malformed"] >= 1, c     # corrupt -> checksum
    assert c["rejected_dup"] >= 1, c           # dup -> dedup, single-count
    assert c["rejected_quarantined"] >= 1, c   # poison -> wire screen
    assert drops[1] and drops[2], drops

    pl = ";".join(f"client_drop@{r}:clients=" + "+".join(map(str, pos))
                  for r, pos in enumerate(drops) if pos)
    b = _sketch_session(shards=shards, fault_plan=_FP.parse(pl), clip=3.0)
    for _ in range(3):
        b.run_round(LR)
    _assert_params_equal(a, b)
    assert list(a._requeue) == list(b._requeue)


def test_served_payload_round_over_socket_matches_inproc():
    """The loopback socket (real frame serialization, checksums, concurrent
    connections) and the in-process transport commit IDENTICAL params for
    the same trace — float32 framing is exact, so the wire adds no
    arithmetic."""
    a = _sketch_session()
    _serve_payload_rounds(a, 2, transport="inproc", quorum=4, deadline=30.0)
    b = _sketch_session()
    _serve_payload_rounds(b, 2, transport="socket", quorum=4, deadline=30.0)
    _assert_params_equal(a, b)


def test_payload_session_rejects_split_compile():
    """wire_payloads IS a two-program round; stacking --split_compile on it
    would silently pick a different program pair — reject at build."""
    with pytest.raises(ValueError, match="two-program"):
        rs = np.random.RandomState(0)
        x = rs.randn(32, 6).astype(np.float32)
        y = np.zeros(32, np.int32)
        train = FedDataset(x, y, shard_iid(32, 4, np.random.RandomState(1)))
        params = {"w": jnp.zeros((6, 3)), "b": jnp.zeros(3)}
        FederatedSession(
            train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
            params=params, net_state={},
            mode_cfg=ModeConfig(mode="sketch", d=21, k=4, num_rows=3,
                                num_cols=8),
            train_set=train, num_workers=2, local_batch_size=4,
            wire_payloads=True, split_compile=True)


def test_serve_payload_mode_requires_wire_payload_session():
    a = _tiny_session()  # announce-shaped session (wire_payloads off)
    with pytest.raises(ValueError, match="wire_payloads"):
        AggregationService(
            a, ServeConfig(quorum=2, payload="sketch"),
            traffic=TrafficGenerator(TraceConfig(population=12)))


# ------------------------------------------ zero-copy fast path (bitwise pin)


@pytest.mark.parametrize("transport", ["inproc", "socket"])
@pytest.mark.parametrize("shards", [0, 2], ids=["fused", "sharded"])
def test_fastpath_served_round_bit_identical_to_slow_path(shards, transport):
    """THE fast-path acceptance pin: --serve_fastpath (pinned ring +
    batched gauntlet + chunked ingest/H2D overlap) commits params BITWISE
    identical to the slow path over the same trace and the same injected
    chaos — fused and sharded, inproc and socket. The ring is a layout
    change, never an order change."""
    plan = "wire_corrupt@1:clients=0;client_poison@2:clients=3,value=nan"
    a = _sketch_session(shards=shards, fault_plan=_FP.parse(plan), clip=3.0)
    svc_a, drops_a = _serve_payload_rounds(
        a, 3, transport=transport, quorum=4, deadline=30.0, fastpath=True)
    b = _sketch_session(shards=shards, fault_plan=_FP.parse(plan), clip=3.0)
    svc_b, drops_b = _serve_payload_rounds(
        b, 3, transport=transport, quorum=4, deadline=30.0, fastpath=False)
    assert drops_a == drops_b
    _assert_params_equal(a, b)
    assert list(a._requeue) == list(b._requeue)
    # the chaos actually went through the fast-path gauntlet
    ca = svc_a.queue.counters()
    assert ca["rejected_malformed"] >= 1, ca
    assert ca["rejected_quarantined"] >= 1, ca
    if transport == "socket":
        # and the socket run really batched: the gauntlet histogram saw
        # blocks, and the ring saw occupancy
        assert svc_a.registry.histogram("serve_gauntlet_batch_ms").count > 0
    assert svc_a.registry.histogram("serve_ring_occupancy").count > 0


def test_fastpath_touches_fewer_bytes_than_slow_path_over_socket():
    """The perf claim the lint rule guards, as a counter: over the socket
    the slow path touches each accepted table's bytes twice (decode copy +
    assembler stack copy), the fast path once (the ring-slot write)."""
    a = _sketch_session()
    svc_a, _ = _serve_payload_rounds(
        a, 2, transport="socket", quorum=4, deadline=30.0, fastpath=True)
    fast = svc_a.registry.counter("serve_table_bytes_copied_total").value
    b = _sketch_session()
    svc_b, _ = _serve_payload_rounds(
        b, 2, transport="socket", quorum=4, deadline=30.0, fastpath=False)
    slow = svc_b.registry.counter("serve_table_bytes_copied_total").value
    assert 0 < fast < slow, (fast, slow)
    _assert_params_equal(a, b)  # fewer copies, same bytes served


def test_fastpath_requires_sketch_payload_and_no_edges():
    a = _sketch_session()
    with pytest.raises(ValueError, match="serve_edges"):
        AggregationService(
            a, ServeConfig(quorum=2, payload="sketch", fastpath=True,
                           transport="socket", edges=2),
            traffic=TrafficGenerator(TraceConfig(population=12)))
    b = _tiny_session()
    with pytest.raises(ValueError, match="fastpath"):
        AggregationService(
            b, ServeConfig(quorum=2, payload="announce", fastpath=True),
            traffic=TrafficGenerator(TraceConfig(population=12)))


# ------------------------------------- single-damaged-frame property (bitwise)


def _one_payload_round(session, mutate=None, target=2):
    """One served-style payload round driven at queue level: every invitee
    submits its real table, `mutate(frame)` damages the target position's
    frame (None = clean). Returns committed params (flat)."""
    ids = session.sample_cohort(0)
    prep0 = session.prepare_served_round(
        0, ids, np.ones(len(ids), np.float32))
    tables, aux = session.compute_client_tables(prep0)
    q = IngestQueue(capacity=16, payload_policy=_policy())
    q.open_round(0, ids)
    asm = CohortAssembler(q, quorum=len(ids), deadline_s=10.0,
                          payload_shape=_PAYLOAD_SHAPE)
    for i, cid in enumerate(ids):
        payload = encode_frame(tables[i])
        if i == target and mutate is not None:
            sent = mutate(payload)
            for p in sent if isinstance(sent, list) else [sent]:
                if p is not None:
                    q.submit(Submission(int(cid), 0, 0.1, payload=p))
        else:
            q.submit(Submission(int(cid), 0, 0.1, payload=payload))
    closed = asm.close_virtual(0, ids)
    prep = session.finish_served_payload(
        prep0, closed.arrived, closed.tables, aux)
    session.commit_round(session.dispatch_round(prep, LR))
    return np.asarray(
        ravel_pytree(jax.device_get(session.state["params"]))[0])


DAMAGE = {
    "corrupt": lambda f: _FP.corrupt_frame(f),
    "truncate": lambda f: _FP.truncate_frame(f),
    "stale_schema": lambda f: {**f, "schema": SCHEMA_VERSION + 7},
    "wrong_shape": lambda f: {**f, "shape": [1, 1]},
    "garbage": lambda f: "not a frame at all",
    "dropped_mid_send": lambda f: None,  # the send never completes
}


@pytest.mark.parametrize("kind", sorted(DAMAGE))
def test_single_damaged_frame_never_changes_committed_params(kind):
    """The robustness property: ANY single corrupted / truncated / stale /
    garbled / half-sent frame changes NOTHING about the committed params
    relative to the round where that client simply never submitted —
    rejection == drop, bitwise. (A duplicated frame is the other half:
    == the round where it submitted once.)"""
    damaged = _one_payload_round(_sketch_session(), mutate=DAMAGE[kind])
    # the reference: the target client never submits at all
    reference = _one_payload_round(
        _sketch_session(), mutate=lambda f: None)
    np.testing.assert_array_equal(damaged, reference)


def test_duplicated_frame_is_counted_once_bitwise():
    duplicated = _one_payload_round(
        _sketch_session(), mutate=lambda f: [f, f])
    clean = _one_payload_round(_sketch_session(), mutate=None)
    np.testing.assert_array_equal(duplicated, clean)


def _one_payload_round_batched(session, mutate=None, target=2):
    """_one_payload_round's batched-gauntlet twin: every submission goes
    through ONE submit_block call (the worker-pool entry point), so the
    damaged frame sits INSIDE a vectorized validation block surrounded by
    clean neighbors. Returns committed params (flat)."""
    ids = session.sample_cohort(0)
    prep0 = session.prepare_served_round(
        0, ids, np.ones(len(ids), np.float32))
    tables, aux = session.compute_client_tables(prep0)
    q = IngestQueue(capacity=16, payload_policy=_policy())
    q.open_round(0, ids)
    asm = CohortAssembler(q, quorum=len(ids), deadline_s=10.0,
                          payload_shape=_PAYLOAD_SHAPE)
    subs = []
    for i, cid in enumerate(ids):
        payload = encode_frame(tables[i])
        if i == target and mutate is not None:
            sent = mutate(payload)
            for p in sent if isinstance(sent, list) else [sent]:
                if p is not None:
                    subs.append(Submission(int(cid), 0, 0.1, payload=p))
        else:
            subs.append(Submission(int(cid), 0, 0.1, payload=payload))
    statuses = q.submit_block(subs)
    assert len(statuses) == len(subs)
    closed = asm.close_virtual(0, ids)
    prep = session.finish_served_payload(
        prep0, closed.arrived, closed.tables, aux)
    session.commit_round(session.dispatch_round(prep, LR))
    return np.asarray(
        ravel_pytree(jax.device_get(session.state["params"]))[0])


@pytest.mark.parametrize("kind", sorted(DAMAGE))
def test_damaged_frame_inside_batched_block_rejects_only_itself(kind):
    """The batched gauntlet inherits the per-frame robustness property: a
    corrupted / truncated / stale / garbled / half-sent frame inside a
    validation BLOCK rejects only that submission — committed params are
    bitwise the round where that client never submitted, and its clean
    block-mates all land."""
    damaged = _one_payload_round_batched(
        _sketch_session(), mutate=DAMAGE[kind])
    reference = _one_payload_round_batched(
        _sketch_session(), mutate=lambda f: None)
    np.testing.assert_array_equal(damaged, reference)
    # and the batched path is bitwise the scalar path, damage and all
    scalar = _one_payload_round(_sketch_session(), mutate=DAMAGE[kind])
    np.testing.assert_array_equal(damaged, scalar)


def test_duplicated_frame_inside_batched_block_is_counted_once():
    duplicated = _one_payload_round_batched(
        _sketch_session(), mutate=lambda f: [f, f])
    clean = _one_payload_round_batched(_sketch_session(), mutate=None)
    np.testing.assert_array_equal(duplicated, clean)


def test_batched_block_screens_poison_against_quarantine_median():
    """The vectorized L2 screen reproduces the scalar quarantine verdict:
    a NaN table and an outlier-norm table inside one block both reject,
    their clean neighbors accept, with the same detail discipline."""
    q = IngestQueue(capacity=16, payload_policy=_policy(clip=2.0, median=1.0))
    q.open_round(0, [1, 2, 3, 4])
    nan_t = _table()
    nan_t[0, 0] = np.nan
    subs = [
        Submission(1, 0, 0.1, payload=encode_frame(_table(0.1))),
        Submission(2, 0, 0.1, payload=encode_frame(nan_t)),
        Submission(3, 0, 0.1, payload=encode_frame(_table(100.0))),
        Submission(4, 0, 0.1, payload=encode_frame(_table(0.2))),
    ]
    statuses = q.submit_block(subs)
    assert statuses == [ACCEPTED, QUARANTINED, QUARANTINED, ACCEPTED]
    c = q.counters()
    assert c["rejected_quarantined"] == 2
    assert c["accepted"] == 2


# --------------------------------------------- close_wall under concurrency


def test_close_wall_cut_excludes_arrivals_racing_the_drain():
    """Recv-order wall-clock cut: submissions ADMITTED between the wait's
    satisfaction and close_round's drain are stragglers, not survivors —
    the cut is decided on the snapshot the wait returned."""
    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2, 3, 4])
    asm = CohortAssembler(q, quorum=2, deadline_s=0.05)
    orig_wait = q.wait_for

    def racy_wait(count, timeout_s, rnd=None):
        q.submit(_sub(1))
        q.submit(_sub(2))
        snap = orig_wait(count, 0.0)
        # these land AFTER the wall-clock cut, BEFORE the drain
        q.submit(_sub(3))
        q.submit(_sub(4))
        return snap

    q.wait_for = racy_wait
    closed = asm.close_wall(0, [1, 2, 3, 4])
    assert closed.closed_by == "quorum"
    assert closed.arrived.tolist() == [1.0, 1.0, 0.0, 0.0]
    assert closed.stragglers == 2  # submitted, admitted, but past the cut


def test_close_wall_deadline_verdict_survives_racing_arrivals():
    """A deadline-expired wait must stay closed_by='deadline' even when
    late arrivals pile in during the wait->drain gap — they cannot
    retroactively make the round a quorum close."""
    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2, 3])
    asm = CohortAssembler(q, quorum=3, deadline_s=0.01)
    orig_wait = q.wait_for

    def racy_wait(count, timeout_s, rnd=None):
        q.submit(_sub(1))
        snap = orig_wait(count, 0.01)  # times out short of quorum
        q.submit(_sub(2))
        q.submit(_sub(3))
        return snap

    q.wait_for = racy_wait
    closed = asm.close_wall(0, [1, 2, 3])
    assert closed.closed_by == "deadline"
    assert closed.arrived.tolist() == [1.0, 0.0, 0.0]


def test_close_wall_under_socket_load_with_stragglers():
    """Satellite: the recv-order wall-clock cut under REAL concurrent
    socket connections carrying payload frames, with injected stragglers.
    Exactly the first `quorum` admitted clients survive, every survivor's
    validated table rides into the close, and the slow group never makes
    the cut."""
    import threading as th

    ids = list(range(12))
    q = IngestQueue(capacity=64, payload_policy=_policy())
    q.open_round(0, ids)
    t = SocketTransport(q)
    t.start()
    asm = CohortAssembler(q, quorum=6, deadline_s=10.0,
                          payload_shape=_PAYLOAD_SHAPE)
    import time as _time
    fast, slow = set(range(8)), set(range(8, 12))

    def client(cid):
        _time.sleep(0.02 if cid in fast else 1.2)  # injected stragglers
        try:
            submit_over_socket(t.address, Submission(
                cid, 0, latency_s=0.02, payload=_table(float(cid + 1))))
        except OSError:
            pass

    threads = [th.Thread(target=client, args=(cid,)) for cid in ids]
    try:
        for x in threads:
            x.start()
        closed = asm.close_wall(0, ids)
    finally:
        for x in threads:
            x.join()
        t.stop()
    assert closed.closed_by == "quorum"
    assert closed.survivors == 6
    survivors = {int(c) for c, a in zip(closed.invited, closed.arrived)
                 if a == 1.0}
    assert survivors <= fast, survivors  # recv order == the fast group
    # every survivor's VALIDATED table (and nobody else's) is in the stack
    for pos, cid in enumerate(closed.invited):
        expect = (_table(float(cid + 1)) if closed.arrived[pos] == 1.0
                  else np.zeros(_PAYLOAD_SHAPE, np.float32))
        np.testing.assert_array_equal(closed.tables[pos], expect)


# ------------------------------------------------------- transport hardening


def test_socket_read_deadline_disconnects_silent_peer():
    """Slow-loris defense: a peer that connects and never sends is
    disconnected when the read deadline lapses — its handler thread exits
    on its own, before any stop()."""
    import socket as sk
    import threading as th
    import time as _time

    q = IngestQueue(capacity=4)
    q.open_round(0, [1])
    t = SocketTransport(q, read_deadline_s=0.2)
    t.start()
    try:
        conn = sk.create_connection(t.address)
        deadline = _time.monotonic() + 3.0
        while _time.monotonic() < deadline:
            if not any(x.name == "serve-conn" and x.is_alive()
                       for x in th.enumerate()):
                break
            _time.sleep(0.05)
        else:
            raise AssertionError("silent peer's thread outlived the "
                                 "read deadline")
        conn.close()
    finally:
        t.stop()


def test_socket_max_frame_rejects_newline_less_flood():
    """Memory-bomb defense: a newline-less byte flood is cut off at the
    frame cap with a MALFORMED reply and a disconnect — per-connection
    memory stays bounded no matter what the peer sends."""
    import socket as sk

    q = IngestQueue(capacity=4)
    q.open_round(0, [1])
    t = SocketTransport(q, max_frame_bytes=2048)
    t.start()
    try:
        with sk.create_connection(t.address) as conn:
            conn.sendall(b"x" * 8192)  # no newline ever
            conn.settimeout(5.0)
            reply = b""
            while b"\n" not in reply:
                chunk = conn.recv(4096)
                if not chunk:
                    break
                reply += chunk
            assert b"MALFORMED" in reply, reply
            assert conn.recv(4096) == b""  # server hung up
    finally:
        t.stop()
    assert q.counters()["accepted"] == 0
    # a transport-decided MALFORMED still shows in the queue's counters —
    # the /metrics submissions block must see a byte-flood happening
    assert q.counters()["rejected_malformed"] == 1


def test_socket_stop_joins_half_open_and_mid_frame_connections():
    """Thread hygiene satellite: stop() force-closes live connections and
    joins EVERY per-connection thread within its deadline — including
    threads parked on abandoned half-open peers and mid-frame senders."""
    import socket as sk
    import threading as th

    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2])
    t = SocketTransport(q, read_deadline_s=30.0)  # deadline will NOT help
    t.start()
    conns = []
    try:
        for _ in range(3):
            conns.append(sk.create_connection(t.address))  # half-open
        conns[0].sendall(b'{"client_id": 1, ')  # mid-frame, never finished
        # a completed submission keeps one healthy connection around too
        assert submit_over_socket(
            t.address, Submission(2, 0, latency_s=0.1)) == ACCEPTED
    finally:
        t.stop(join_deadline_s=5.0)
        leaked = [x.name for x in th.enumerate()
                  if x.name.startswith("serve-") and x.is_alive()]
        assert not leaked, leaked
        for c in conns:
            c.close()


def test_abort_over_socket_is_a_no_show():
    """conn_drop realism: a connection that dies mid-send admits NOTHING —
    the partial frame never parses and the handler thread moves on."""
    q = IngestQueue(capacity=4, payload_policy=_policy())
    q.open_round(0, [1])
    t = SocketTransport(q)
    t.start()
    try:
        abort_over_socket(t.address, Submission(
            1, 0, latency_s=0.1, payload=_table()))
        assert q.counters()["accepted"] == 0
        # the same client can still submit for real afterwards
        assert submit_over_socket(t.address, Submission(
            1, 0, latency_s=0.2, payload=encode_frame(_table()))) == ACCEPTED
    finally:
        t.stop()


# ------------------------------------------- checkpoint resume (payload path)


@pytest.mark.chaos
def test_cli_serve_payload_preempt_resume_bit_identical(tiny_cv, tmp_path):
    """Checkpoint -> resume MID-SERVED-ROUND on the payload path: the
    --serve_payload sketch CLI run preempted by an injected SIGTERM resumes
    BIT-identical to the uninterrupted run — cohort stream, payload tables,
    requeue state and all."""
    flags = ("--serve", "inproc", "--serve_payload", "sketch",
             "--mode", "sketch", "--k", "16", "--num_cols", "256",
             "--num_rows", "3", "--serve_deadline", "2.0",
             "--num_rounds", "4")
    argv = [
        "--dataset", "cifar10", "--num_clients", "8", "--num_workers", "2",
        "--local_batch_size", "4", "--lr_scale", "0.05",
        "--weight_decay", "0", "--data_root", "/nonexistent", *flags,
    ]
    sa = cv_train.main(list(argv))  # uninterrupted reference

    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--checkpoint_every", "2",
             "--fault_plan", "preempt@2"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(list(argv) + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    sc = cv_train.main(list(argv) + chaos + ["--resume"])
    assert sc.round == 4
    _assert_params_equal(sa, sc)
    assert list(sa._requeue) == list(sc._requeue)
    assert sa._requeue_enqueued == sc._requeue_enqueued
