"""Pretrained-weight loader tests: logit parity against HuggingFace's torch
GPT-2 on a randomly initialised tiny checkpoint (no network needed — the
checkpoint is constructed in the test), plus vocab-resize / position-slice
semantics."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models.gpt2 import GPT2LMHead
from commefficient_tpu.models.gpt2_loader import load_hf_gpt2

VOCAB, POS, EMBD, LAYER, HEAD = 512, 128, 64, 2, 2


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """A tiny randomly-initialised HF GPT-2 checkpoint dir + the torch model."""
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel

    hf_cfg = HFConfig(
        vocab_size=VOCAB, n_positions=POS, n_embd=EMBD, n_layer=LAYER,
        n_head=HEAD, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    model = GPT2LMHeadModel(hf_cfg).eval()
    d = tmp_path_factory.mktemp("gpt2_ckpt")
    torch.save(model.state_dict(), d / "pytorch_model.bin")
    (d / "config.json").write_text(json.dumps({
        "n_head": HEAD, "n_layer": LAYER, "n_embd": EMBD,
        "layer_norm_epsilon": 1e-5,
    }))
    return d, model


def test_logit_parity_with_hf(hf_checkpoint):
    """The loaded flax model reproduces HF torch logits on random inputs —
    verifies every mapping choice at once (Conv1D orientation, qkv packing,
    ln eps, tied head, gelu variant)."""
    import torch

    ckpt_dir, hf_model = hf_checkpoint
    params, cfg = load_hf_gpt2(str(ckpt_dir))
    assert (cfg.vocab_size, cfg.n_positions, cfg.n_embd, cfg.n_layer, cfg.n_head) == (
        VOCAB, POS, EMBD, LAYER, HEAD
    )
    model = GPT2LMHead(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (2, 24))
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(ids), train=False))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=2e-4)


def test_logit_parity_with_token_types(hf_checkpoint):
    import torch

    ckpt_dir, hf_model = hf_checkpoint
    params, cfg = load_hf_gpt2(str(ckpt_dir))
    model = GPT2LMHead(cfg)
    rng = np.random.RandomState(1)
    ids = rng.randint(0, VOCAB, (1, 16))
    tt = rng.randint(0, VOCAB, (1, 16))
    ours = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids), train=False,
        token_type_ids=jnp.asarray(tt),
    ))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids), token_type_ids=torch.tensor(tt)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=2e-4)


def test_vocab_resize_appends_mean_rows(hf_checkpoint):
    ckpt_dir, _ = hf_checkpoint
    base_params, _ = load_hf_gpt2(str(ckpt_dir))
    params, cfg = load_hf_gpt2(str(ckpt_dir), target_vocab_size=VOCAB + 5)
    assert cfg.vocab_size == VOCAB + 5
    assert params["wte"].shape == (VOCAB + 5, EMBD)
    np.testing.assert_array_equal(
        np.asarray(params["wte"][:VOCAB]), np.asarray(base_params["wte"])
    )
    # new rows sit near the mean embedding, not at random scale
    mean = np.asarray(base_params["wte"]).mean(axis=0)
    dev = np.abs(np.asarray(params["wte"][VOCAB:]) - mean)
    assert dev.max() < 0.2
    # logits over the original vocab are unchanged for original-token inputs
    model = GPT2LMHead(cfg)
    ids = np.random.RandomState(2).randint(0, VOCAB, (1, 8))
    out = model.apply({"params": params}, jnp.asarray(ids), train=False)
    base_model = GPT2LMHead(dataclasses_replace_vocab(cfg, VOCAB))
    base_out = base_model.apply({"params": base_params}, jnp.asarray(ids), train=False)
    np.testing.assert_allclose(
        np.asarray(out[..., :VOCAB]), np.asarray(base_out), rtol=1e-5, atol=1e-5
    )


def dataclasses_replace_vocab(cfg, vocab):
    import dataclasses

    return dataclasses.replace(cfg, vocab_size=vocab)


def test_position_slice_and_errors(hf_checkpoint):
    ckpt_dir, _ = hf_checkpoint
    params, cfg = load_hf_gpt2(str(ckpt_dir), n_positions=32)
    assert cfg.n_positions == 32 and params["wpe"].shape == (32, EMBD)
    with pytest.raises(ValueError):
        load_hf_gpt2(str(ckpt_dir), target_vocab_size=VOCAB - 1)
    with pytest.raises(ValueError):
        load_hf_gpt2(str(ckpt_dir), n_positions=POS + 1)


def test_loaded_model_trains_one_round(hf_checkpoint):
    """The loaded tree plugs into the federated engine (tree structure and
    dtypes are engine-compatible, not just forward-compatible)."""
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine
    from commefficient_tpu.models.losses import make_lm_loss
    from commefficient_tpu.modes.config import ModeConfig

    ckpt_dir, _ = hf_checkpoint
    params, cfg = load_hf_gpt2(str(ckpt_dir), n_positions=16)
    model = GPT2LMHead(cfg)
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="uncompressed", d=d, momentum_type="none", error_type="none")
    ecfg = engine.EngineConfig(mode=mcfg)
    state = engine.init_server_state(ecfg, params, {})
    step = jax.jit(engine.make_round_step(make_lm_loss(model, train=True), ecfg))
    ids = jnp.ones((2, 3, 16), dtype=jnp.int32)
    batch = {"input_ids": ids, "labels": ids}
    new_state, _, metrics = step(state, batch, {}, jnp.float32(0.01), jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss_sum"]))
    flat_old = ravel_pytree(state["params"])[0]
    flat_new = ravel_pytree(new_state["params"])[0]
    assert not np.allclose(np.asarray(flat_old), np.asarray(flat_new))
