"""Native batch-assembly runtime: builds with g++, samples valid
without-replacement batches, matches the numpy fallback's semantics."""

import numpy as np

from commefficient_tpu import native
from commefficient_tpu.data.fed_dataset import FedDataset


def test_native_builds():
    assert native.available(), "g++ build of batch_assembly.cpp failed"


def _check_batch(ds, b, client_ids, batch_size):
    for wi, cid in enumerate(client_ids):
        shard = set(ds.client_indices[cid].tolist())
        k = int(b["mask"][wi].sum())
        assert k == min(len(shard), batch_size)
        # every sampled row is a row of this client's shard, no duplicates
        rows = [tuple(r.ravel().tolist()) for r in b["x"][wi][: k]]
        allowed = {tuple(ds.x[i].ravel().tolist()) for i in shard}
        assert set(rows) <= allowed
        assert len(set(rows)) == k  # without replacement (rows are unique here)
        # labels match their x rows
        for r, lab in zip(b["x"][wi][:k], b["y"][wi][:k]):
            src = int(r.ravel()[0])  # x rows constructed as unique ints
            assert ds.y[src] == lab


def test_sampling_validity_and_mask():
    n = 64
    x = np.arange(n, dtype=np.float32).reshape(n, 1)  # row i == [i]
    y = (np.arange(n) * 3 % 7).astype(np.int32)
    shards = [np.arange(0, 5), np.arange(5, 40), np.arange(40, 64)]
    ds = FedDataset(x, y, shards)
    rng = np.random.RandomState(0)
    b = ds.client_batch(rng, np.array([0, 1, 2]), batch_size=16)
    assert b["x"].shape == (3, 16, 1)
    _check_batch(ds, b, [0, 1, 2], 16)


def test_determinism_given_seed():
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.zeros(100, np.int32)
    ds = FedDataset(x, y, [np.arange(100)])
    b1 = ds.client_batch(np.random.RandomState(7), np.array([0]), 8)
    b2 = ds.client_batch(np.random.RandomState(7), np.array([0]), 8)
    np.testing.assert_array_equal(b1["x"], b2["x"])


def test_local_iters_axis():
    x = np.arange(30, dtype=np.float32).reshape(30, 1)
    ds = FedDataset(x, np.zeros(30, np.int32), [np.arange(30), np.arange(3)])
    b = ds.client_batch(np.random.RandomState(1), np.array([1, 0]), 4, local_iters=3)
    assert b["x"].shape == (2, 3, 4, 1)
    assert b["mask"][0].sum() == 9  # 3-example client x 3 iters
    assert b["mask"][1].sum() == 12
