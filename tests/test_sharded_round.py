"""The SPMD sharded round (ISSUE 3 tentpole): the CPU-mesh parity slice.

conftest forces an 8-device CPU mesh (XLA_FLAGS=
--xla_force_host_platform_device_count=8), so this whole file is the
forced-8-device tier-1 job slice — sharded-path regressions fail here, fast,
off-TPU (scripts/tier1_8dev.sh runs it standalone with the flags pinned
explicitly).

The bit-identity contract under test: client_shards=S is part of the round's
numerical contract (it fixes the fp summation order, like client_chunk), and
a given S produces IDENTICAL BITS on one device (the lax.map reference) and
on an S-way mesh (shard_map + all_gather ordered merge). Different shard
counts differ only at fp-reassociation level (allclose, pinned too).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated import engine
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.parallel import mesh as meshlib


def init_mlp(key, din=10, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (din, dh)) * 0.1,
        "b1": jnp.zeros(dh),
        "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
        "b2": jnp.zeros(dout),
    }


def mlp_loss(params, net_state, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits)
    per_ex = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1)[:, 0]
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    loss = (per_ex * mask).sum() / count
    return loss, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()},
    }


def _data(key, n, din=10, dout=4):
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (n, din))
    w_true = jax.random.normal(kw, (din, dout))
    return {"x": x, "y": (x @ w_true).argmax(-1), "mask": jnp.ones(n)}


SKETCH_KW = dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
                 hash_family="rotation", momentum_type="virtual",
                 error_type="virtual")

# >= 2 mode configs, deliberately exercising the full replicated tail:
# dropout + the compiled non-finite guard on the flagship sketch config, and
# DP clip+noise on the dense-wire control.
MODE_CASES = [
    ("sketch", dict(SKETCH_KW),
     dict(client_dropout=0.25, on_nonfinite="skip")),
    ("uncompressed_dp", dict(mode="uncompressed", momentum_type="virtual",
                             error_type="none"),
     dict(dp_clip=1.0, dp_noise=0.5, client_dropout=0.3)),
    ("true_topk_chunked", dict(mode="true_topk", k=24,
                               momentum_type="virtual", error_type="virtual"),
     dict(client_chunk=2)),
]


def _cfg(mode_kw, eng_kw, shards=8):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(**{**mode_kw, "d": d})
    cfg = engine.EngineConfig(mode=mcfg, weight_decay=5e-4,
                              client_shards=shards, **eng_kw)
    return params, cfg


def _flat(state):
    return np.asarray(ravel_pytree(state["params"])[0])


@pytest.mark.parametrize("name, mode_kw, eng_kw", MODE_CASES,
                         ids=[c[0] for c in MODE_CASES])
def test_sharded_mesh_bit_identical_to_single_device(name, mode_kw, eng_kw):
    """THE acceptance pin: the shard_map round on the 8-device mesh produces
    the same bits (params + every metric) as the same shard-structured
    program on one device, over multiple chained rounds. The server mode
    state is additionally pinned to last-bit tolerance: XLA:CPU's
    value-dependent vectorization of the identical per-shard subgraph
    differs between a while-loop body (the reference's lax.map) and the
    inlined shard_map body, leaving ~1e-9 on a handful of sketch-table
    entries — params and metrics still come out bit-equal, and everything
    structure-matched (hybrid vs flat mesh, split vs fused, block vs
    sequential, checkpoint resume) is pinned fully bitwise below."""
    mesh = meshlib.make_mesh(8)
    params, cfg = _cfg(mode_kw, eng_kw)
    W = 16
    data = _data(jax.random.PRNGKey(1), W * 4)
    batch = jax.tree.map(lambda a: a.reshape((W, 4) + a.shape[1:]), data)
    lr = jnp.float32(0.1)

    ref_step = jax.jit(engine.make_sharded_round_step(mlp_loss, cfg))
    mesh_step = jax.jit(engine.make_sharded_round_step(mlp_loss, cfg, mesh))
    s_ref = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_mesh = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    sharded_batch = meshlib.shard_client_batch(mesh, batch)
    for i in range(3):
        rng = jax.random.PRNGKey(100 + i)
        s_ref, _, m_ref = ref_step(s_ref, batch, {}, lr, rng)
        s_mesh, _, m_mesh = mesh_step(s_mesh, sharded_batch, {}, lr, rng)
        assert set(m_ref) == set(m_mesh)
        for k in m_ref:
            np.testing.assert_array_equal(np.asarray(m_ref[k]),
                                          np.asarray(m_mesh[k]), err_msg=k)
    np.testing.assert_array_equal(_flat(s_ref), _flat(s_mesh))
    for a, b in zip(jax.tree.leaves(s_ref["mode_state"]),
                    jax.tree.leaves(s_mesh["mode_state"])):
        # last-bit tolerance, not allclose-loose: see the docstring
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-7, atol=1e-8)


@pytest.mark.parametrize("name, mode_kw, eng_kw", MODE_CASES[:2],
                         ids=[c[0] for c in MODE_CASES[:2]])
def test_sharded_allclose_to_plain_round(name, mode_kw, eng_kw):
    """Across shard counts the round changes only by fp summation order: the
    S=8 sharded round stays allclose to the plain (S=1) round."""
    params, cfg = _cfg(mode_kw, eng_kw)
    cfg1 = dataclasses.replace(cfg, client_shards=1)
    W = 16
    data = _data(jax.random.PRNGKey(2), W * 4)
    batch = jax.tree.map(lambda a: a.reshape((W, 4) + a.shape[1:]), data)
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(7)

    sharded = jax.jit(engine.make_sharded_round_step(mlp_loss, cfg))
    plain = jax.jit(engine.make_round_step(mlp_loss, cfg1))
    s_s, _, m_s = sharded(
        engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {}),
        batch, {}, lr, rng)
    s_p, _, m_p = plain(
        engine.init_server_state(cfg1, jax.tree.map(jnp.copy, params), {}),
        batch, {}, lr, rng)
    np.testing.assert_allclose(_flat(s_s), _flat(s_p), rtol=1e-5, atol=1e-7)
    assert float(m_s["participants"]) == float(m_p["participants"])
    np.testing.assert_allclose(float(m_s["loss_sum"]), float(m_p["loss_sum"]),
                               rtol=1e-6)


def test_sharded_split_bit_identical_to_sharded_fused():
    """The Mosaic-isolating two-program sharded round (partials stay
    device-resident across the program boundary) equals the fused shard_map
    round bit-for-bit."""
    mesh = meshlib.make_mesh(8)
    params, cfg = _cfg(dict(SKETCH_KW), dict(client_dropout=0.25,
                                             on_nonfinite="skip"))
    W = 16
    data = _data(jax.random.PRNGKey(3), W * 4)
    batch = meshlib.shard_client_batch(
        mesh, jax.tree.map(lambda a: a.reshape((W, 4) + a.shape[1:]), data))
    lr = jnp.float32(0.1)

    fused = jax.jit(engine.make_sharded_round_step(mlp_loss, cfg, mesh))
    client_p, server_p = engine.make_sharded_split_round_step(
        mlp_loss, cfg, mesh)
    split = engine.compose_split(jax.jit(client_p), jax.jit(server_p))
    s_f = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    s_s = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    for i in range(3):
        rng = jax.random.PRNGKey(50 + i)
        s_f, _, m_f = fused(s_f, batch, {}, lr, rng)
        s_s, _, m_s = split(s_s, batch, {}, lr, rng)
        for k in m_f:
            np.testing.assert_array_equal(np.asarray(m_f[k]),
                                          np.asarray(m_s[k]), err_msg=k)
    np.testing.assert_array_equal(_flat(s_f), _flat(s_s))


def test_sharded_multi_round_block_matches_sequential():
    """The K-round fused block scans the SPMD body: bitwise equal to K
    sequential sharded dispatches."""
    mesh = meshlib.make_mesh(8)
    params, cfg = _cfg(dict(SKETCH_KW), {})
    K, W = 3, 8
    x = jax.random.normal(jax.random.PRNGKey(4), (K, W, 4, 10))
    w_true = jax.random.normal(jax.random.PRNGKey(5), (10, 4))
    batches = {"x": x, "y": (x @ w_true).argmax(-1),
               "mask": jnp.ones((K, W, 4))}
    lrs = jnp.asarray([0.1, 0.2, 0.05], jnp.float32)
    rngs = jax.random.split(jax.random.PRNGKey(6), K)

    step = jax.jit(engine.make_sharded_round_step(mlp_loss, cfg, mesh))
    st = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
    for i in range(K):
        b = meshlib.shard_client_batch(
            mesh, jax.tree.map(lambda a: a[i], batches))
        st, _, _ = step(st, b, {}, lrs[i], rngs[i])

    multi = jax.jit(engine.make_multi_round_step(mlp_loss, cfg, mesh))
    stm, ms = multi(
        engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {}),
        meshlib.shard_stacked_client_batch(mesh, batches), lrs, rngs)
    np.testing.assert_array_equal(_flat(st), _flat(stm))
    assert all(np.asarray(v).shape[0] == K for v in ms.values())


def test_sharded_scope_rejected_loudly():
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    for kw in (
        dict(mode="local_topk", d=d, k=8, momentum_type="none",
             error_type="local", num_clients=4),
        dict(mode="fedavg", d=d, num_local_iters=2, error_type="none",
             momentum_type="none"),
    ):
        cfg = engine.EngineConfig(mode=ModeConfig(**kw), client_shards=8)
        with pytest.raises(ValueError, match="sharded round supports"):
            engine.make_sharded_round_step(mlp_loss, cfg)
    # nonlinear partial wires can't merge by addition
    with pytest.raises(ValueError, match="nonlinear"):
        modes.merge_partial_wires(
            ModeConfig(mode="local_topk", d=d, k=8, momentum_type="none",
                       error_type="none"),
            {"idx": jnp.zeros((2, 8), jnp.int32),
             "vals": jnp.zeros((2, 8))},
        )
    with pytest.raises(ValueError, match="client_shards"):
        engine.EngineConfig(mode=ModeConfig(mode="uncompressed", d=d,
                                            momentum_type="none",
                                            error_type="none"),
                            client_shards=0)


# --------------------------------------------------------------- session


def _mlp_dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.normal(size=(n, 10)).astype(np.float32)
    y = rng.randint(0, 4, size=n).astype(np.int32)
    return FedDataset(x, y, shard_iid(n, 16, np.random.RandomState(1)))


def _session(mesh=None, client_shards=0, split=False, **kw):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
        params=jax.tree.map(jnp.copy, params), net_state={},
        mode_cfg=ModeConfig(**{**SKETCH_KW, "d": d}),
        train_set=_mlp_dataset(), num_workers=8, local_batch_size=2,
        seed=7, mesh=mesh, client_shards=client_shards, split_compile=split,
        **kw,
    )


def test_session_mesh_bit_identical_to_reference_session():
    """Session-level acceptance: run_round + the run_rounds fused block on
    the 8-way mesh session == the client_shards=8 single-device reference
    session, bit for bit — params, mode state, and every logged metric
    (comm accounting included)."""
    a = _session(mesh=meshlib.make_mesh(8))
    b = _session(client_shards=8)
    assert a.cfg.client_shards == b.cfg.client_shards == 8
    seq_a = [a.run_round(0.1), a.run_round(0.2)] + a.run_rounds([0.05, 0.1])
    seq_b = [b.run_round(0.1), b.run_round(0.2)] + b.run_rounds([0.05, 0.1])
    for ma, mb in zip(seq_a, seq_b):
        assert ma == mb
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(b.state["params"])[0]),
    )
    assert a.comm_mb_total == b.comm_mb_total


def test_session_split_mesh_matches_fused_mesh():
    a = _session(mesh=meshlib.make_mesh(8), split=False)
    b = _session(mesh=meshlib.make_mesh(8), split=True)
    for _ in range(2):
        assert a.run_round(0.1) == b.run_round(0.1)
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(b.state["params"])[0]),
    )


def test_session_hybrid_mesh_bit_identical_to_plain_mesh():
    """(slices, clients) DCN x ICI hybrid at the same total shard count:
    shard order is row-major over both axes, so the round is bit-identical
    to the flat 8-way mesh."""
    a = _session(mesh=meshlib.make_mesh(8))
    h = _session(mesh=meshlib.make_mesh(8, num_slices=2))
    assert a.run_round(0.1) == h.run_round(0.1)
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(h.state["params"])[0]),
    )


def test_session_rejects_client_shards_for_out_of_scope_mode():
    """An EXPLICIT client_shards request for a mode outside the sharded
    scope must fail loudly (mirroring the engine's scope check) — silently
    running the plain round would hand a parity test a different program."""
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    with pytest.raises(ValueError, match="sharded-round scope"):
        FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss, params=params,
            net_state={},
            mode_cfg=ModeConfig(mode="fedavg", d=d, momentum_type="none",
                                error_type="none", num_local_iters=2),
            train_set=_mlp_dataset(), num_workers=8, local_batch_size=2,
            client_shards=4,
        )


def test_session_rejects_client_shards_mesh_disagreement():
    """ANY explicit client_shards that disagrees with the mesh raises —
    including 1 ('force unsharded'), which must not silently compile the
    mesh's S-way program."""
    for shards in (1, 4):
        with pytest.raises(ValueError, match="disagrees"):
            _session(mesh=meshlib.make_mesh(8), client_shards=shards)


def test_session_out_of_scope_mode_keeps_gspmd_path():
    """local_topk with local error state is outside the SPMD scope: the
    session must keep the GSPMD path (client_shards stays 1) and still run."""
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    s = FederatedSession(
        train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss, params=params,
        net_state={},
        mode_cfg=ModeConfig(mode="local_topk", d=d, k=8,
                            momentum_type="none", error_type="local",
                            num_clients=16),
        train_set=_mlp_dataset(), num_workers=8, local_batch_size=2,
        seed=3, mesh=meshlib.make_mesh(8),
    )
    assert s.cfg.client_shards == 1 and not s._spmd
    assert np.isfinite(s.run_round(0.1)["loss_sum"])


def test_sharded_checkpoint_resume_bit_identical(tmp_path):
    """Checkpoint+resume mid-run ON THE SHARDED PATH: 2 rounds, save, fresh
    mesh session restores, 2 more rounds — bit-identical to 4 uninterrupted
    sharded rounds (params + metrics), so preemption recovery and the SPMD
    round compose."""
    from commefficient_tpu.utils import checkpoint as ckpt

    ckpt_dir = str(tmp_path / "ck")
    lrs = [0.1, 0.2, 0.05, 0.1]
    a = _session(mesh=meshlib.make_mesh(8), donate_state=False)
    straight = [a.run_round(lr) for lr in lrs]

    b = _session(mesh=meshlib.make_mesh(8), donate_state=False)
    first = [b.run_round(lr) for lr in lrs[:2]]
    ckpt.save(ckpt_dir, b)

    c = _session(mesh=meshlib.make_mesh(8), donate_state=False)
    assert ckpt.restore_latest(ckpt_dir, c)
    assert c.round == 2
    resumed = first + [c.run_round(lr) for lr in lrs[2:]]
    for ma, mb in zip(straight, resumed):
        assert ma == mb
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(c.state["params"])[0]),
    )


# ------------------------------------------------- mesh spec + autotune


def test_parse_mesh_spec():
    assert meshlib.parse_mesh_spec("clients=8") == {"clients": 8, "slices": 1}
    assert meshlib.parse_mesh_spec("clients=4,slices=2") == {
        "clients": 4, "slices": 2}
    for bad in ("", "clients", "clients=0", "clients=4,model=2", "slices=2",
                "clients=x", "clients=8,clients=4"):
        with pytest.raises(ValueError):
            meshlib.parse_mesh_spec(bad)


def test_make_mesh_from_spec():
    m = meshlib.make_mesh_from_spec("clients=4,slices=2")
    assert meshlib.client_shards(m) == 8
    assert dict(m.shape) == {meshlib.DCN_AXIS: 2, meshlib.CLIENT_AXIS: 4}
    with pytest.raises(ValueError, match="devices"):
        meshlib.make_mesh_from_spec("clients=1024")


def test_merge_comm_bytes_headline():
    """The comm-efficiency arithmetic bench.py's mesh section records: at
    flagship dims the dense all-reduce costs ~d/(r*c) more than the sketch
    merge."""
    c = meshlib.merge_comm_bytes(8, r=5, c=500_000, d=6_500_000)
    assert c["dense_over_sketch_ratio"] == pytest.approx(2.6)
    assert c["sketch_table_mb"] == pytest.approx(10.0)
    assert (c["dense_allreduce_mb_per_device"]
            > c["sketch_psum_mb_per_device"])


def test_auto_inflight_policy():
    from commefficient_tpu.runner import auto_inflight

    # local backend: sub-ms RTT stays at the floor
    assert auto_inflight(0.1, 50.0) == 2
    # tunnelled TPU: 70 ms RTT over a 50 ms round wants a deep chain
    assert auto_inflight(70.0, 50.0) == 14
    # clamped at the preemption-grace ceiling
    assert auto_inflight(500.0, 1.0) == 16
    # no round timed yet: the historical default
    assert auto_inflight(70.0, 0.0) == 4


def test_merge_tables_shape_guard():
    from commefficient_tpu.sketch import csvec

    spec = csvec.CSVecSpec(d=100, c=16, r=3, family="rotation")
    stacked = jnp.ones((4, 3, 16))
    np.testing.assert_array_equal(
        np.asarray(csvec.merge_tables(spec, stacked)), np.full((3, 16), 4.0))
    with pytest.raises(ValueError, match="stacked partial tables"):
        csvec.merge_tables(spec, jnp.ones((3, 16)))
