"""Checkpoint/resume: a restored session continues bit-for-bit like the
uninterrupted run (params, mode state, round counter, host sampling RNG)."""

import numpy as np
import pytest

import cv_train
from commefficient_tpu.utils import checkpoint as ckpt
from commefficient_tpu.utils.config import make_parser, resolve_defaults


def _args(tmp, extra=()):
    argv = [
        "--dataset", "cifar10", "--mode", "sketch", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--k", "100",
        "--num_cols", "2000", "--num_rows", "3", "--lr_scale", "0.05",
        "--data_root", "/nonexistent", *extra,
    ]
    return resolve_defaults(make_parser("cv").parse_args(argv))


@pytest.fixture()
def small_session(tmp_path, monkeypatch):
    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)
    return tmp_path


def test_save_restore_resume_equivalence(small_session, tmp_path):
    args = _args(tmp_path)
    # run A: 6 uninterrupted rounds
    sa, _ = cv_train.build(args)
    for i in range(6):
        sa.run_round(0.05)
    # run B: 3 rounds, checkpoint, fresh session, restore, 3 more
    sb, _ = cv_train.build(_args(tmp_path))
    for i in range(3):
        sb.run_round(0.05)
    path = ckpt.save(str(tmp_path / "ck"), sb)
    sc, _ = cv_train.build(_args(tmp_path))
    ckpt.restore(path, sc)
    assert sc.round == 3
    for i in range(3):
        sc.run_round(0.05)

    import jax

    for a, b in zip(jax.tree.leaves(sa.state["params"]), jax.tree.leaves(sc.state["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    for a, b in zip(
        jax.tree.leaves(sa.state["mode_state"]), jax.tree.leaves(sc.state["mode_state"])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_latest_and_prune(small_session, tmp_path):
    args = _args(tmp_path)
    s, _ = cv_train.build(args)
    paths = []
    for i in range(5):
        s.run_round(0.05)
        paths.append(ckpt.save(str(tmp_path / "ck"), s, keep=2))
    import os

    remaining = sorted(os.listdir(tmp_path / "ck"))
    assert len(remaining) == 2
    assert ckpt.latest(str(tmp_path / "ck")).endswith(remaining[-1])
