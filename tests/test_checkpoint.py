"""Checkpoint/resume: a restored session continues bit-for-bit like the
uninterrupted run (params, mode state, round counter, host sampling RNG)."""

import numpy as np
import pytest

import cv_train
from commefficient_tpu.utils import checkpoint as ckpt
from commefficient_tpu.utils.config import make_parser, resolve_defaults


def _args(tmp, extra=()):
    argv = [
        "--dataset", "cifar10", "--mode", "sketch", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--k", "100",
        "--num_cols", "2000", "--num_rows", "3", "--lr_scale", "0.05",
        "--data_root", "/nonexistent", *extra,
    ]
    return resolve_defaults(make_parser("cv").parse_args(argv))


@pytest.fixture()
def small_session(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    # checkpoint logic is model-agnostic; a 2-layer MLP compiles in seconds
    # where ResNet-9 takes ~40-80 s per session on this 1-core box (the
    # real model's CLI path is covered by test_determinism/test_golden)
    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


def test_save_restore_resume_equivalence(small_session, tmp_path):
    args = _args(tmp_path)
    # run A: 6 uninterrupted rounds
    sa, _ = cv_train.build(args)
    for i in range(6):
        sa.run_round(0.05)
    # run B: 3 rounds, checkpoint, fresh session, restore, 3 more
    sb, _ = cv_train.build(_args(tmp_path))
    for i in range(3):
        sb.run_round(0.05)
    path = ckpt.save(str(tmp_path / "ck"), sb)
    sc, _ = cv_train.build(_args(tmp_path))
    ckpt.restore(path, sc)
    assert sc.round == 3
    for i in range(3):
        sc.run_round(0.05)

    import jax

    for a, b in zip(jax.tree.leaves(sa.state["params"]), jax.tree.leaves(sc.state["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    for a, b in zip(
        jax.tree.leaves(sa.state["mode_state"]), jax.tree.leaves(sc.state["mode_state"])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_comm_mb_total_checkpointed_under_dropout(small_session, tmp_path):
    """Cumulative communication is MEASURED (survivor-scaled under dropout),
    so a resumed run must restore the measured sum — deriving it as
    round * static-per-round-estimate overstates it (ADVICE r3)."""
    args = _args(tmp_path, extra=("--client_dropout", "0.5"))
    s, _ = cv_train.build(args)
    measured = 0.0
    dropped_any = False
    for _ in range(6):
        m = s.run_round(0.05)
        measured += m["comm_total_mb"]
        dropped_any = dropped_any or m["participants"] < s.num_workers
    assert dropped_any  # the seed produces at least one non-full round
    assert s.comm_mb_total == pytest.approx(measured)
    static = s.round * s.comm_per_round["comm_total_mb"]
    assert s.comm_mb_total < static  # the distinction is non-trivial here

    path = ckpt.save(str(tmp_path / "ck"), s)
    s2, _ = cv_train.build(_args(tmp_path, extra=("--client_dropout", "0.5")))
    ckpt.restore(path, s2)
    assert s2.comm_mb_total == pytest.approx(measured)
    # and it keeps accumulating measured figures after resume
    m = s2.run_round(0.05)
    assert s2.comm_mb_total == pytest.approx(measured + m["comm_total_mb"])


def test_cohort_size_change_across_checkpoint_warns(small_session, tmp_path, capsys):
    """Restoring into a session with a different num_workers (mesh rounding
    or a flag change) silently breaks exact client-sequence replay — the
    restore must say so loudly."""
    s, _ = cv_train.build(_args(tmp_path))
    s.run_round(0.05)
    path = ckpt.save(str(tmp_path / "ck"), s)
    # the 8-way mesh rounds every cohort to a multiple of 8; 32 clients with
    # --num_workers 16 stays 16, vs the saved session's 8
    s2, _ = cv_train.build(
        _args(tmp_path, extra=("--num_clients", "32", "--num_workers", "16"))
    )
    capsys.readouterr()
    ckpt.restore(path, s2)
    assert "will NOT replay" in capsys.readouterr().out
    # same cohort: no warning
    s3, _ = cv_train.build(_args(tmp_path))
    capsys.readouterr()
    ckpt.restore(path, s3)
    assert "will NOT replay" not in capsys.readouterr().out


def test_latest_and_prune(small_session, tmp_path):
    args = _args(tmp_path)
    s, _ = cv_train.build(args)
    paths = []
    for i in range(5):
        s.run_round(0.05)
        paths.append(ckpt.save(str(tmp_path / "ck"), s, keep=2))
    import os

    remaining = sorted(os.listdir(tmp_path / "ck"))
    assert len(remaining) == 2
    assert ckpt.latest(str(tmp_path / "ck")).endswith(remaining[-1])


def test_restore_via_relative_checkpoint_dir(small_session, tmp_path, monkeypatch):
    """`--checkpoint_dir ck` (relative, as every CLI example uses): orbax's
    tensorstore rejects relative paths at RESTORE time while save() abspaths,
    so latest() must return an absolute path — the asymmetry let a run save
    for hours and then crash the --resume (observed round 4, session 3)."""
    import os

    args = _args(tmp_path)
    s, _ = cv_train.build(args)
    for _ in range(2):
        s.run_round(0.05)
    monkeypatch.chdir(tmp_path)
    ckpt.save("ck_rel", s)
    path = ckpt.latest("ck_rel")
    assert os.path.isabs(path), path
    s2, _ = cv_train.build(_args(tmp_path))
    ckpt.restore(path, s2)  # raised ValueError before the fix
    assert s2.round == s.round
    np.testing.assert_array_equal(
        np.asarray(s2.state["round"]), np.asarray(s.state["round"])
    )


def test_save_readback_catches_silent_bitrot(small_session, tmp_path, monkeypatch):
    """Manifest verification on SAVE: media that acknowledges a write and
    stores different bytes must fail the save LOUDLY (counted, raised inside
    the retry wrapper) — not surface hours later at restore when the damaged
    checkpoint is the only copy. A transient bitrot recovers via the
    re-write; persistent bitrot exhausts retries and raises."""
    from commefficient_tpu.resilience import FaultPlan, RetryPolicy

    s, _ = cv_train.build(_args(tmp_path))
    s.run_round(0.05)

    real_manifest = ckpt._write_manifest
    lies = {"left": 1}

    def lying_media(path):
        # manifest records the TRUE hashes; then the 'media' flips a byte of
        # the largest staged file — exactly what the post-commit read-back
        # exists to catch (write-path corruption under an intact manifest)
        real_manifest(path)
        if lies["left"] > 0:
            lies["left"] -= 1
            target = FaultPlan._largest_data_file(path)
            with open(target, "r+b") as f:
                b = f.read(1)
                f.seek(0)
                f.write(bytes([b[0] ^ 0xFF]))

    monkeypatch.setattr(ckpt, "_write_manifest", lying_media)
    before = ckpt.save_verify_failures()
    path = ckpt.save(str(tmp_path / "ck"), s,
                     retry_policy=RetryPolicy(max_retries=2, base_delay_s=0.001))
    assert ckpt.save_verify_failures() == before + 1  # counted in metrics
    assert ckpt.verify(path) is True  # the retry re-wrote a clean copy

    lies["left"] = 99  # persistent bitrot: every attempt fails, loudly
    with pytest.raises(ckpt.CheckpointVerifyError):
        ckpt.save(str(tmp_path / "ck2"), s,
                  retry_policy=RetryPolicy(max_retries=1, base_delay_s=0.001))
    assert ckpt.save_verify_failures() == before + 3

    # a corrupt RE-SAVE of an already-checkpointed round must put the
    # displaced verified-good copy back, never destroy it
    with pytest.raises(ckpt.CheckpointVerifyError):
        ckpt.save(str(tmp_path / "ck"), s,
                  retry_policy=RetryPolicy(max_retries=0))
    assert ckpt.verify(path) is True  # the good round survived the attempt

    # the opt-out keeps the old (unverified) save behavior
    lies["left"] = 1
    p3 = ckpt.save(str(tmp_path / "ck3"), s,
                   retry_policy=RetryPolicy(max_retries=0),
                   verify_on_save=False)
    assert ckpt.verify(p3) is False  # damage committed silently, as opted


def test_cifar100_build_path_round(small_session, tmp_path):
    """--dataset cifar100 through the full cv_train build path (the parser
    offered the choice with nothing behind it until round 4); loader-level
    100-class assertions live in test_data.py::test_cifar100_loader."""
    args = _args(tmp_path, extra=("--dataset", "cifar100"))
    s, _ = cv_train.build(args)
    m = s.run_round(0.05)
    assert np.isfinite(m["loss_sum"]) and m["count"] > 0
