"""Data-layer tests: shard protocols, fixed-shape batch assembly, masking,
and the transfer-learning-conv-ai dialog packing."""

import os

import numpy as np

from commefficient_tpu.data.cifar import load_cifar_fed
from commefficient_tpu.data.fed_dataset import FedDataset, shard_by_label, shard_iid
from commefficient_tpu.data.femnist import load_femnist_fed
from commefficient_tpu.data.personachat import (
    build_input_from_segments,
    load_personachat_fed,
    pack_example,
)
from commefficient_tpu.utils.tokenizer import ByteTokenizer

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def test_shard_by_label_noniid():
    labels = np.random.RandomState(0).permutation(np.repeat(np.arange(10), 50))
    shards = shard_by_label(labels, 100)  # 500 examples -> 100 shards of 5
    assert len(shards) == 100
    all_idx = np.concatenate(shards)
    assert len(all_idx) == 500 and len(set(all_idx.tolist())) == 500
    # sort-by-label: only shards straddling a class boundary can be mixed
    single = sum(1 for s in shards if len(set(labels[s].tolist())) == 1)
    assert single >= 90


def test_shard_iid_partition():
    shards = shard_iid(100, 7, np.random.RandomState(0))
    assert len(np.concatenate(shards)) == 100
    assert len(set(np.concatenate(shards).tolist())) == 100


def test_client_batch_shapes_and_mask():
    x = np.arange(20, dtype=np.float32).reshape(20, 1)
    y = np.arange(20, dtype=np.int32)
    ds = FedDataset(x, y, [np.arange(3), np.arange(3, 20)])  # tiny + big client
    rng = np.random.RandomState(0)
    b = ds.client_batch(rng, np.array([0, 1]), batch_size=8)
    assert b["x"].shape == (2, 8, 1) and b["mask"].shape == (2, 8)
    assert b["mask"][0].sum() == 3  # small client padded
    assert b["mask"][1].sum() == 8
    # padded slots contribute nothing: y is 0 there but mask is 0
    b5 = ds.client_batch(rng, np.array([0]), batch_size=4, local_iters=5)
    assert b5["x"].shape == (1, 5, 4, 1) and b5["mask"].sum() == 15  # 3 x 5


def test_eval_batches_cover_everything_once():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    ds = FedDataset(x, np.zeros(10, np.int32), [np.arange(10)])
    seen = 0.0
    for b in ds.eval_batches(4):
        seen += b["mask"].sum()
    assert seen == 10


def test_prefetch_iter_preserves_values_order_and_errors():
    """The eval-loader overlap helper (runner tentpole): identical items in
    identical order, producer exceptions re-raised at the consuming point,
    and the producer thread stopped when the consumer abandons early."""
    import threading
    import time

    import pytest

    from commefficient_tpu.data.fed_dataset import prefetch_iter

    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    ds = FedDataset(x, np.zeros(10, np.int32), [np.arange(10)])
    plain = list(ds.eval_batches(4))
    fetched = list(prefetch_iter(ds.eval_batches(4), depth=2))
    assert len(plain) == len(fetched)
    for a, b in zip(plain, fetched):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    # depth <= 0 degrades to plain iteration
    assert len(list(prefetch_iter(ds.eval_batches(4), depth=0))) == len(plain)

    def boom():
        yield 1
        raise ValueError("loader died")

    it = prefetch_iter(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="loader died"):
        next(it)

    # abandoning the generator stops the producer (no thread leak)
    before = threading.active_count()
    g = prefetch_iter(iter(range(1000)), depth=1)
    assert next(g) == 0
    g.close()
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_cifar_synthetic_fallback():
    train, test, nc = load_cifar_fed("cifar10", num_clients=50, iid=False,
                                     data_root="/nonexistent", synthetic_train=500,
                                     synthetic_test=100)
    assert nc == 10 and train.num_clients == 50
    assert train.x.shape[1:] == (32, 32, 3)


def test_femnist_synthetic_fallback():
    train, test, nc = load_femnist_fed("/nonexistent", num_clients=20)
    assert nc == 62 and train.num_clients == 20
    # per-writer class skew: each client uses <= 8 classes
    for s in train.client_indices[:5]:
        assert len(set(train.y[s].tolist())) <= 8


def test_cifar_fixture_pickles():
    """Real-file loader path over checked-in tiny pickle batches: 5 train
    batches + test batch, CHW->HWC transpose, mean/std normalisation."""
    from commefficient_tpu.data.cifar import CIFAR10_MEAN, CIFAR10_STD

    train, test, nc = load_cifar_fed(
        "cifar10", num_clients=2, iid=True, data_root=os.path.join(FIXTURES, "cifar")
    )
    assert nc == 10
    assert train.x.shape == (10, 32, 32, 3) and test.x.shape == (2, 32, 32, 3)
    assert train.x.dtype == np.float32
    # labels concatenated in batch order
    assert sorted(train.y.tolist()) == list(range(10))
    # normalisation applied: uint8/255 range maps into ~(-mean/std, (1-mean)/std)
    lo, hi = (-CIFAR10_MEAN / CIFAR10_STD).min(), ((1 - CIFAR10_MEAN) / CIFAR10_STD).max()
    assert train.x.min() >= lo - 1e-5 and train.x.max() <= hi + 1e-5
    assert train.num_clients == 2


def test_femnist_fixture_leaf_json():
    """Real-file LEAF loader over a checked-in 2-writer json: per-writer
    shards, 28x28x1 reshape, per-user test holdout."""
    train, test, nc = load_femnist_fed(FIXTURES)
    assert nc == 62
    # 7 examples total, 1 held out per writer -> 5 train, 2 test
    assert len(train.x) == len(test.x) == 7  # shared arrays, index shards
    assert train.x.shape[1:] == (28, 28, 1)
    assert train.num_clients == 2
    assert sum(len(s) for s in train.client_indices) == 5
    assert len(test.client_indices[0]) == 2
    # writer_a's favoured label dominates its shard
    ya = train.y[train.client_indices[0]]
    assert (ya == 3).sum() >= len(ya) - 1


def test_personachat_synthetic_fallback():
    train, valid, tok = load_personachat_fed("/nonexistent", num_clients=30, seq_len=64)
    assert train.num_clients == 30
    b = train.client_batch(np.random.RandomState(0), np.array([0, 1]), 2)
    assert b["input_ids"].shape == (2, 2, 64)
    assert b["token_type_ids"].shape == (2, 2, 64)
    assert b["labels"].min() >= -100
    # padding masked
    assert (b["labels"] == -100).any()


def test_build_input_from_segments_structure():
    """The lineage recipe: <bos> persona, speaker-prefixed turns alternating
    so the reply is <speaker2>; token types = segment speaker; labels only on
    reply tokens + eos."""
    tok = ByteTokenizer()
    persona = [tok.encode("i like cats")]
    history = [tok.encode("hi"), tok.encode("hello")]
    reply = tok.encode("meow")
    inst = build_input_from_segments(persona, history, reply, tok)
    ids, types, labels = inst["input_ids"], inst["token_type_ids"], inst["lm_labels"]
    assert len(ids) == len(types) == len(labels)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    # persona segment: bos + persona tokens, typed speaker2
    p_len = 1 + len(persona[0])
    assert types[:p_len] == [tok.speaker2_id] * p_len
    # two history turns: with the reply at speaker2, they alternate s2, s1
    h0 = p_len
    assert ids[h0] == tok.speaker2_id
    h1 = h0 + 1 + len(history[0])
    assert ids[h1] == tok.speaker1_id
    # reply segment: speaker token masked, reply + eos are the targets
    r0 = h1 + 1 + len(history[1])
    assert ids[r0] == tok.speaker2_id
    assert labels[: r0 + 1] == [-100] * (r0 + 1)
    assert labels[r0 + 1:] == reply + [tok.eos_id]
    assert types[r0:] == [tok.speaker2_id] * (len(ids) - r0)
    assert inst["mc_token_ids"] == len(ids) - 1


def test_pack_example_overflow_drops_history_keeps_reply():
    tok = ByteTokenizer()
    persona = [tok.encode("persona here")]
    history = [tok.encode("x" * 30) for _ in range(6)]
    reply = tok.encode("final answer")
    T = 64
    x, t, y = pack_example(persona, history, reply, tok, T)
    assert x.shape == (T,) and t.shape == (T,) and y.shape == (T,)
    # the reply survives verbatim at the labeled positions
    labeled = y[y != -100]
    assert labeled.tolist() == reply + [tok.eos_id]
    # sequence still starts with bos + persona
    assert x[0] == tok.bos_id
    assert x[1: 1 + len(persona[0])].tolist() == persona[0]


def test_personachat_fixture_file():
    """Real-file loader path over the checked-in tiny json: persona grouping
    merges dialogs that share a persona; valid split is separate; packing is
    the build_input_from_segments layout."""
    train, valid, tok = load_personachat_fed(FIXTURES, seq_len=96)
    # 3 train dialogs over 2 distinct personas -> 2 clients
    assert train.num_clients == 2
    # persona "i like cats/farm" has 2 dialogs with 2+1 utterances
    assert [len(s) for s in train.client_indices] == [3, 1]
    assert valid.num_clients == 1
    b = train.client_batch(np.random.RandomState(0), np.array([0]), 2)
    ids, types, labels = b["input_ids"][0, 0], b["token_type_ids"][0, 0], b["labels"][0, 0]
    assert ids[0] == tok.bos_id
    # gold reply is candidates[-1]; its tokens appear as labels
    labeled = labels[labels != -100]
    assert tok.eos_id in labeled.tolist()
    assert set(np.asarray(types).tolist()) <= {
        tok.speaker1_id, tok.speaker2_id, tok.pad_id
    }
    # eval path too
    ev = next(valid.eval_batches(2))
    assert ev["input_ids"].shape == (2, 96) and ev["token_type_ids"].shape == (2, 96)


def test_synthetic_separation_controls_bayes_accuracy():
    """--synthetic_separation: at the default the synthetic CIFAR task is
    trivially separable; at 0.025 the Bayes-optimal (nearest-prototype)
    accuracy sits near 0.86, giving accuracy-vs-comm curves headroom
    (results/README.md)."""
    from commefficient_tpu.data.cifar import _synthetic

    def bayes(sep):
        xtr, ytr, xte, yte = _synthetic(2000, 3000, 10, seed=0, separation=sep)
        # classify with the exact Bayes rule (empirical class-mean
        # estimates are either self-inclusion-biased or estimation-noise-
        # dominated at this separation scale)
        from commefficient_tpu.data.cifar import _prototypes

        protos = _prototypes(np.random.RandomState(0), 10, sep)
        X = xte.reshape(len(xte), -1)
        P = protos.reshape(10, -1)
        d2 = (X**2).sum(1)[:, None] - 2 * X @ P.T + (P**2).sum(1)[None]
        return float((d2.argmin(1) == yte).mean())

    assert bayes(1.0) > 0.99
    hard = bayes(0.025)
    assert 0.70 < hard < 0.95, hard


def test_cifar100_loader():
    """--dataset cifar100 (SURVEY.md §2 L0a: "CIFAR10/100"): the synthetic
    fallback really is 100-class. The full cv_train round on this dataset is
    covered in test_checkpoint.py::test_cifar100_build_path_round."""
    train, test, num_classes = load_cifar_fed(
        "cifar100", num_clients=20, iid=False, data_root="/nonexistent",
        synthetic_train=200, synthetic_test=100)
    assert num_classes == 100
    assert train.y.max() < 100 and len(np.unique(train.y)) > 10
    assert train.num_clients == 20
