"""Data-layer tests: shard protocols, fixed-shape batch assembly, masking."""

import numpy as np

from commefficient_tpu.data.cifar import load_cifar_fed
from commefficient_tpu.data.fed_dataset import FedDataset, shard_by_label, shard_iid
from commefficient_tpu.data.femnist import load_femnist_fed
from commefficient_tpu.data.personachat import load_personachat_fed


def test_shard_by_label_noniid():
    labels = np.random.RandomState(0).permutation(np.repeat(np.arange(10), 50))
    shards = shard_by_label(labels, 100)  # 500 examples -> 100 shards of 5
    assert len(shards) == 100
    all_idx = np.concatenate(shards)
    assert len(all_idx) == 500 and len(set(all_idx.tolist())) == 500
    # sort-by-label: only shards straddling a class boundary can be mixed
    single = sum(1 for s in shards if len(set(labels[s].tolist())) == 1)
    assert single >= 90


def test_shard_iid_partition():
    shards = shard_iid(100, 7, np.random.RandomState(0))
    assert len(np.concatenate(shards)) == 100
    assert len(set(np.concatenate(shards).tolist())) == 100


def test_client_batch_shapes_and_mask():
    x = np.arange(20, dtype=np.float32).reshape(20, 1)
    y = np.arange(20, dtype=np.int32)
    ds = FedDataset(x, y, [np.arange(3), np.arange(3, 20)])  # tiny + big client
    rng = np.random.RandomState(0)
    b = ds.client_batch(rng, np.array([0, 1]), batch_size=8)
    assert b["x"].shape == (2, 8, 1) and b["mask"].shape == (2, 8)
    assert b["mask"][0].sum() == 3  # small client padded
    assert b["mask"][1].sum() == 8
    # padded slots contribute nothing: y is 0 there but mask is 0
    b5 = ds.client_batch(rng, np.array([0]), batch_size=4, local_iters=5)
    assert b5["x"].shape == (1, 5, 4, 1) and b5["mask"].sum() == 15  # 3 x 5


def test_eval_batches_cover_everything_once():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    ds = FedDataset(x, np.zeros(10, np.int32), [np.arange(10)])
    seen = 0.0
    for b in ds.eval_batches(4):
        seen += b["mask"].sum()
    assert seen == 10


def test_cifar_synthetic_fallback():
    train, test, nc = load_cifar_fed("cifar10", num_clients=50, iid=False,
                                     data_root="/nonexistent", synthetic_train=500,
                                     synthetic_test=100)
    assert nc == 10 and train.num_clients == 50
    assert train.x.shape[1:] == (32, 32, 3)


def test_femnist_synthetic_fallback():
    train, test, nc = load_femnist_fed("/nonexistent", num_clients=20)
    assert nc == 62 and train.num_clients == 20
    # per-writer class skew: each client uses <= 8 classes
    for s in train.client_indices[:5]:
        assert len(set(train.y[s].tolist())) <= 8


def test_personachat_synthetic_fallback():
    train, valid, tok = load_personachat_fed("/nonexistent", num_clients=30, seq_len=64)
    assert train.num_clients == 30
    b = train.client_batch(np.random.RandomState(0), np.array([0, 1]), 2)
    assert b["input_ids"].shape == (2, 2, 64)
    assert b["labels"].min() >= -100
    # padding masked
    assert (b["labels"] == -100).any()
