"""Ring attention == dense causal attention, with the seq axis sharded over
the 8-device CPU mesh (the long-context path's correctness oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from commefficient_tpu.ops.ring_attention import (
    _dense_causal,
    ring_attention,
    use_ring_mesh,
)


def _qkv(key, B=2, T=64, H=4, D=16):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype=jnp.float32) for k in ks)


def test_fallback_matches_reference_softmax():
    q, k, v = _qkv(0)
    out = ring_attention(q, k, v)
    ref = _dense_causal(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_matches_dense_over_mesh():
    q, k, v = _qkv(1)
    ref = _dense_causal(q, k, v)
    for n in (2, 4, 8):
        mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
        with use_ring_mesh(mesh):
            out = ring_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
            err_msg=f"ring_size={n}",
        )


def test_ring_under_jit():
    q, k, v = _qkv(2)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    with use_ring_mesh(mesh):
        out = jax.jit(ring_attention)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_causal(q, k, v)), rtol=2e-4, atol=2e-4
    )
