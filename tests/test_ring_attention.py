"""Ring attention == dense causal attention, with the seq axis sharded over
the 8-device CPU mesh (the long-context path's correctness oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from commefficient_tpu.ops.ring_attention import (
    _dense_causal,
    ring_attention,
    use_ring_mesh,
)


def _qkv(key, B=2, T=64, H=4, D=16):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype=jnp.float32) for k in ks)


def test_fallback_matches_reference_softmax():
    q, k, v = _qkv(0)
    out = ring_attention(q, k, v)
    ref = _dense_causal(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_matches_dense_over_mesh():
    q, k, v = _qkv(1)
    ref = _dense_causal(q, k, v)
    for n in (2, 4, 8):
        mesh = Mesh(np.array(jax.devices()[:n]), ("seq",))
        with use_ring_mesh(mesh):
            out = ring_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
            err_msg=f"ring_size={n}",
        )


def test_ring_under_jit():
    q, k, v = _qkv(2)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    with use_ring_mesh(mesh):
        out = jax.jit(ring_attention)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_causal(q, k, v)), rtol=2e-4, atol=2e-4
    )


def test_ring_inside_federated_round_matches_dense():
    """VERDICT r2 #8: ring attention INSIDE a federated GPT-2 round, combined
    with the client axis — a (clients=2, seq=4) mesh runs vmap-over-clients
    and shard_map-over-seq in one compiled program, matching the dense-attn
    unsharded round."""
    import dataclasses

    import numpy as np
    from jax.flatten_util import ravel_pytree
    from jax.sharding import NamedSharding, PartitionSpec as P

    from commefficient_tpu.federated import engine
    from commefficient_tpu.models.gpt2 import TINY, GPT2LMHead
    from commefficient_tpu.models.losses import make_lm_loss
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.parallel import mesh as meshlib
    from commefficient_tpu.utils import jax_compat

    T, W, B = 32, 2, 2
    mesh = meshlib.make_mesh(8, seq_parallel=4)
    assert dict(mesh.shape) == {meshlib.CLIENT_AXIS: 2, meshlib.SEQ_AXIS: 4}
    batch = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(0), (W, B, T), 0, 512),
        "labels": jax.random.randint(jax.random.PRNGKey(0), (W, B, T), 0, 512),
        "mask": jnp.ones((W, B, T), jnp.float32),
    }

    def run(attn_impl, use_mesh):
        cfg = dataclasses.replace(TINY, n_positions=T, attn_impl=attn_impl)
        model = GPT2LMHead(cfg)
        params = model.init(
            jax.random.PRNGKey(1), jnp.zeros((1, T), jnp.int32), train=False
        )["params"]
        d = ravel_pytree(params)[0].size
        mcfg = ModeConfig(mode="uncompressed", d=d, momentum_type="none", error_type="none")
        ecfg = engine.EngineConfig(mode=mcfg)
        state = engine.init_server_state(ecfg, params, {})
        step = jax.jit(engine.make_round_step(make_lm_loss(model, train=True), ecfg))
        if use_mesh:
            b = jax.device_put(batch, meshlib.client_sharding(mesh))
            with jax_compat.set_mesh(mesh):
                new, _, _ = step(state, b, {}, jnp.float32(0.1), jax.random.PRNGKey(2))
        else:
            new, _, _ = step(state, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(2))
        return ravel_pytree(new["params"])[0]

    ref = run("dense", False)
    got = run("ring", True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-5)
