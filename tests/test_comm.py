"""Communication-accounting sanity: sketch beats dense at paper dims."""

from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.utils.comm import compression_ratio, round_comm_mb


def test_sketch_compresses_at_paper_dims():
    # CIFAR ResNet-9: d=6.5M, sketch 5 x 500k, k=50k -> up 10MB vs dense 26MB
    cfg = ModeConfig(mode="sketch", d=6_500_000, k=50_000, num_rows=5,
                     num_cols=500_000, momentum_type="virtual", error_type="virtual")
    assert compression_ratio(cfg, num_workers=100) > 2.0
    mb = round_comm_mb(cfg, 100)
    assert mb["comm_up_mb"] == 100 * 5 * 500_000 * 4 / 1e6
    assert mb["comm_down_mb"] == 100 * 50_000 * 8 / 1e6


def test_local_topk_cheap_up_dense_down_bounded():
    cfg = ModeConfig(mode="local_topk", d=1_000_000, k=1000,
                     momentum_type="none", error_type="local", num_clients=10)
    mb = round_comm_mb(cfg, 10)
    assert mb["comm_up_mb"] < mb["comm_down_mb"] <= 10 * 10 * 1000 * 8 / 1e6


def test_uncompressed_is_dense_both_ways():
    cfg = ModeConfig(mode="uncompressed", d=1000, momentum_type="none", error_type="none")
    mb = round_comm_mb(cfg, 4)
    assert mb["comm_up_mb"] == mb["comm_down_mb"] == 4 * 4000 / 1e6
    assert abs(compression_ratio(cfg, 4) - 1.0) < 1e-9
