"""Sketch-health observability (obs/health.py + obs/ledger.py + obs/slo.py).

The acceptance pins:

1. BIT-IDENTITY: a run with --health_every 1 and --ledger armed commits
   the exact params and metric rows of a run with both off — fused AND
   sharded (client_shards=2 reference) AND served (wire-payload round) —
   because the in-program estimators and fingerprints only READ round
   state, and the session pops the reserved "health/"/"ledger/" metric
   prefixes before any row consumer sees them.
2. The recall proxy (bracketed: naive same-rows upper / split-row cross
   lower, midpoint reported) tracks the dense-path truth within 0.05 on
   a dense-comparable geometry, and the bracket WIDENS under saturation.
3. The round ledger holds exactly the committed rounds — gap-free and
   duplicate-free across preempt -> resume on the real CLI (the resume
   truncation + commit-only appends), with the diff/replay-check CLI
   catching divergence and gaps.
4. The SLO engine fires on an injected quarantine spike, and --slo halt
   exits the runner cleanly through the checkpointed-halt path.
5. /metrics.prom renders # TYPE-annotated Prometheus text from the same
   registry the JSON endpoint reads.
6. The postmortem bundle carries trace + ledger tail + registry snapshot
   + config (the chaos `postmortem` mode drives the watchdog-abort path
   end to end; here the writer itself is pinned).
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

import cv_train
from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession, FedOptimizer
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.obs import health as obhealth
from commefficient_tpu.obs import ledger as obledger
from commefficient_tpu.obs import slo as obslo
from commefficient_tpu.obs import registry as obreg
from commefficient_tpu.resilience import EXIT_RESUMABLE
from commefficient_tpu.runner import RunnerConfig, run_loop
from commefficient_tpu.sketch import csvec

LR = 0.05


def _quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0), {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def _session(health_every=0, shards=0, wire=False, ledger_fp=False,
             seed=0, rows=3, cols=8, k=4, **kw):
    rs = np.random.RandomState(0)
    x = rs.randn(96, 6).astype(np.float32)
    w_true = rs.randn(6, 3).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), 12, np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(6, 3).astype(np.float32) * 0.1),
              "b": jnp.zeros(3)}
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="sketch", d=d, k=k, num_rows=rows,
                            num_cols=cols, momentum=0.9,
                            momentum_type="virtual", error_type="virtual"),
        train_set=train, num_workers=4, local_batch_size=4, seed=seed,
        client_shards=shards, wire_payloads=wire,
        health_every=health_every, ledger_fingerprint=ledger_fp, **kw)


def _assert_params_equal(sa, sb):
    for a, b in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class _CapturingMonitor(obhealth.HealthMonitor):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.calls: list[tuple[int, dict]] = []

    def on_round(self, rnd, health, metrics):
        block = super().on_round(rnd, health, metrics)
        self.calls.append((rnd, block))
        return block


# ------------------------------------------------- THE bit-identity pins


@pytest.mark.parametrize(
    "shards,wire", [(0, False), (2, False), (0, True)],
    ids=["fused", "sharded", "served-payload"])
def test_health_and_ledger_bit_identity(shards, wire, tmp_path):
    """health_every=1 + ledger fingerprints vs both off: params and every
    committed metric row identical to the last bit on all three round
    shapes — the estimators only read, and the reserved prefixes are
    popped before any consumer."""
    a = _session(shards=shards, wire=wire)
    rows_a = [a.run_round(LR) for _ in range(4)]

    b = _session(health_every=1, shards=shards, wire=wire, ledger_fp=True)
    b.health_monitor = _CapturingMonitor(
        mode_cfg=b.cfg.mode, num_workers=b.num_workers, health_every=1)
    b.ledger = obledger.RoundLedger(str(tmp_path / "led.jsonl"))
    rows_b = [b.run_round(LR) for _ in range(4)]
    b.ledger.close()

    assert rows_a == rows_b
    _assert_params_equal(a, b)
    # and the instrumentation actually ran: 4 health blocks, 4 ledger rows
    assert [r for r, _ in b.health_monitor.calls] == [0, 1, 2, 3]
    recs = obledger.round_records(str(tmp_path / "led.jsonl"))
    assert [r["round"] for r in recs] == [0, 1, 2, 3]
    assert all(r["fingerprint"] for r in recs)
    assert all(r["health"] for r in recs)


def test_health_cadence_and_registry_gauges():
    """health_every=3 computes (and records) on rounds 0, 3 only; the
    monitor publishes health_* gauges and counts health rounds."""
    s = _session(health_every=3)
    mon = _CapturingMonitor(mode_cfg=s.cfg.mode, num_workers=s.num_workers,
                            health_every=3)
    s.health_monitor = mon
    before = obreg.default().counter("health_rounds_total").value
    for _ in range(5):
        s.run_round(LR)
    assert [r for r, _ in mon.calls] == [0, 3]
    assert obreg.default().counter("health_rounds_total").value \
        - before == 2
    _, block = mon.calls[-1]
    for key in ("grad_mass_est", "topk_mass_proxy", "row_mass_cv",
                "release_frac", "verror_ratio", "uplink_vs_dense"):
        assert isinstance(block[key], float), (key, block)
    assert obreg.default().gauge("health_topk_mass_proxy").value >= 0.0
    # dense-reference extras exist on the fused ravel path
    assert "topk_mass_true" in block and "leaf_norms" in block
    assert len(block["leaf_norms"]) == 2  # w + b leaves


def test_health_in_fused_block_dispatch():
    """A K-round fused block (run_rounds -> lax.scan) carries the health
    leaf through the scan: one block per round, correct cadence."""
    s = _session(health_every=2)
    mon = _CapturingMonitor(mode_cfg=s.cfg.mode, num_workers=s.num_workers,
                            health_every=2)
    s.health_monitor = mon
    s.run_rounds([LR] * 4)
    assert [r for r, _ in mon.calls] == [0, 2]
    ref = _session()
    ref.run_rounds([LR] * 4)
    _assert_params_equal(s, ref)


def test_health_validation_and_split_rejection():
    with pytest.raises(ValueError, match="health"):
        _session(health_every=-1)
    with pytest.raises(ValueError, match="fused-paths-only"):
        _session(health_every=1, split_compile=True)
    with pytest.raises(ValueError, match="sketch"):
        rs = np.random.RandomState(0)
        x = rs.randn(96, 6).astype(np.float32)
        y = (x @ rs.randn(6, 3).astype(np.float32)).argmax(-1).astype(
            np.int32)
        FederatedSession(
            train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
            params={"w": jnp.zeros((6, 3)), "b": jnp.zeros(3)},
            net_state={},
            mode_cfg=ModeConfig(mode="uncompressed", d=21, momentum=0.0,
                                momentum_type="none", error_type="none"),
            train_set=FedDataset(
                x, y, shard_iid(96, 12, np.random.RandomState(1))),
            num_workers=4, local_batch_size=4, health_every=1)


# --------------------------------------------- the recall-proxy bracket


def test_recall_proxy_brackets_truth_and_widens_under_saturation():
    """On a moderate geometry the bracketed proxy tracks the true top-k
    energy fraction within 0.05; cranking the compression (c/16) widens
    the bracket — the estimator reports its own degradation."""
    rs = np.random.RandomState(0)
    d = 50_000
    g = jnp.asarray(rs.standard_t(3.0, size=d).astype(np.float32))
    gsq = float(jnp.sum(g * g))

    def bracket(k, c):
        spec = ModeConfig(mode="sketch", d=d, k=k, num_rows=5, num_cols=c,
                          momentum=0.0, momentum_type="none",
                          error_type="virtual").sketch_spec
        tab = csvec.sketch_vec(spec, g)
        mass = float(obhealth.table_mass_estimate(tab))
        _, pv = csvec.unsketch_topk(spec, tab, k)
        naive = float(obhealth.topk_energy(pv)) / mass
        pess = float(obhealth.split_topk_energy_fraction(spec, tab, k, mass))
        tidx = csvec.topk_abs(g, k)
        true = float(jnp.sum(g[tidx] ** 2)) / gsq
        return naive, pess, 0.5 * (naive + pess), true

    naive, pess, proxy, true = bracket(512, 16_384)
    assert abs(proxy - true) <= 0.05, (proxy, true)
    assert naive >= pess  # the bracket's orientation
    width_ok = naive - pess
    naive2, pess2, _, _ = bracket(512, 1_024)  # saturated: k/c = 0.5
    assert naive2 - pess2 > width_ok, (
        "saturation did not widen the proxy bracket")


def test_split_estimator_chunked_path_matches_single_shot():
    """Past csvec's single-shot byte budget the split estimator scans the
    d axis with a running top-k carry instead of materializing [r, d] —
    the two paths must select the same coordinates and produce the same
    energy (the no-[d]-materialization discipline extends to health)."""
    rs = np.random.RandomState(0)
    d = 30_000
    g = jnp.asarray(rs.standard_t(3.0, size=d).astype(np.float32))
    spec = ModeConfig(mode="sketch", d=d, k=256, num_rows=5,
                      num_cols=4096, momentum=0.0, momentum_type="none",
                      error_type="virtual").sketch_spec
    tab = csvec.sketch_vec(spec, g)
    mass = float(obhealth.table_mass_estimate(tab))
    single = float(obhealth.split_topk_energy_fraction(spec, tab, 256, mass))
    orig = csvec.UNSKETCH_SINGLE_SHOT_BYTES
    try:
        csvec.UNSKETCH_SINGLE_SHOT_BYTES = 4 * spec.r * 4000  # force chunks
        chunked = float(
            obhealth.split_topk_energy_fraction(spec, tab, 256, mass))
    finally:
        csvec.UNSKETCH_SINGLE_SHOT_BYTES = orig
    assert abs(single - chunked) < 1e-4, (single, chunked)


def test_slo_shared_series_history_not_duplicated():
    """Two rules on ONE series must not double-append its history: the
    floor rule below needs a full 3-round window, so with correct
    bookkeeping it cannot fire before round 2 even with a second rule
    watching the same series."""
    eng = obslo.SloEngine(
        obslo.parse_rules("hi:loss_sum>100@3;lo:loss_sum<1@3"),
        mode="warn", alert=lambda m: None)
    fired = []
    for rnd in range(2):
        fired += eng.on_round(rnd, {"loss_sum": 0.5})
    assert not fired, fired  # 2 samples < window despite 2 rules
    fired += eng.on_round(2, {"loss_sum": 0.5})
    assert [e["rule"] for e in fired] == ["lo"]


def test_monitor_uplink_respects_zero_participants():
    mon = obhealth.HealthMonitor(mode_cfg=_session().cfg.mode,
                                 num_workers=4, health_every=1)
    block = mon.on_round(0, {"grad_mass_est": 1.0},
                         {"participants": 0.0})
    assert block["uplink_bytes"] == 0.0  # a fully-degraded round uploaded
    # nothing — 0.0 is a value, not a missing key


def test_table_mass_estimate_tracks_norm():
    rs = np.random.RandomState(1)
    d = 20_000
    g = jnp.asarray(rs.randn(d).astype(np.float32))
    spec = ModeConfig(mode="sketch", d=d, k=16, num_rows=5, num_cols=4096,
                      momentum=0.0, momentum_type="none",
                      error_type="virtual").sketch_spec
    tab = csvec.sketch_vec(spec, g)
    mass = float(obhealth.table_mass_estimate(tab))
    assert abs(mass - float(jnp.sum(g * g))) / float(jnp.sum(g * g)) < 0.1
    assert float(obhealth.row_mass_cv(tab)) < 0.2  # healthy sketch


# --------------------------------------------------------- round ledger


def test_ledger_appends_are_monotonic_and_replay_clean(tmp_path):
    path = str(tmp_path / "l.jsonl")
    led = obledger.RoundLedger(path, static={"merge_policy": "sum"})
    for r in range(3):
        led.append_round(r, cohort=[1, 2], metrics={"participants": 2.0,
                                                    "lr": 0.1})
    with pytest.raises(obledger.LedgerError, match="out of order"):
        led.append_round(2)
    led.close()
    assert obledger.replay_check(path) == []
    recs = obledger.read_records(path)
    assert recs[0]["kind"] == "header"
    assert recs[0]["static"]["merge_policy"] == "sum"


def test_ledger_replay_check_catches_gap_and_dup(tmp_path):
    path = str(tmp_path / "l.jsonl")
    rows = [{"schema": 1, "kind": "round", "round": r} for r in
            (0, 1, 3, 3)]
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    problems = obledger.replay_check(path)
    assert any("gap" in p for p in problems), problems
    assert any("duplicate" in p for p in problems), problems
    assert obledger.main(["replay-check", path]) == 1
    # a torn FINAL line is the legal crash artifact
    with open(path, "a") as fh:
        fh.write('{"schema": 1, "kind": "round", "rou')
    assert len(obledger.read_records(path)) == 4


def test_ledger_diff_names_first_divergence(tmp_path):
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, seed in ((pa, 0), (pb, 0)):
        s = _session(ledger_fp=True, seed=seed)
        s.ledger = obledger.RoundLedger(path)
        for _ in range(3):
            s.run_round(LR)
        s.ledger.close()
    assert obledger.diff(pa, pb)["equal"]
    assert obledger.main(["diff", pa, pb]) == 0
    pc = str(tmp_path / "c.jsonl")
    s = _session(ledger_fp=True, seed=7)  # different trajectory
    s.ledger = obledger.RoundLedger(pc)
    for _ in range(3):
        s.run_round(LR)
    s.ledger.close()
    res = obledger.diff(pa, pc)
    assert not res["equal"]
    assert res["first_divergence"]["round"] == 0
    assert obledger.main(["diff", pa, pc]) == 1


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


@pytest.mark.chaos
def test_ledger_resume_continuation_is_gap_free(tiny_cv, tmp_path):
    """Preempt mid-run -> exit 75 -> --resume: ONE ledger file, every
    round exactly once (the resume truncation drops rounds committed
    after the checkpoint being resumed from; the resumed run re-commits
    and re-appends them), and the resumed records re-derive the SAME
    fingerprints an uninterrupted run writes (commit-only appends +
    bit-exact resume)."""
    led = str(tmp_path / "run.jsonl")
    base = [
        "--dataset", "cifar10", "--mode", "sketch", "--k", "32",
        "--num_rows", "3", "--num_cols", "128", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--lr_scale",
        "0.05", "--weight_decay", "0", "--data_root", "/nonexistent",
        "--num_rounds", "6", "--eval_every", "2",
        "--checkpoint_dir", str(tmp_path / "ck"),
        "--checkpoint_every", "2",
        "--ledger", led, "--health_every", "2",
    ]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(base + ["--fault_plan", "preempt@3"])
    assert ei.value.code == EXIT_RESUMABLE
    session = cv_train.main(base + ["--resume"])
    assert session.round == 6
    assert obledger.replay_check(led) == [], obledger.replay_check(led)
    recs = obledger.round_records(led)
    assert [r["round"] for r in recs] == list(range(6))
    # the uninterrupted twin writes the identical round sequence
    led2 = str(tmp_path / "twin.jsonl")
    cv_train.main([a if a != led else led2 for a in base
                   if a not in ("--checkpoint_dir", str(tmp_path / "ck"))]
                  + ["--checkpoint_dir", str(tmp_path / "ck2")])
    twin = obledger.round_records(led2)
    assert [r["fingerprint"] for r in twin] \
        == [r["fingerprint"] for r in recs]


# ------------------------------------------------------------ SLO engine


def test_slo_rule_grammar():
    r = obslo.SloRule.parse("q:quarantine_rate>0.3@5")
    assert (r.name, r.series, r.op, r.threshold, r.window) == (
        "q", "quarantine_rate", ">", 0.3, 5)
    assert obslo.SloRule.parse("f:topk_mass_proxy<0.05").window == 5
    assert obslo.SloRule.parse("i:server_idle_ms^5@10").op == "^"
    for bad in ("noop", "x:series=1", "x:series>nan@0", "x:s>1@0"):
        with pytest.raises(ValueError):
            obslo.SloRule.parse(bad)
    with pytest.raises(ValueError, match="duplicate"):
        obslo.parse_rules("a:x>1;a:y>2")
    assert len(obslo.parse_rules("")) == len(obslo.DEFAULT_RULES)


def test_slo_spike_fires_edge_triggered_and_halt_latches():
    eng = obslo.SloEngine(obslo.parse_rules("q:quarantine_rate>0.3@3"),
                          mode="halt", alert=lambda m: None)
    before = obreg.default().counter("slo_violations_total").value
    clean = {"participants": 8.0, "clients_quarantined": 0.0}
    spike = {"participants": 4.0, "clients_quarantined": 4.0}
    fired = []
    for rnd in range(4):
        fired += eng.on_round(rnd, clean)
    assert not fired and not eng.halted
    for rnd in range(4, 8):
        fired += eng.on_round(rnd, spike)
    assert len(fired) == 1, fired  # edge-triggered: one episode, one event
    assert eng.halted and "quarantine_rate" in eng.halted_reason
    assert obreg.default().counter(
        "slo_violations_total").value - before == 1
    snap = eng.snapshot()
    assert snap["halted"] and snap["mode"] == "halt"


def test_slo_floor_rule_waits_for_window_and_reads_health():
    eng = obslo.SloEngine(obslo.parse_rules("r:topk_mass_proxy<0.5@3"),
                          mode="warn", alert=lambda m: None)
    ev = []
    for rnd in range(2):
        ev += eng.on_round(rnd, {}, {"topk_mass_proxy": 0.1})
    assert not ev  # floor rules can't fire before the window fills
    ev += eng.on_round(2, {}, {"topk_mass_proxy": 0.1})
    assert len(ev) == 1 and ev[0]["rule"] == "r"


def test_slo_halt_exits_run_loop_cleanly():
    """--slo halt: the engine latches at commit and the runner exits
    through the same clean path as --on_nonfinite halt, message naming
    the rule."""
    s = _session()
    eng = obslo.SloEngine(obslo.parse_rules("p:participants>0.5@2"),
                          mode="halt", alert=lambda m: None)
    s.slo = eng
    cfg = RunnerConfig(total_rounds=6, eval_every=6, sync_loop=True)
    with pytest.raises(SystemExit) as ei:
        run_loop(s, FedOptimizer(lambda _: LR, 1), cfg, slo=eng)
    assert "SLO violation" in str(ei.value.code)
    assert "p:" in str(ei.value.code) or "p" in eng.halted_reason


# ----------------------------------------------- Prometheus exposition


def test_prometheus_render_has_type_lines():
    from commefficient_tpu.serve.metrics import render_prometheus

    reg = obreg.Registry()
    reg.counter("runner_rounds_total").inc(3)
    reg.gauge("server_idle_ms").set(1.5)
    reg.histogram("runner_phase_drain_ms").observe(2.0)
    reg.meter("serve_arrival_rate").record(5)
    text = render_prometheus(reg)
    assert "# TYPE runner_rounds_total counter" in text
    assert "runner_rounds_total 3" in text
    assert "# TYPE server_idle_ms gauge" in text
    assert "server_idle_ms_max 1.5" in text
    assert "# TYPE runner_phase_drain_ms summary" in text
    assert 'runner_phase_drain_ms{quantile="0.5"} 2' in text
    assert "runner_phase_drain_ms_count 1" in text
    assert "# TYPE serve_arrival_rate_rate_per_s gauge" in text
    assert text.endswith("\n")


def test_prometheus_endpoint_serves_beside_json():
    from commefficient_tpu.serve.metrics import MetricsServer

    reg = obreg.Registry()
    reg.counter("slo_violations_total").inc()
    srv = MetricsServer(lambda: {"round": 1}, port=0, registry=reg)
    srv.start()
    try:
        host, port = srv.address
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics.prom", timeout=5) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode()
        assert "# TYPE slo_violations_total counter" in body
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5) as r:
            assert json.loads(r.read())["round"] == 1
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://{host}:{port}/nope", timeout=5)
    finally:
        srv.stop()


# ------------------------------------------------------------ postmortem


def test_postmortem_bundle_contents(tmp_path):
    led = str(tmp_path / "l.jsonl")
    s = _session(ledger_fp=True)
    s.ledger = obledger.RoundLedger(led)
    for _ in range(3):
        s.run_round(LR)
    s.ledger.close()
    out = obledger.write_postmortem_bundle(
        str(tmp_path / "bundle"), reason="test", ledger_path=led,
        last_k=2, config={"mode": "sketch", "fn": print})
    reason = json.load(open(f"{out}/reason.json"))
    assert reason["reason"] == "test"
    assert reason["artifact_failures"] is None
    assert "traceEvents" in json.load(open(f"{out}/trace.json"))
    tail = [json.loads(line) for line in open(f"{out}/ledger_tail.jsonl")]
    assert [r["round"] for r in tail if r.get("kind") == "round"] == [1, 2]
    assert isinstance(json.load(open(f"{out}/registry.json")), dict)
    cfg = json.load(open(f"{out}/config.json"))
    assert cfg["mode"] == "sketch"
    assert isinstance(cfg["fn"], str)  # non-JSON values stringified


def test_runstats_carries_slo_violations():
    s = _session()
    eng = obslo.SloEngine(obslo.parse_rules("p:participants>0.5@1"),
                          mode="warn", alert=lambda m: None)
    s.slo = eng
    cfg = RunnerConfig(total_rounds=3, eval_every=3, sync_loop=True)
    stats = run_loop(s, FedOptimizer(lambda _: LR, 1), cfg, slo=eng)
    assert stats.rounds == 3
    assert stats.slo_violations == 1  # one episode, edge-triggered
