"""Hung-round watchdog tests (utils/watchdog.py — the failure-detection
subsystem the reference lacks, SURVEY.md §5)."""

import time

from commefficient_tpu.utils.watchdog import RoundWatchdog


def test_unarmed_until_history():
    wd = RoundWatchdog(min_history=3)
    assert wd.threshold_s() is None
    for i in range(3):
        with wd.round(i):
            pass
    assert wd.threshold_s() is not None


def test_fast_rounds_never_alert():
    alerts = []
    wd = RoundWatchdog(factor=10.0, min_history=2, floor_s=0.5, alert=alerts.append)
    for i in range(6):
        with wd.round(i):
            time.sleep(0.01)
    assert alerts == [] and wd.stalls_detected == 0


def test_unrecorded_segments_do_not_feed_the_median():
    """The async runner's dispatch segments return in ~ms (no host sync);
    record=False must guard them WITHOUT dragging the learned median to ~0
    (which would collapse every threshold to the floor and false-fire the
    ladder on healthy boundary drains)."""
    wd = RoundWatchdog(min_history=2, floor_s=0.01)
    for i in range(2):
        with wd.round(i):
            time.sleep(0.05)
    before = wd.threshold_s()
    for i in range(2, 12):
        with wd.round(i, record=False):
            pass  # ~0 s dispatch; must not enter _times
    assert len(wd._times) == 2
    assert wd.threshold_s() == before


def test_multi_round_segment_scales_threshold_and_normalizes_median():
    """A drain that waits out K queued rounds is not a stall: the stage-1
    delay scales by K and the recorded time is per-round, so the median
    stays a true round time."""
    alerts = []
    wd = RoundWatchdog(factor=3.0, min_history=2, floor_s=0.01,
                       alert=alerts.append)
    for i in range(2):
        with wd.round(i):
            time.sleep(0.03)
    thr = wd.threshold_s()
    # a 4-round drain taking ~4x a round: within 4*thr, no alert
    with wd.round(2, rounds=4):
        time.sleep(min(0.12, 4 * thr * 0.8))
    assert alerts == [] and wd.stalls_detected == 0
    # and the median absorbed ~a round time, not the whole drain
    assert wd._times[-1] < 2 * wd._times[0] + 0.05


def test_stalled_round_alerts_once_with_diagnosis():
    alerts = []
    wd = RoundWatchdog(factor=3.0, min_history=2, floor_s=0.05, alert=alerts.append)
    for i in range(3):
        with wd.round(i):
            time.sleep(0.02)
    with wd.round(99):
        time.sleep(0.4)  # >> 3 x ~0.02s median, > floor
    assert wd.stalls_detected == 1  # ONE stall, however many ladder stages
    assert "round 99" in alerts[0] and "hung" in alerts[0]
    # recovery: the long round joins the history; the next fast round is fine
    with wd.round(100):
        pass
    assert wd.stalls_detected == 1


def test_floor_suppresses_early_alerts():
    alerts = []
    wd = RoundWatchdog(factor=2.0, min_history=1, floor_s=10.0, alert=alerts.append)
    with wd.round(0):
        time.sleep(0.01)
    with wd.round(1):
        time.sleep(0.1)  # 10x the median but far under the 10s floor
    assert alerts == []


def _stall_until(wd, round_index, n_stages, deadline_s=15.0):
    """Hold a round open until the ladder has fired n_stages (or deadline)."""
    with wd.round(round_index):
        deadline = time.monotonic() + deadline_s
        while len(wd.stages_fired) < n_stages and time.monotonic() < deadline:
            time.sleep(0.02)


def test_escalation_ladder_fires_in_order():
    alerts, fired = [], []
    wd = RoundWatchdog(
        factor=2.0, min_history=2, floor_s=0.05, alert=alerts.append,
        on_emergency=lambda: fired.append("ckpt"),
        on_abort=lambda: fired.append("abort"),
    )
    for i in range(2):
        with wd.round(i):
            time.sleep(0.01)
    _stall_until(wd, 99, n_stages=4)
    assert wd.stages_fired == ["warn", "stacks", "checkpoint", "abort"]
    assert fired == ["ckpt", "abort"]
    assert wd.stalls_detected == 1  # one stall, four stages
    # stage 2's payload is the "where is it stuck" stack dump
    assert "thread" in alerts[1] and "_stall_until" in alerts[1]
    # a later fast round must not fire anything further
    n = len(wd.stages_fired)
    with wd.round(100):
        pass
    assert len(wd.stages_fired) == n


def test_ladder_without_callbacks_ends_with_diagnosis():
    alerts = []
    wd = RoundWatchdog(factor=2.0, min_history=2, floor_s=0.05,
                       alert=alerts.append)
    for i in range(2):
        with wd.round(i):
            time.sleep(0.01)
    _stall_until(wd, 7, n_stages=4)
    assert wd.stages_fired == ["warn", "stacks", "checkpoint", "abort"]
    joined = "\n".join(alerts)
    assert "no emergency-checkpoint callback" in joined
    assert "abort disabled" in joined


def test_emergency_checkpoint_failure_does_not_stop_ladder():
    alerts, fired = [], []

    def broken_ckpt():
        raise OSError("disk full")

    wd = RoundWatchdog(
        factor=2.0, min_history=2, floor_s=0.05, alert=alerts.append,
        on_emergency=broken_ckpt, on_abort=lambda: fired.append("abort"),
    )
    for i in range(2):
        with wd.round(i):
            time.sleep(0.01)
    _stall_until(wd, 5, n_stages=4)
    assert wd.stages_fired[-1] == "abort" and fired == ["abort"]
    assert any("emergency checkpoint failed" in a for a in alerts)
