"""Hung-round watchdog tests (utils/watchdog.py — the failure-detection
subsystem the reference lacks, SURVEY.md §5)."""

import time

from commefficient_tpu.utils.watchdog import RoundWatchdog


def test_unarmed_until_history():
    wd = RoundWatchdog(min_history=3)
    assert wd.threshold_s() is None
    for i in range(3):
        with wd.round(i):
            pass
    assert wd.threshold_s() is not None


def test_fast_rounds_never_alert():
    alerts = []
    wd = RoundWatchdog(factor=10.0, min_history=2, floor_s=0.5, alert=alerts.append)
    for i in range(6):
        with wd.round(i):
            time.sleep(0.01)
    assert alerts == [] and wd.stalls_detected == 0


def test_stalled_round_alerts_once_with_diagnosis():
    alerts = []
    wd = RoundWatchdog(factor=3.0, min_history=2, floor_s=0.05, alert=alerts.append)
    for i in range(3):
        with wd.round(i):
            time.sleep(0.02)
    with wd.round(99):
        time.sleep(0.4)  # >> 3 x ~0.02s median, > floor
    assert wd.stalls_detected == 1
    assert len(alerts) == 1
    assert "round 99" in alerts[0] and "hung" in alerts[0]
    # recovery: the long round joins the history; the next fast round is fine
    with wd.round(100):
        pass
    assert wd.stalls_detected == 1


def test_floor_suppresses_early_alerts():
    alerts = []
    wd = RoundWatchdog(factor=2.0, min_history=1, floor_s=10.0, alert=alerts.append)
    with wd.round(0):
        time.sleep(0.01)
    with wd.round(1):
        time.sleep(0.1)  # 10x the median but far under the 10s floor
    assert alerts == []
