"""scripts/tradeoff_table.py: the results-table renderer must describe ONE
run per arm even when several runs were appended to the same JSONL file (an
lr sweep appends; the table and best-acc footer must not mix arms)."""

import json
import subprocess
import sys

from conftest import repo_root


def _run(paths):
    out = subprocess.run(
        [sys.executable, f"{repo_root()}/scripts/tradeoff_table.py", *paths],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    return out.stdout, out.stderr


def _write(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_round_reset_keeps_only_final_run(tmp_path):
    """Three concatenated runs: an early run's 0.9 best-acc must not leak
    into the footer while the table shows the final run's 0.5/0.6."""
    p = tmp_path / "cifar10_hard_sketch.jsonl"
    _write(p, [
        {"round": 8, "test_acc": 0.9, "comm_mb": 10.0},   # run 1 (stale)
        {"round": 16, "test_acc": 0.95, "comm_mb": 20.0},
        {"round": 8, "test_acc": 0.2, "comm_mb": 10.0},   # run 2 (stale)
        {"round": 8, "test_acc": 0.5, "comm_mb": 10.0},   # run 3 (final)
        {"round": 16, "test_acc": 0.6, "comm_mb": 20.0},
    ])
    stdout, stderr = _run([str(p)])
    assert "round reset" in stderr
    assert "best test_acc 0.600" in stdout  # footer from the final run only
    assert "0.950" not in stdout and "0.900" not in stdout


def test_resume_overlap_keeps_early_history(tmp_path):
    """A crash-resumed run re-appends rounds it already logged; the early
    rounds must survive and the post-resume duplicates must win."""
    p = tmp_path / "cifar10_hard_localtopk.jsonl"
    _write(p, [
        {"round": 8, "test_acc": 0.3, "comm_mb": 5.0},
        {"round": 16, "test_acc": 0.5, "comm_mb": 10.0},   # pre-crash
        {"round": 16, "test_acc": 0.55, "comm_mb": 10.0},  # post-resume dup
        {"round": 24, "test_acc": 0.7, "comm_mb": 15.0},
    ])
    stdout, stderr = _run([str(p)])
    assert "resume overlap" in stderr
    assert "| 8 | 0.300" in stdout        # early history preserved
    assert "0.550" in stdout              # post-resume row wins the overlap
    assert "0.500" not in stdout
    assert "best test_acc 0.700" in stdout


def test_new_run_with_coarser_eval_cadence_detected(tmp_path):
    """A fresh appended run whose first eval round lands MID-history (larger
    eval_every) must still be detected as a new run: its cumulative comm_mb
    restarts, while a resume would continue at the same comm level."""
    p = tmp_path / "cifar10_hard_fedavg.jsonl"
    _write(p, [
        {"round": 8, "test_acc": 0.9, "comm_mb": 10.0},   # run 1 (stale)
        {"round": 16, "test_acc": 0.95, "comm_mb": 20.0},
        {"round": 24, "test_acc": 0.97, "comm_mb": 30.0},
        {"round": 16, "test_acc": 0.3, "comm_mb": 4.0},   # run 2: comm restarted
        {"round": 32, "test_acc": 0.4, "comm_mb": 8.0},
    ])
    stdout, stderr = _run([str(p)])
    assert "round reset" in stderr
    assert "best test_acc 0.400" in stdout
    assert "0.970" not in stdout and "0.950" not in stdout


def test_single_run_untouched(tmp_path):
    p = tmp_path / "cifar10_hard_uncompressed.jsonl"
    _write(p, [
        {"round": 8, "test_acc": 0.4, "comm_mb": 5.0},
        {"round": 16, "test_acc": 0.7, "comm_mb": 10.0},
    ])
    stdout, stderr = _run([str(p)])
    assert "round reset" not in stderr
    assert "best test_acc 0.700" in stdout
