"""graftlint (commefficient_tpu/analysis/) — the static-analysis suite.

Three layers:

1. Fixture corpus: per rule code, a minimal VIOLATING snippet must fire
   (>= 1 finding of exactly that code) and its CONFORMING twin must stay
   silent for that code. Fixtures impersonate in-scope modules with a
   `# graftlint: module=` directive, so the scoped rules engage.
2. The real repo: `--json` over commefficient_tpu/ must exit 0 against the
   shipped baseline, and the shipped baseline must carry ZERO G002/G003/G004
   entries (those contracts admit no grandfathering).
3. Directive hygiene: `# graftlint: disable=` must name a valid rule code
   (a bad code is itself reported, G000, and is not suppressible).

Pure-host tests: the linter never imports the analyzed code, so none of
this touches jax.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from commefficient_tpu.analysis import ALL_RULES, RULE_CODES, Analyzer
from commefficient_tpu.analysis.baseline import DEFAULT_BASELINE, Baseline
from commefficient_tpu.analysis.rules_config import registered_flags

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "commefficient_tpu")


def _codes(path: str) -> list[str]:
    result = Analyzer().run([path])
    return [v.code for v in result.violations]


# ------------------------------------------------------------------ fixtures


@pytest.mark.parametrize("code", RULE_CODES)
def test_rule_fires_on_violating_fixture(code):
    path = os.path.join(FIXTURES, f"{code.lower()}_bad.py")
    assert os.path.exists(path), f"missing violating fixture for {code}"
    found = _codes(path)
    assert code in found, (
        f"{code} did not fire on its violating fixture (found: {found})")


@pytest.mark.parametrize("code", RULE_CODES)
def test_rule_silent_on_conforming_fixture(code):
    path = os.path.join(FIXTURES, f"{code.lower()}_ok.py")
    assert os.path.exists(path), f"missing conforming fixture for {code}"
    found = _codes(path)
    assert code not in found, (
        f"{code} false-positived on its conforming twin (found: {found})")


def test_g007_fires_through_helper_import():
    """Package-level reachability: a time.sleep smuggled behind a helper
    IMPORT (run_loop -> other_module.wait_ready) must fire G007 — the case
    the old module-local call graph missed."""
    found = _codes(os.path.join(FIXTURES, "g007_import_bad.py"))
    assert "G007" in found, found


def test_g007_import_traversal_stops_at_drain_point():
    """The same import shape with the helper's wait DECLARED a drain point
    (in the helper's own module) must stay silent — that is how the serve/
    transports declare their sanctioned blocking points in code."""
    found = _codes(os.path.join(FIXTURES, "g007_import_ok.py"))
    assert "G007" not in found, found


def test_g010_sketch_boundary_declares_the_ravel_path():
    """The conforming twin's ravel site is legal ONLY because its def
    carries `# graftlint: sketch-boundary` — strip the directive and the
    same code must fire (the boundary is a declaration, not a loophole)."""
    with open(os.path.join(FIXTURES, "g010_ok.py")) as f:
        text = f.read()
    stripped = text.replace(
        "# graftlint: sketch-boundary — the ravel path IS the declared "
        "flat boundary\n", "")
    assert stripped != text, "fixture lost its sketch-boundary line"
    import tempfile

    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(stripped)
        path = tmp.name
    try:
        assert "G010" in _codes(path)
    finally:
        os.unlink(path)


def test_g010_import_alone_is_silent():
    """`from jax.flatten_util import ravel_pytree` without a call moves no
    bytes — only the call that materializes the flat vector fires."""
    import tempfile

    src = ("# graftlint: module=commefficient_tpu/modes/modes.py\n"
           "from jax.flatten_util import ravel_pytree\n")
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G010" not in _codes(path)
    finally:
        os.unlink(path)


def test_g012_robust_merge_is_a_declaration_not_a_loophole():
    """Strip the conforming twin's `# graftlint: robust-merge` marker and
    the same sorts must fire — the boundary is declared, never inferred."""
    with open(os.path.join(FIXTURES, "g012_ok.py")) as f:
        text = f.read()
    stripped = text.replace(
        "# graftlint: robust-merge — the declared order-statistics site\n",
        "")
    assert stripped != text, "fixture lost its robust-merge line"
    import tempfile

    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(stripped)
        path = tmp.name
    try:
        assert "G012" in _codes(path)
    finally:
        os.unlink(path)


def test_g012_second_declared_boundary_fires():
    """THE robust-merge boundary is one function: a second declaration in
    parity scope is a second aggregation semantics hiding under the
    first's exemption, and must itself be a violation."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/modes/modes.py\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "# graftlint: robust-merge\n"
        "def first(stacked):\n"
        "    return jnp.sort(stacked, axis=0)\n"
        "\n"
        "\n"
        "# graftlint: robust-merge\n"
        "def second(stacked):\n"
        "    return jnp.median(stacked, axis=0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        found = _codes(path)
        assert found.count("G012") == 1, found  # the SECOND def, only
    finally:
        os.unlink(path)


def test_g012_boundary_outside_modes_fires_cross_file():
    """The boundary lives in ONE sanctioned file: declaring robust-merge in
    engine.py (also parity scope) must fire even for a lone declaration —
    that is how a cross-file second boundary is caught without cross-file
    rule state."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/federated/engine.py\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "# graftlint: robust-merge\n"
        "def rogue(stacked):\n"
        "    return jnp.sort(stacked, axis=0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        found = _codes(path)
        # the illegal declaration AND the unexempted sort both fire
        assert found.count("G012") == 2, found
    finally:
        os.unlink(path)


def test_g012_sketch_row_median_out_of_scope():
    """csvec's per-row median estimator (sketch/) sorts over the r hash-row
    axis — the Count-Sketch definition, not a client merge; the rule's
    scope deliberately excludes sketch/."""
    import tempfile

    src = ("# graftlint: module=commefficient_tpu/sketch/csvec.py\n"
           "import jax.numpy as jnp\n"
           "def estimate(per_row, r):\n"
           "    return jnp.sort(per_row, axis=0)[(r - 1) // 2]\n")
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G012" not in _codes(path)
    finally:
        os.unlink(path)


def test_g013_second_declared_boundary_fires():
    """THE staleness-fold boundary is one function in engine.py: a second
    declaration is a second fold semantics hiding under the first's
    exemption, and must itself be a violation."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/federated/engine.py\n"
        "import jax\n"
        "\n"
        "\n"
        "# graftlint: staleness-fold\n"
        "def first(table, live, stale_tables, stale_weights):\n"
        "    return table + (stale_weights[:, None, None]\n"
        "                    * stale_tables).sum(0)\n"
        "\n"
        "\n"
        "# graftlint: staleness-fold\n"
        "def second(table, live, stale_tables, stale_weights):\n"
        "    return table + (stale_tables * stale_weights[:, None,\n"
        "                    None]).sum(0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        found = _codes(path)
        assert found.count("G013") == 1, found  # the SECOND def, only
    finally:
        os.unlink(path)


def test_g013_forwarding_is_legal_config_scalars_exempt():
    """The merge may FORWARD the stale stack to the boundary, and the
    stale_slots config scalar is not a wire value — neither fires; an
    inline multiply outside the boundary does."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/federated/engine.py\n"
        "# graftlint: staleness-fold\n"
        "def _stale_fold(table, live, stale_tables, stale_weights):\n"
        "    return table + (stale_weights[:, None, None]\n"
        "                    * stale_tables).sum(0)\n"
        "\n"
        "\n"
        "def merge(table, live, stale_tables, stale_weights,\n"
        "          stale_slots=0):\n"
        "    if stale_slots:\n"
        "        return _stale_fold(table, live, stale_tables,\n"
        "                           stale_weights)\n"
        "    return table\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G013" not in _codes(path)
    finally:
        os.unlink(path)
    bad = src + (
        "\n\ndef sneaky(table, stale_tables, stale_weights):\n"
        "    return table + (stale_weights[:, None, None]\n"
        "                    * stale_tables).sum(0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(bad)
        path = tmp.name
    try:
        assert "G013" in _codes(path)
    finally:
        os.unlink(path)


def test_g012_weighted_sort_smuggled_into_stale_fold_fires():
    """The weighted-order-statistics form (per-buffer robust merge): a
    sort/searchsorted smuggled INTO the declared staleness-fold boundary
    must fire G012 — the stale-fold declaration sanctions the LINEAR
    slot-ordered scan only, never order statistics (the wrong boundary's
    exemption buys nothing)."""
    found = _codes(os.path.join(FIXTURES, "g012_weighted_bad.py"))
    assert found.count("G012") >= 2, found  # sort + searchsorted at least
    assert "G013" not in found, found  # the stale arithmetic IS in-boundary


def test_g012_weighted_forwarding_to_robust_boundary_is_silent():
    """The conforming twin: the merge FORWARDS the stale union stacks to
    the robust-merge boundary through the attribute call
    (modes.merge_partial_wires) — no G012, and no G013 (keyword
    forwarding is the sanctioned shape)."""
    found = _codes(os.path.join(FIXTURES, "g012_weighted_ok.py"))
    assert "G012" not in found, found
    assert "G013" not in found, found


def test_g013_stale_arithmetic_inside_robust_merge_boundary_is_legal():
    """The async x robust composition: stale wire values joining the
    weighted order statistics INSIDE the declared robust-merge boundary
    (modes/modes.py) are sanctioned — that is the one other place their
    fold semantics are pinned; the same arithmetic outside it fires."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/modes/modes.py\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "# graftlint: robust-merge\n"
        "def _robust_table_merge(stacked, live, policy, trim,\n"
        "                        stale_tables=None, stale_weights=None):\n"
        "    union = jnp.concatenate([stacked, stale_tables], axis=0)\n"
        "    w = jnp.concatenate([live, stale_weights])\n"
        "    order = jnp.argsort(union, axis=0, stable=True)\n"
        "    return union.sum(0), w.sum(), order\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        found = _codes(path)
        assert "G013" not in found, found
        assert "G012" not in found, found
    finally:
        os.unlink(path)
    bad = src + (
        "\n\ndef outside(stale_tables, stale_weights):\n"
        "    return (stale_weights[:, None, None] * stale_tables).sum(0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(bad)
        path = tmp.name
    try:
        assert "G013" in _codes(path)
    finally:
        os.unlink(path)


def test_g013_generic_attribute_call_is_not_forwarding():
    """Attribute-call forwarding is sanctioned ONLY into the boundary
    entry points (merge_partial_wires / _robust_table_merge /
    _stale_fold): `jnp.average(stale_tables, weights=stale_weights)` is a
    smuggled weighted fold wearing a call's clothes — not an order
    statistic (G012 can't see it) and not forwarding — and must fire."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/federated/engine.py\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def sneaky(table, stale_tables, stale_weights):\n"
        "    return table + jnp.average(stale_tables, axis=0,\n"
        "                               weights=stale_weights)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G013" in _codes(path)
    finally:
        os.unlink(path)


def test_g014_second_declared_boundary_fires():
    """THE ledger-commit boundary is one function in federated/api.py: a
    second declaration is a second write path hiding under the first's
    exemption, and must itself be a violation."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/federated/api.py\n"
        "\n"
        "\n"
        "# graftlint: ledger-commit\n"
        "def first(session, rnd, m):\n"
        "    session.ledger.append_round(rnd, metrics=m)\n"
        "\n"
        "\n"
        "# graftlint: ledger-commit\n"
        "def second(session, rnd, m):\n"
        "    session.ledger.append_round(rnd, metrics=m)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        found = _codes(path)
        assert found.count("G014") == 1, found  # the SECOND def, only
    finally:
        os.unlink(path)


def test_g014_runner_scope_and_construction_legal():
    """runner/ is in G014's scope (an exit path 'flushing' uncommitted
    rounds is the bug class), and constructing the writer stays legal —
    building a RoundLedger is wiring, appending is the policed verb."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/runner/loop.py\n"
        "from commefficient_tpu.obs.ledger import RoundLedger\n"
        "\n"
        "\n"
        "def run_loop(session, pending):\n"
        "    ledger = RoundLedger('/tmp/run.jsonl')  # wiring: legal\n"
        "    for rnd in pending:\n"
        "        ledger.append_round(rnd)  # uncommitted flush: illegal\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        found = _codes(path)
        assert found.count("G014") == 1, found  # the append, not the ctor
    finally:
        os.unlink(path)


def test_g016_ring_write_is_a_declaration_not_a_loophole():
    """The conforming twin's slot write is legal ONLY because its def
    carries `# graftlint: ring-write` — strip the directive and re-point
    the copy at a banned move, and the same module must fire (the
    boundary is a declaration, not a loophole)."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/serve/ring.py\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def write_slot(block, index, raw):\n"
        "    # undeclared per-submission copy in fast-path scope\n"
        "    block.tables[index][...] = np.frombuffer(raw, '<f4').copy()\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G016" in _codes(path)
    finally:
        os.unlink(path)


def test_g016_scope_is_fastpath_modules_only():
    """np.stack is the serve/ slow path's bread and butter — the rule must
    stay silent outside the declared fast-path modules (the assembler's
    stack copy is the thing the bench COMPARES against, not a bug)."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/serve/assembler.py\n"
        "import numpy as np\n"
        "\n"
        "\n"
        "def collect(tables):\n"
        "    return np.stack(tables, axis=0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G016" not in _codes(path)
    finally:
        os.unlink(path)


def test_g017_fires_direct_and_through_transitive_chain():
    """The violating fixture carries BOTH shapes: a direct module-level
    jax import in the worker-entry module, and one smuggled behind a
    same-directory helper import (the spawned worker executes both) —
    each must be its own finding."""
    found = _codes(os.path.join(FIXTURES, "g017_bad.py"))
    assert found.count("G017") >= 2, found


def test_g017_scope_is_worker_entry_modules_only():
    """A module-level jax import anywhere ELSE in the package is business
    as usual — the rule engages only on the declared worker-entry chain
    (service.py is the ROOT half; it imports jax by design)."""
    import tempfile

    src = (
        "# graftlint: module=commefficient_tpu/serve/service.py\n"
        "import jax.numpy as jnp\n"
        "\n"
        "\n"
        "def merge(stack):\n"
        "    return jnp.sum(stack, axis=0)\n"
    )
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as tmp:
        tmp.write(src)
        path = tmp.name
    try:
        assert "G017" not in _codes(path)
    finally:
        os.unlink(path)


def test_every_rule_has_fixture_pair():
    # adding a rule without fixtures should fail HERE, not in review
    for code in RULE_CODES:
        for suffix in ("bad", "ok"):
            assert os.path.exists(
                os.path.join(FIXTURES, f"{code.lower()}_{suffix}.py"))


def test_rule_codes_unique_and_well_formed():
    assert len(set(RULE_CODES)) == len(RULE_CODES)
    for rule in ALL_RULES:
        assert rule.code.startswith("G") and len(rule.code) == 4
        assert rule.name and rule.fixit


# ------------------------------------------------------------- the real repo


def test_repo_is_clean_under_shipped_baseline():
    """The acceptance gate: `python -m commefficient_tpu.analysis
    commefficient_tpu/ --json` exits 0 on the PR head."""
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis", PKG, "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    report = json.loads(out.stdout)
    assert out.returncode == 0, (
        f"graftlint found violations:\n"
        + "\n".join(f"{v['rel']}:{v['lineno']}: {v['code']} {v['message']}"
                    for v in report["violations"]))
    assert report["ok"] is True
    assert report["files_checked"] > 40


def test_shipped_baseline_has_no_parity_leaf_or_ckpt_entries():
    """G002/G003/G004 admit no grandfathering — the shipped baseline must
    end every PR empty of them."""
    baseline = Baseline.load(DEFAULT_BASELINE)
    banned = {e["code"] for e in baseline.entries} & {"G002", "G003", "G004"}
    assert not banned, f"baseline grandfathers banned codes: {banned}"


def test_clis_and_bench_are_clean():
    paths = [os.path.join(REPO, f)
             for f in ("cv_train.py", "gpt2_train.py", "bench.py")]
    result = Analyzer().run(paths)
    assert result.ok, [v.format() for v in result.violations]


# ------------------------------------------------------------- directives


def test_disable_must_name_valid_rule_code(tmp_path):
    bad = tmp_path / "bad_directive.py"
    bad.write_text(
        "import jax\n"
        "x = 1  # graftlint: disable=G999\n"
        "y = 2  # graftlint: disable=frobnicate\n"
    )
    codes = _codes(str(bad))
    assert codes.count("G000") == 2, codes


def test_bad_directive_is_not_suppressible(tmp_path):
    f = tmp_path / "self_suppress.py"
    # disabling G000 on the same line must not silence the directive error
    f.write_text("x = 1  # graftlint: disable=G000\n")
    assert "G000" in _codes(str(f))


def test_valid_disable_suppresses(tmp_path):
    f = tmp_path / "suppressed.py"
    f.write_text(
        "# graftlint: module=commefficient_tpu/modes/fake.py\n"
        "from jax import lax\n"
        "def merge(t, ax):\n"
        "    return lax.psum(t, ax)  # graftlint: disable=G002 — test\n"
    )
    result = Analyzer().run([str(f)])
    assert result.ok
    assert result.suppressed == 1


def test_drain_point_exempts_whole_function(tmp_path):
    f = tmp_path / "drained.py"
    f.write_text(
        "# graftlint: module=commefficient_tpu/federated/fake.py\n"
        "import jax\n"
        "# graftlint: drain-point — test boundary\n"
        "def commit(pending):\n"
        "    return jax.device_get(pending)\n"
    )
    assert "G001" not in _codes(str(f))


def test_unknown_directive_verb_is_reported(tmp_path):
    f = tmp_path / "verb.py"
    f.write_text("x = 1  # graftlint: frobnicate=G001\n")
    assert "G000" in _codes(str(f))


# ------------------------------------------------------------- baseline


def test_baseline_matches_by_line_text_not_lineno(tmp_path):
    src = tmp_path / "grandfathered.py"
    src.write_text(
        "# graftlint: module=commefficient_tpu/runner/fake.py\n"
        "def from_args(args):\n"
        "    return args.not_a_flag\n"
    )
    result = Analyzer().run([str(src)])
    (v,) = result.violations
    bl = Baseline([{"path": v.rel, "code": v.code,
                    "line": v.line_text.strip()}])
    # shifting the site down two lines must not invalidate the entry
    src.write_text(
        "# graftlint: module=commefficient_tpu/runner/fake.py\n"
        "\n\n"
        "def from_args(args):\n"
        "    return args.not_a_flag\n"
    )
    result = Analyzer(baseline=bl).run([str(src)])
    assert result.ok and len(result.baselined) == 1


def test_stale_baseline_entries_are_reported(tmp_path):
    src = tmp_path / "fixed.py"
    src.write_text("x = 1\n")
    bl = Baseline([{"path": "fixed.py", "code": "G008",
                    "line": "return args.gone"}])
    result = Analyzer(baseline=bl).run([str(src)])
    assert result.ok
    assert len(result.stale_baseline) == 1


def test_write_baseline_refuses_banned_codes(tmp_path):
    src = tmp_path / "mixed.py"
    src.write_text(
        "# graftlint: module=commefficient_tpu/modes/fake.py\n"
        "from jax import lax\n"
        "def merge(t, ax):\n"
        "    return lax.psum(t, ax)\n"
    )
    bl_path = tmp_path / "baseline.json"
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis", str(src),
         "--baseline", str(bl_path), "--write-baseline"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    written = json.loads(bl_path.read_text())
    assert written["entries"] == []  # G002 must be fixed, not grandfathered
    assert "refused" in out.stdout


# ------------------------------------------------------------- G008 plumbing


def test_registered_flags_extracted_from_config():
    flags = registered_flags()
    # a few load-bearing names from both task variants
    for name in ("checkpoint_every", "sync_loop", "max_inflight",
                 "fault_plan", "mesh", "model_parallel", "requeue_policy"):
        assert name in flags, name


def test_typoed_path_fails_loudly():
    # a gate that silently checks zero files is permanently green — a bad
    # path must exit 2, not 0
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis",
         "no_such_dir_xyz"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 2
    assert "no_such_dir_xyz" in out.stderr


def test_write_baseline_refuses_select():
    # a partial-rule rewrite would discard other rules' grandfathered
    # entries (the baseline file is rewritten whole)
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis", PKG,
         "--select", "G001", "--write-baseline"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 2
    assert "cannot be combined" in out.stderr


def test_report_json_flag_writes_archive(tmp_path):
    report = tmp_path / "report.json"
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis",
         os.path.join(FIXTURES, "g002_ok.py"), "--report-json", str(report)],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 0
    assert json.loads(report.read_text())["ok"] is True
    assert "graftlint:" in out.stdout  # human text still on stdout


def test_json_report_shape(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis",
         os.path.join(FIXTURES, "g002_bad.py"), "--json", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 1
    report = json.loads(out.stdout)
    assert report["counts"].get("G002") == 1
    (v,) = report["violations"]
    assert {"code", "rel", "lineno", "message", "fixit"} <= set(v)


# -- PR 20: concurrency rules (G018/G019/G020) + the G001 taint pass ---------


def test_g018_reports_both_directions_of_the_cycle():
    # fill_slot nests SLOT->RING lexically; flush_ring reaches RING->SLOT
    # through _grab_slot — BOTH edges of the inversion must be reported,
    # each at its own acquisition site
    vs = [v for v in Analyzer().run(
        [os.path.join(FIXTURES, "g018_bad.py")]).violations
        if v.code == "G018"]
    assert len(vs) == 2
    assert sorted(v.lineno for v in vs) == [17, 28]


def test_g018_edge_against_declared_order_fires(tmp_path):
    # no cycle at all — a SINGLE nesting that contradicts the declared
    # lock-order names is already a violation (the declaration is the
    # contract, not merely a cycle-breaking hint)
    f = tmp_path / "order_bad.py"
    f.write_text(
        "# graftlint: module=commefficient_tpu/serve/scale/order_demo.py\n"
        "import threading\n"
        "# graftlint: lock-order l1-ring\n"
        "_RING = threading.Lock()\n"
        "# graftlint: lock-order l0-slot\n"
        "_SLOT = threading.Lock()\n"
        "def go():\n"
        "    with _RING:\n"
        "        with _SLOT:\n"
        "            return 1\n")
    vs = [v for v in Analyzer().run([str(f)]).violations if v.code == "G018"]
    assert len(vs) == 1
    assert "declared lock order" in vs[0].message


def test_g018_declared_order_sanctions_the_nesting(tmp_path):
    f = tmp_path / "order_ok.py"
    f.write_text(
        "# graftlint: module=commefficient_tpu/serve/scale/order_demo2.py\n"
        "import threading\n"
        "# graftlint: lock-order l0-slot\n"
        "_SLOT = threading.Lock()\n"
        "# graftlint: lock-order l1-ring\n"
        "_RING = threading.Lock()\n"
        "def go():\n"
        "    with _SLOT:\n"
        "        with _RING:\n"
        "            return 1\n")
    assert "G018" not in _codes(str(f))


def test_g019_lockfree_directive_is_load_bearing(tmp_path):
    # strip the lockfree declaration from the conforming twin and the
    # tick counter becomes a finding — the directive is what sanctions it
    src = open(os.path.join(FIXTURES, "g019_ok.py"),
               encoding="utf-8").read()
    stripped = "\n".join(
        ln for ln in src.splitlines()
        if "lockfree" not in ln and "coarse progress" not in ln) + "\n"
    f = tmp_path / "g019_stripped.py"
    f.write_text(stripped)
    assert "G019" in _codes(str(f))
    assert "G019" not in _codes(os.path.join(FIXTURES, "g019_ok.py"))


def test_g019_lock_held_through_private_helper_counts(tmp_path):
    # must-hold: a private helper mutating shared state is safe when EVERY
    # call site holds the lock...
    common = (
        "# graftlint: module=commefficient_tpu/serve/scale/helper_demo{n}.py\n"
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "        self._t = None\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _bump(self):\n"
        "        self._n += 1\n"
        "    def submit(self):\n"
        "        {caller}\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            self._bump()\n")
    ok = tmp_path / "helper_ok.py"
    ok.write_text(common.format(
        n=1, caller="with self._lock:\n            self._bump()"))
    assert "G019" not in _codes(str(ok))
    # ...and a finding when even one call site is bare
    bad = tmp_path / "helper_bad.py"
    bad.write_text(common.format(n=2, caller="self._bump()"))
    assert "G019" in _codes(str(bad))


def test_g020_jsonl_sink_call_fires(tmp_path):
    # the tracer's buffered emits take the ring lock internally — calling
    # them from signal context is the exact deadlock PR 7 carved
    # instant_signal_safe out to avoid
    f = tmp_path / "sink_bad.py"
    f.write_text(
        "import signal\n"
        "class _T:\n"
        "    def instant(self, *a, **k):\n"
        "        pass\n"
        "_TR = _T()\n"
        "def _h(signum, frame):\n"
        "    _TR.instant('term')\n"
        "def install():\n"
        "    signal.signal(signal.SIGTERM, _h)\n")
    vs = [v for v in Analyzer().run([str(f)]).violations if v.code == "G020"]
    assert len(vs) == 1
    assert "instant_signal_safe" in vs[0].message


def test_g020_rlock_is_exempt(tmp_path):
    # RLock is reentrant: re-acquiring from a handler that interrupted the
    # holder cannot self-deadlock, so it is not flagged
    f = tmp_path / "rlock_ok.py"
    f.write_text(
        "import signal\n"
        "import threading\n"
        "_RL = threading.RLock()\n"
        "def _h(signum, frame):\n"
        "    with _RL:\n"
        "        return signum\n"
        "def install():\n"
        "    signal.signal(signal.SIGTERM, _h)\n")
    assert "G020" not in _codes(str(f))


def test_g001_taint_catches_what_the_syntactic_scan_misses():
    # the acceptance regression pair: the PRE-taint rule (taint_pass
    # disabled) provably misses the helper-hidden float(); the shipped
    # rule catches it at the compiled-scope call site
    from commefficient_tpu.analysis.rules_sync import HostSyncInRoundPath

    class SyntacticOnly(HostSyncInRoundPath):
        taint_pass = False

    bad = os.path.join(FIXTURES, "g001_taint_bad.py")
    rules_without = [SyntacticOnly if r is HostSyncInRoundPath else r
                     for r in ALL_RULES]
    pre = [v.code for v in Analyzer(rules=rules_without).run([bad]).violations]
    assert "G001" not in pre  # the miss the taint pass exists to close
    vs = [v for v in Analyzer().run([bad]).violations if v.code == "G001"]
    assert len(vs) == 1
    assert vs[0].lineno == 13
    assert "coerce_scale" in vs[0].message


def test_g001_taint_metadata_is_laundered():
    # .shape and module constants are host-safe even on traced values —
    # the ok twin routes both through the same helper and stays silent
    assert "G001" not in _codes(os.path.join(FIXTURES, "g001_taint_ok.py"))


def test_lock_order_directive_needs_a_name(tmp_path):
    f = tmp_path / "noname.py"
    f.write_text("# graftlint: lock-order\nx = 1\n")
    assert "G000" in _codes(str(f))


def test_lockfree_directive_needs_a_justification(tmp_path):
    f = tmp_path / "nowhy.py"
    f.write_text("# graftlint: lockfree\nx = 1\n")
    assert "G000" in _codes(str(f))


def test_parallel_run_is_byte_deterministic():
    # jobs>1 fans files across processes; baseline matching and the final
    # sort happen in the parent, so the result must match serial exactly
    paths = [os.path.join(FIXTURES, n) for n in
             ("g018_bad.py", "g019_bad.py", "g020_bad.py",
              "g001_taint_bad.py", "g002_bad.py", "g007_import_bad.py")]
    serial = Analyzer().run(paths, jobs=1)
    par = Analyzer().run(paths, jobs=2)
    assert par.violations == serial.violations
    assert par.suppressed == serial.suppressed
    assert par.files_checked == serial.files_checked


def _git(repo, *args):
    subprocess.run(["git", *args], cwd=repo, check=True,
                   capture_output=True, text=True)


def _tmp_git_repo(tmp_path):
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "t@t")
    _git(tmp_path, "config", "user.name", "t")
    return tmp_path


def test_changed_only_rejects_explicit_paths():
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis",
         "--changed-only", "commefficient_tpu"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 2
    assert "one or the other" in out.stderr


def test_changed_only_lints_exactly_the_staged_files(tmp_path):
    repo = _tmp_git_repo(tmp_path)
    env = dict(os.environ, PYTHONPATH=REPO)
    (repo / "commefficient_tpu").mkdir()
    demo = repo / "commefficient_tpu" / "tmp_demo.py"
    demo.write_text("x = 1\n")
    (repo / "unrelated.txt").write_text("hi\n")

    # nothing lintable staged -> clean exit, nothing analyzed
    _git(repo, "add", "unrelated.txt")
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis",
         "--changed-only"],
        capture_output=True, text=True, cwd=repo, env=env, timeout=60,
    )
    assert out.returncode == 0
    assert "nothing staged to lint" in out.stdout

    # a staged package file IS analyzed (and only it)
    _git(repo, "add", "commefficient_tpu/tmp_demo.py")
    out = subprocess.run(
        [sys.executable, "-m", "commefficient_tpu.analysis",
         "--changed-only"],
        capture_output=True, text=True, cwd=repo, env=env, timeout=60,
    )
    assert out.returncode == 0
    assert "1 file(s) checked" in out.stdout


def test_install_hooks_writes_changed_only_hook(tmp_path):
    repo = _tmp_git_repo(tmp_path)
    out = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "install_hooks.sh")],
        capture_output=True, text=True, cwd=repo, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    hook = repo / ".git" / "hooks" / "pre-commit"
    assert hook.is_file()
    assert os.access(hook, os.X_OK)
    assert "--changed-only" in hook.read_text()
    # idempotent re-run over our own hook
    out = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "install_hooks.sh")],
        capture_output=True, text=True, cwd=repo, timeout=60,
    )
    assert out.returncode == 0

    # but a FOREIGN pre-commit hook is refused without FORCE=1
    hook.write_text("#!/bin/sh\necho custom\n")
    out = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "install_hooks.sh")],
        capture_output=True, text=True, cwd=repo, timeout=60,
    )
    assert out.returncode != 0
    assert "FORCE=1" in out.stderr
